"""Rule-language parser: rule line -> tuple of Ops.

Syntax (hashcat/John compatible subset — the widely-published standard):
an operation is one character, immediately followed by its parameters.
Positional parameters are base-36 digits ('0'-'9' = 0-9, 'A'-'Z' =
10-35); character parameters are literal bytes (including space).
Whitespace *between* operations is ignored; lines starting with '#' and
blank lines are comments.

Each parsed op is (opcode, p1, p2) with unused params = 0, a layout that
serializes directly into the int32 bytecode table the device metadata
uses and that both interpreters share.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Iterable, Sequence


class Opcode(enum.IntEnum):
    NOOP = 0
    LOWER = 1          # l
    UPPER = 2          # u
    CAPITALIZE = 3     # c   (first upper, rest lower)
    INV_CAPITALIZE = 4  # C  (first lower, rest upper)
    TOGGLE_ALL = 5     # t
    TOGGLE_AT = 6      # TN
    REVERSE = 7        # r
    DUPLICATE = 8      # d
    DUPLICATE_N = 9    # pN
    REFLECT = 10       # f
    ROT_LEFT = 11      # {
    ROT_RIGHT = 12     # }
    DEL_FIRST = 13     # [
    DEL_LAST = 14      # ]
    DEL_AT = 15        # DN
    EXTRACT = 16       # xNM   keep [N, N+M)
    OMIT = 17          # ONM   delete [N, N+M)
    INSERT = 18        # iNX
    OVERWRITE = 19     # oNX
    TRUNCATE = 20      # 'N
    SUBSTITUTE = 21    # sXY
    PURGE = 22         # @X
    DUP_FIRST = 23     # zN    prepend first char N times
    DUP_LAST = 24      # ZN    append last char N times
    DUP_ALL = 25       # q     duplicate every char
    SWAP_FRONT = 26    # k
    SWAP_BACK = 27     # K
    SWAP_AT = 28       # *NM
    SHIFT_LEFT = 29    # LN    char at N <<= 1
    SHIFT_RIGHT = 30   # RN    char at N >>= 1
    INCR_AT = 31       # +N
    DECR_AT = 32       # -N
    REPL_NEXT = 33     # .N    char at N = char at N+1
    REPL_PREV = 34     # ,N    char at N = char at N-1
    DUP_BLOCK_FRONT = 35   # yN  prepend first N chars
    DUP_BLOCK_BACK = 36    # YN  append last N chars
    APPEND = 37        # $X
    PREPEND = 38       # ^X
    TITLE = 39         # E     lowercase, then upper after space/start
    TITLE_SEP = 40     # eX    same with separator X
    # rejection rules: mark the candidate invalid rather than edit it
    REJ_GT = 41        # <N    reject if len > N
    REJ_LT = 42        # >N    reject if len < N
    REJ_NEQ_LEN = 43   # _N    reject if len != N
    REJ_CONTAIN = 44   # !X    reject if word contains X
    REJ_NOT_CONTAIN = 45   # /X  reject unless word contains X
    REJ_NOT_FIRST = 46     # (X  reject unless first char is X
    REJ_NOT_LAST = 47      # )X  reject unless last char is X
    REJ_NOT_AT = 48        # =NX reject unless char at N is X
    REJ_LT_COUNT = 49      # %NX reject unless >= N instances of X


@dataclasses.dataclass(frozen=True)
class OpSpec:
    char: str
    opcode: Opcode
    #: parameter kinds, in order: 'p' = base-36 position, 'c' = literal char
    params: str


_SPECS = [
    OpSpec(":", Opcode.NOOP, ""),
    OpSpec("l", Opcode.LOWER, ""),
    OpSpec("u", Opcode.UPPER, ""),
    OpSpec("c", Opcode.CAPITALIZE, ""),
    OpSpec("C", Opcode.INV_CAPITALIZE, ""),
    OpSpec("t", Opcode.TOGGLE_ALL, ""),
    OpSpec("T", Opcode.TOGGLE_AT, "p"),
    OpSpec("r", Opcode.REVERSE, ""),
    OpSpec("d", Opcode.DUPLICATE, ""),
    OpSpec("p", Opcode.DUPLICATE_N, "p"),
    OpSpec("f", Opcode.REFLECT, ""),
    OpSpec("{", Opcode.ROT_LEFT, ""),
    OpSpec("}", Opcode.ROT_RIGHT, ""),
    OpSpec("[", Opcode.DEL_FIRST, ""),
    OpSpec("]", Opcode.DEL_LAST, ""),
    OpSpec("D", Opcode.DEL_AT, "p"),
    OpSpec("x", Opcode.EXTRACT, "pp"),
    OpSpec("O", Opcode.OMIT, "pp"),
    OpSpec("i", Opcode.INSERT, "pc"),
    OpSpec("o", Opcode.OVERWRITE, "pc"),
    OpSpec("'", Opcode.TRUNCATE, "p"),
    OpSpec("s", Opcode.SUBSTITUTE, "cc"),
    OpSpec("@", Opcode.PURGE, "c"),
    OpSpec("z", Opcode.DUP_FIRST, "p"),
    OpSpec("Z", Opcode.DUP_LAST, "p"),
    OpSpec("q", Opcode.DUP_ALL, ""),
    OpSpec("k", Opcode.SWAP_FRONT, ""),
    OpSpec("K", Opcode.SWAP_BACK, ""),
    OpSpec("*", Opcode.SWAP_AT, "pp"),
    OpSpec("L", Opcode.SHIFT_LEFT, "p"),
    OpSpec("R", Opcode.SHIFT_RIGHT, "p"),
    OpSpec("+", Opcode.INCR_AT, "p"),
    OpSpec("-", Opcode.DECR_AT, "p"),
    OpSpec(".", Opcode.REPL_NEXT, "p"),
    OpSpec(",", Opcode.REPL_PREV, "p"),
    OpSpec("y", Opcode.DUP_BLOCK_FRONT, "p"),
    OpSpec("Y", Opcode.DUP_BLOCK_BACK, "p"),
    OpSpec("$", Opcode.APPEND, "c"),
    OpSpec("^", Opcode.PREPEND, "c"),
    OpSpec("E", Opcode.TITLE, ""),
    OpSpec("e", Opcode.TITLE_SEP, "c"),
    OpSpec("<", Opcode.REJ_GT, "p"),
    OpSpec(">", Opcode.REJ_LT, "p"),
    OpSpec("_", Opcode.REJ_NEQ_LEN, "p"),
    OpSpec("!", Opcode.REJ_CONTAIN, "c"),
    OpSpec("/", Opcode.REJ_NOT_CONTAIN, "c"),
    OpSpec("(", Opcode.REJ_NOT_FIRST, "c"),
    OpSpec(")", Opcode.REJ_NOT_LAST, "c"),
    OpSpec("=", Opcode.REJ_NOT_AT, "pc"),
    OpSpec("%", Opcode.REJ_LT_COUNT, "pc"),
]

OPS: dict[str, OpSpec] = {s.char: s for s in _SPECS}


@dataclasses.dataclass(frozen=True)
class Op:
    opcode: Opcode
    p1: int = 0
    p2: int = 0

    def __repr__(self) -> str:  # pragma: no cover
        return f"Op({self.opcode.name}, {self.p1}, {self.p2})"


def _position(ch: str, rule: str) -> int:
    """Base-36 position digit: 0-9, A-Z = 10-35."""
    if "0" <= ch <= "9":
        return ord(ch) - ord("0")
    if "A" <= ch <= "Z":
        return ord(ch) - ord("A") + 10
    raise ValueError(f"bad position char {ch!r} in rule {rule!r}")


def parse_rule(rule: str) -> tuple[Op, ...]:
    """One rule line -> ops.  Raises ValueError on malformed syntax."""
    ops: list[Op] = []
    i, n = 0, len(rule)
    while i < n:
        ch = rule[i]
        if ch in (" ", "\t"):
            i += 1
            continue
        spec = OPS.get(ch)
        if spec is None:
            raise ValueError(f"unknown rule operation {ch!r} in {rule!r}")
        i += 1
        params = [0, 0]
        for slot, kind in enumerate(spec.params):
            if i >= n:
                raise ValueError(
                    f"rule {rule!r}: op {ch!r} missing parameter {slot + 1}")
            pch = rule[i]
            i += 1
            params[slot] = (_position(pch, rule) if kind == "p"
                            else ord(pch.encode("latin-1")))
        ops.append(Op(spec.opcode, params[0], params[1]))
    if not ops:
        raise ValueError("empty rule")
    return tuple(ops)


def parse_rules(lines: Iterable[str],
                on_error: str = "raise") -> list[tuple[Op, ...]]:
    """Many rule lines -> list of op tuples.

    on_error: 'raise' or 'skip' (skip silently drops bad lines, the
    lenient mode used for user-supplied files full of exotic ops).
    """
    out: list[tuple[Op, ...]] = []
    for line in lines:
        line = line.rstrip("\n").rstrip("\r")
        if not line.strip() or line.lstrip().startswith("#"):
            continue
        try:
            out.append(parse_rule(line))
        except ValueError:
            if on_error == "raise":
                raise
    if not out:
        raise ValueError("rule set contains no usable rules")
    return out


_RULES_DIR = os.path.join(os.path.dirname(__file__), "data")

BUILTIN_RULESETS = ("best64", "dprf64", "leetspeak", "toggle")


def builtin_ruleset(name: str) -> str:
    path = os.path.join(_RULES_DIR, name + ".rule")
    if not os.path.exists(path):
        raise KeyError(f"no builtin ruleset {name!r}; "
                       f"have {', '.join(BUILTIN_RULESETS)}")
    return path


def resolve_rules_path(name_or_path: str) -> str:
    """Builtin set name or file path -> the file that will be loaded.
    The single source of truth for resolution: job fingerprints hash
    exactly the file `load_rules` parses."""
    if os.path.exists(name_or_path):
        return name_or_path
    try:
        return builtin_ruleset(name_or_path)
    except KeyError:
        raise FileNotFoundError(
            f"rule set {name_or_path!r}: not a file and not a builtin "
            f"({', '.join(BUILTIN_RULESETS)})")


def load_rules(name_or_path: str,
               on_error: str = "raise") -> list[tuple[Op, ...]]:
    """Load rules from a builtin set name or a file path."""
    with open(resolve_rules_path(name_or_path), "r",
              encoding="latin-1") as fh:
        return parse_rules(fh, on_error=on_error)
