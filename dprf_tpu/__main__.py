import sys

from dprf_tpu.cli import main

sys.exit(main())
