// Native wordlist loader: mmap + two-pass scan/pack.
//
// The reference class of framework keeps its data plane native; here
// the host-side bottleneck is turning a multi-GB wordlist file into
// the fixed-width uint8[N, L] + int32[N] tables the device consumes
// (dprf_tpu/generators/wordlist.py).  The Python loop costs ~1 us/word;
// this does the same at memory bandwidth with memchr.
//
// Contract (mirrors generators/wordlist.load_words):
//   - words are lines stripped of trailing \r\n; empty lines dropped;
//   - lines longer than max_len are skipped and counted;
//   - pass 1 (scan) sizes the output, pass 2 (pack) fills
//     caller-allocated numpy buffers, so ownership stays in Python.
//
// Build: cc -O3 -shared -fPIC wordlist.cpp -o libdprf_native.so
// (driven by dprf_tpu/native/__init__.py; ctypes bindings, no pybind).

#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapped {
    const char* data = nullptr;
    size_t size = 0;
    int fd = -1;
    bool ok() const { return fd >= 0 && (size == 0 || data != nullptr); }
};

Mapped map_file(const char* path) {
    Mapped m;
    m.fd = ::open(path, O_RDONLY);
    if (m.fd < 0) return m;
    struct stat st;
    if (::fstat(m.fd, &st) != 0) { ::close(m.fd); m.fd = -1; return m; }
    m.size = static_cast<size_t>(st.st_size);
    if (m.size == 0) return m;
    void* p = ::mmap(nullptr, m.size, PROT_READ, MAP_PRIVATE, m.fd, 0);
    if (p == MAP_FAILED) { ::close(m.fd); m.fd = -1; return m; }
    m.data = static_cast<const char*>(p);
    ::madvise(p, m.size, MADV_SEQUENTIAL);
    return m;
}

void unmap(Mapped& m) {
    if (m.data) ::munmap(const_cast<char*>(m.data), m.size);
    if (m.fd >= 0) ::close(m.fd);
}

inline size_t line_len(const char* start, const char* nl) {
    size_t len = static_cast<size_t>(nl - start);
    while (len > 0 && (start[len - 1] == '\r' || start[len - 1] == '\n'))
        --len;
    return len;
}

}  // namespace

extern "C" {

// Pass 1: count usable words.  Returns 0 on success, -1 on I/O error.
// Outputs: n_words, n_skipped (too long), max_seen (longest kept word).
int dprf_wordlist_scan(const char* path, int32_t max_len,
                       int64_t* n_words, int64_t* n_skipped,
                       int32_t* max_seen) {
    Mapped m = map_file(path);
    if (!m.ok()) return -1;
    int64_t words = 0, skipped = 0;
    int32_t longest = 0;
    const char* p = m.data;
    const char* end = m.data + m.size;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            ::memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* stop = nl ? nl : end;
        size_t len = line_len(p, stop);
        if (len > 0) {
            if (len > static_cast<size_t>(max_len)) {
                ++skipped;
            } else {
                ++words;
                if (static_cast<int32_t>(len) > longest)
                    longest = static_cast<int32_t>(len);
            }
        }
        p = stop + 1;
    }
    *n_words = words;
    *n_skipped = skipped;
    *max_seen = longest;
    unmap(m);
    return 0;
}

// Pass 2: fill buf (row-major, `stride` bytes per row, zero-padded by
// the caller) and lengths.  Stops at capacity rows.  Returns the number
// of rows written, or -1 on I/O error.
int64_t dprf_wordlist_pack(const char* path, int32_t max_len,
                           uint8_t* buf, int64_t stride,
                           int32_t* lengths, int64_t capacity) {
    Mapped m = map_file(path);
    if (!m.ok()) return -1;
    int64_t row = 0;
    const char* p = m.data;
    const char* end = m.data + m.size;
    while (p < end && row < capacity) {
        const char* nl = static_cast<const char*>(
            ::memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* stop = nl ? nl : end;
        size_t len = line_len(p, stop);
        if (len > 0 && len <= static_cast<size_t>(max_len)) {
            ::memcpy(buf + row * stride, p, len);
            lengths[row] = static_cast<int32_t>(len);
            ++row;
        }
        p = stop + 1;
    }
    unmap(m);
    return row;
}

}  // extern "C"
