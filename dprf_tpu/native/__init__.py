"""Native (C++) host runtime components, bound via ctypes.

The device compute path is JAX/XLA/Pallas; the host data plane around
it is native where it matters.  First component: the wordlist
loader/packer (wordlist.cpp) that turns line files into the fixed-width
tables the device consumes at memory bandwidth instead of a Python
per-line loop.

The shared library is compiled on first use with the system compiler
and cached next to the sources (keyed on source mtime).  Everything
degrades gracefully: if no compiler is available the callers fall back
to the pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "wordlist.cpp")
_LIB = os.path.join(_DIR, "libdprf_native.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> Optional[str]:
    """(Re)build the shared library if stale; returns its path or None."""
    try:
        if (os.path.exists(_LIB)
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        for cc in ("c++", "g++", "cc", "gcc"):
            # build to a temp name then rename: concurrent importers
            # must never dlopen a half-written .so
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
            os.close(fd)
            try:
                res = subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                    capture_output=True, timeout=120)
                if res.returncode == 0:
                    os.replace(tmp, _LIB)
                    return _LIB
            except (OSError, subprocess.TimeoutExpired):
                continue
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
    except OSError:
        pass
    return None


def load() -> Optional[ctypes.CDLL]:
    """The bound library, or None if native support is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    from dprf_tpu.utils import env as envreg
    if not envreg.get_bool("DPRF_NATIVE"):
        return None
    path = _compile()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.dprf_wordlist_scan.restype = ctypes.c_int
    lib.dprf_wordlist_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32)]
    lib.dprf_wordlist_pack.restype = ctypes.c_int64
    lib.dprf_wordlist_pack.argtypes = [
        ctypes.c_char_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    _lib = lib
    return _lib


def load_words_packed(path: str, max_len: int):
    """Native loader: file -> (uint8[N, max_len] zero-padded rows,
    int32[N] lengths, n_skipped).  None if native is unavailable or the
    file can't be read natively (caller falls back to Python)."""
    lib = load()
    if lib is None:
        return None
    n_words = ctypes.c_int64()
    n_skipped = ctypes.c_int64()
    max_seen = ctypes.c_int32()
    enc = os.fsencode(path)
    if lib.dprf_wordlist_scan(enc, max_len, ctypes.byref(n_words),
                              ctypes.byref(n_skipped),
                              ctypes.byref(max_seen)) != 0:
        return None
    n = n_words.value
    buf = np.zeros((max(n, 1), max_len), dtype=np.uint8)
    lens = np.zeros((max(n, 1),), dtype=np.int32)
    if n:
        wrote = lib.dprf_wordlist_pack(
            enc, max_len,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            buf.strides[0],
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
        if wrote != n:   # file changed between passes: be safe
            return None
    return buf[:n], lens[:n], n_skipped.value
