"""Bench regression sentinel: compare a measurement against the
committed BENCH_r*.json trajectory.

The driver bench records (``BENCH_r<NN>.json`` at the repo root) wrap
one JSON result line in a ``tail`` field; this module parses them
back into result dicts and gates a current measurement against the
baseline WINDOW: the last K records measured on the SAME device
backend (a CPU-fallback run must never "regress" against a TPU
round), compared as

    regression  <=>  current < median * (1 - tolerance)

where the tolerance is the larger of a noise floor and the window's
own observed run-to-run relative spread -- a trajectory that jitters
10% between rounds must not alarm on an 8% dip, and a rock-steady
one should.  Fewer than MIN_BASELINE comparable records is verdict
``no-baseline`` (pass): the sentinel refuses to alarm on data it
does not have.

Peak device memory gates alongside throughput (ISSUE 13): a record's
``peak_hbm_bytes`` RISING past the baseline window's median by more
than the tolerance is a regression exactly like a throughput dip --
the HBM budget is a perf resource here (probe tables, superstep
buffers), and a silent 30% memory growth is tomorrow's OOM.  Records
measured before the introspection plane lack the field and the
memory sub-gate reports ``no-baseline`` for them, never a crash.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Optional

#: default baseline window (same-device records considered)
DEFAULT_WINDOW = 5
#: minimum tolerated regression even on a noise-free trajectory
NOISE_FLOOR = 0.10
#: same-device records needed before the gate may fail anything
MIN_BASELINE = 2

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

#: committed multichip scaling records (bare run_scaling result
#: JSON; value = efficiency fraction) -- gated exactly like the
#: throughput trajectory, via the same gate() math
SCALING_PATTERN = "SCALING_r*.json"

#: committed target-set-size sweep records (bare run_targets_sweep
#: result JSON; value = H/s at the LARGEST target count, so a probe
#: table that stops being O(1) per candidate dips the gated number)
TARGETS_PATTERN = "TARGETS_r*.json"

#: committed time-to-first-hit records (bare run_ttfh result JSON;
#: value = candidates-to-first-hit SPEEDUP of rank-ordered over
#: linear dispatch, so an ordering regression -- a broken bijection,
#: a scheduler that stops leasing low ranks first -- dips the gated
#: number exactly like a throughput loss)
TTFH_PATTERN = "TTFH_r*.json"


def _result_from_tail(tail: str) -> Optional[dict]:
    """The LAST JSON object line in a driver record's tail -- the
    bench's single stdout JSON line (stderr noise precedes it)."""
    best = None
    for line in tail.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and isinstance(
                doc.get("value"), (int, float)):
            best = doc
    return best


def load_bench_records(repo_dir: str,
                       pattern: str = "BENCH_r*.json") -> list:
    """Parsed bench results from the committed driver records, sorted
    by round number; each result dict gains ``round``."""
    out = []
    for path in glob.glob(os.path.join(repo_dir, pattern)):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        res = None
        if isinstance(doc, dict):
            if isinstance(doc.get("tail"), str):
                res = _result_from_tail(doc["tail"])
            elif isinstance(doc.get("value"), (int, float)):
                res = doc            # bare result file
        if res is None:
            continue
        res = dict(res)
        res["round"] = int(m.group(1))
        out.append(res)
    out.sort(key=lambda r: r["round"])
    return out


def latest_record(repo_dir: str) -> Optional[dict]:
    recs = load_bench_records(repo_dir)
    return recs[-1] if recs else None


def _comparable(current: dict, rec: dict) -> bool:
    """Baseline records must be measured on the same backend; the
    engine too when both records carry one."""
    if rec.get("device") != current.get("device"):
        return False
    ce, re_ = current.get("engine"), rec.get("engine")
    if ce is not None and re_ is not None and ce != re_:
        return False
    return True


def _window_stats(vals: list, noise_floor: float) -> tuple:
    """(median, tolerance) of a sorted baseline window: the tolerance
    is the larger of the noise floor and the window's own observed
    run-to-run relative spread."""
    n = len(vals)
    median = (vals[n // 2] if n % 2
              else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
    spread = (vals[-1] - vals[0]) / median if median > 0 else 0.0
    return median, max(float(noise_floor), spread)


def _memory_gate(current: dict, comp: list, window: int,
                 noise_floor: float) -> dict:
    """Peak-HBM sub-gate (ISSUE 13): a memory regression is the
    current ``peak_hbm_bytes`` rising ABOVE the baseline window's
    median by more than the tolerance -- the mirror image of the
    throughput rule.  Records measured before the introspection plane
    lack the field entirely and gate as ``no-baseline`` (pass): the
    sentinel refuses to alarm on data it does not have."""
    def _peak(rec) -> float:
        v = rec.get("peak_hbm_bytes")
        return float(v) if isinstance(v, (int, float)) and v > 0 \
            else 0.0

    value = _peak(current)
    base = [r for r in comp if _peak(r) > 0]
    base = base[-max(1, int(window)):]
    if len(base) < MIN_BASELINE or value <= 0:
        return {"verdict": "no-baseline", "median_bytes": None,
                "tolerance": None, "ratio": None,
                "window": len(base)}
    median, tolerance = _window_stats(
        sorted(_peak(r) for r in base), noise_floor)
    ratio = value / median if median > 0 else 0.0
    verdict = "regression" if ratio > 1.0 + tolerance else "pass"
    return {"verdict": verdict,
            "median_bytes": median,
            "tolerance": round(tolerance, 4),
            "ratio": round(ratio, 4),
            "window": len(base)}


def gate(current: dict, baseline: list, window: int = DEFAULT_WINDOW,
         noise_floor: float = NOISE_FLOOR) -> dict:
    """Gate verdict for ``current`` (a bench result dict with
    ``value`` and ``device``) against the ``baseline`` record list.

    Returns {"verdict": "pass"|"regression"|"no-baseline",
    "median_hs", "tolerance", "ratio", "window", "baseline_rounds",
    "memory"}.  The ``memory`` sub-verdict gates ``peak_hbm_bytes``
    the same way (regression = peak RISING past the window's band);
    either side regressing makes the overall verdict a regression.
    """
    value = float(current.get("value") or 0.0)
    comp = [r for r in baseline if _comparable(current, r)
            and float(r.get("value") or 0) > 0]
    memory = _memory_gate(current, comp, window, noise_floor)
    comp = comp[-max(1, int(window)):]
    if len(comp) < MIN_BASELINE or value <= 0:
        return {"verdict": ("regression"
                            if memory["verdict"] == "regression"
                            else "no-baseline"),
                "median_hs": None, "tolerance": None, "ratio": None,
                "window": len(comp),
                "baseline_rounds": [r["round"] for r in comp
                                    if "round" in r],
                "memory": memory}
    median, tolerance = _window_stats(
        sorted(float(r["value"]) for r in comp), noise_floor)
    ratio = value / median if median > 0 else 0.0
    verdict = "regression" if ratio < 1.0 - tolerance else "pass"
    if memory["verdict"] == "regression":
        verdict = "regression"
    return {"verdict": verdict,
            "median_hs": median,
            "tolerance": round(tolerance, 4),
            "ratio": round(ratio, 4),
            "window": len(comp),
            "baseline_rounds": [r["round"] for r in comp
                                if "round" in r],
            "memory": memory}


def gate_repo(current: dict, repo_dir: str,
              window: int = DEFAULT_WINDOW,
              pattern: str = "BENCH_r*.json") -> dict:
    return gate(current, load_bench_records(repo_dir, pattern=pattern),
                window=window)


def gate_dry(repo_dir: str, window: int = DEFAULT_WINDOW,
             pattern: str = "BENCH_r*.json") -> dict:
    """CI mode: gate the NEWEST committed record against the window
    before it -- no fresh measurement needed (the committed
    trajectory audits itself).  Adds ``current_round``/``current_hs``
    so the verdict is self-describing."""
    recs = load_bench_records(repo_dir, pattern=pattern)
    if not recs:
        return {"verdict": "no-baseline", "median_hs": None,
                "tolerance": None, "ratio": None, "window": 0,
                "baseline_rounds": [],
                "memory": {"verdict": "no-baseline",
                           "median_bytes": None, "tolerance": None,
                           "ratio": None, "window": 0}}
    current, prior = recs[-1], recs[:-1]
    out = gate(current, prior, window=window)
    out["current_round"] = current.get("round")
    out["current_hs"] = current.get("value")
    return out


def repo_root() -> str:
    """The tree this package is installed in (where BENCH_r*.json
    live) -- overridable by callers with an explicit dir."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
