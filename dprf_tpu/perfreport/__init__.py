"""Offline performance attribution (ISSUE 9): the bench regression
sentinel and the one-shot session report.

Two consumers of the artifacts the runtime already writes:

  - ``perfreport.compare`` -- ``dprf bench --gate`` /
    ``tools/bench_compare.py``: gate a fresh bench measurement against
    the committed BENCH_r*.json trajectory (median of the last K
    same-device records, noise tolerance from their observed
    run-to-run spread), exit non-zero on regression;
  - ``perfreport.report`` -- ``dprf report SESSION``: render a
    text performance report (throughput, per-phase p50/p95, device
    busy fraction, compile-cache hit rate, pipeline depth, per-job
    fair-share actual-vs-weight) ENTIRELY from session artifacts (the
    trace JSONL, telemetry snapshots, and the journal), so a
    post-mortem needs no live coordinator;
  - ``perfreport.audit`` -- ``dprf audit SESSION`` (ISSUE 19):
    rebuild the coverage story (fraction, gaps, digests, trace-replay
    overlaps, exactly-once hits) from artifacts alone and render a
    clean/incomplete/dirty verdict.
"""

from dprf_tpu.perfreport.audit import build_audit, render_audit
from dprf_tpu.perfreport.compare import (gate, latest_record,
                                         load_bench_records)
from dprf_tpu.perfreport.report import build_report, render_report

__all__ = ["gate", "latest_record", "load_bench_records",
           "build_report", "render_report", "build_audit",
           "render_audit"]
