"""``dprf audit SESSION``: offline coverage reconstruction from
session artifacts alone (ISSUE 19).

The live coverage ledger (telemetry/coverage.py) watches a run from
inside; this is the outside check -- given only the journal family a
session leaves behind, rebuild the coverage story and judge it:

  - the journal's ``units`` snapshots are the coverage AUTHORITY:
    merged intervals per job, rebuilt into an IntervalSet, measured
    against the job's declared keyspace (gaps, fraction), and
    re-digested -- the rebuilt digest must match the digest the
    snapshot itself carried, exactly as a resume must
    (``Dispatcher.from_completed``);
  - the trace stream's ``complete`` spans (which carry each unit's
    ``start``/``length`` since ISSUE 19) REPLAY coverage event by
    event: any index completed twice in the replay is a
    double-covered candidate the stale-lease guard should have
    stopped.  The trace file is bounded (rotation), so a missing span
    is never evidence of a problem -- only a positive overlap is;
  - the journal's ``hit`` records prove each cracked target was found
    exactly once (the coordinator dedupes before journaling, so a
    duplicate here means the exactly-once invariant broke upstream).

Verdict: ``dirty`` on any positive evidence (digest mismatch, replay
overlap, duplicate hits), else ``incomplete`` when a job's covered
fraction is below 1.0 (nothing wrong -- the run just stopped early or
cracked out), else ``clean``.  The chaos harness
(dprf_tpu/testing/chaos.py) gates on ``clean``.
"""

from __future__ import annotations

import os
from typing import Optional

from dprf_tpu.telemetry.coverage import (IntervalSet, coverage_digest,
                                         max_gaps)
from dprf_tpu.telemetry.trace import load_trace, trace_path


def _replay_trace(spans: list) -> dict:
    """job id -> {covered: IntervalSet, completes, overlap} replayed
    from the ``complete`` spans' ranges, in span order.

    ``restore`` spans (``Dispatcher.from_completed``) mark a
    coordinator-restart GENERATION boundary: the first restore after
    any complete resets the job's covered set, and the restore batch
    seeds it with what the journal had actually snapshotted.  A
    crash-restart legitimately re-sweeps ranges completed after the
    last snapshot -- only re-coverage WITHIN a generation (or of a
    range the restore itself seeded) is double coverage.  The
    ``overlap`` count is cumulative across generations."""
    replay: dict = {}
    in_restore: dict = {}    # job id -> currently inside restore batch

    def _job(jid: str) -> dict:
        return replay.setdefault(jid, {"covered": IntervalSet(),
                                       "completes": 0, "overlap": 0})

    for s in spans:
        name = s.get("name")
        if name not in ("complete", "restore"):
            continue
        a = s.get("attrs") or {}
        try:
            start = int(a["start"])
            length = int(a["length"])
        except (KeyError, TypeError, ValueError):
            continue   # pre-ISSUE-19 span without a range: no evidence
        jid = str(a.get("job", "j0"))
        r = _job(jid)
        if name == "restore":
            if not in_restore.get(jid):
                in_restore[jid] = True
                r["covered"] = IntervalSet()
            r["covered"].add(start, start + length)
            continue
        in_restore[jid] = False
        r["completes"] += 1
        r["overlap"] += length - r["covered"].add(start, start + length)
    return replay


def _dupe_hits(hits: list) -> int:
    """Hit records whose (target, candidate index) already appeared --
    each is one hit found MORE than once."""
    seen: set = set()
    dupes = 0
    for h in hits:
        key = (h.get("target"), h.get("index"))
        if key in seen:
            dupes += 1
        else:
            seen.add(key)
    return dupes


def _audit_job(jid: str, keyspace: Optional[int], intervals: list,
               digest_journal: Optional[str], hits: list,
               replay: Optional[dict]) -> dict:
    iv = IntervalSet(intervals)
    covered = iv.covered()
    doc: dict = {
        "job": jid,
        "keyspace": keyspace,
        "covered": covered,
        "fraction": (round(covered / keyspace, 6)
                     if keyspace else None),
        "gaps": (iv.gaps(keyspace)[:max_gaps()] if keyspace else []),
        "gap_total": (keyspace - covered if keyspace else None),
        "digest_journal": digest_journal,
        # re-digest the journaled intervals: must reproduce the digest
        # the snapshot carried (the live ledger's digest at write time)
        "digest_rebuilt": (coverage_digest(keyspace, intervals)
                           if keyspace else None),
        "hits": len(hits),
        "hit_dupes": _dupe_hits(hits),
        "trace_completes": 0,
        "trace_overlap": 0,
        "trace_covered": 0,
    }
    doc["digest_match"] = (
        None if not digest_journal or not doc["digest_rebuilt"]
        else digest_journal == doc["digest_rebuilt"])
    if replay is not None:
        doc["trace_completes"] = replay["completes"]
        doc["trace_overlap"] = replay["overlap"]
        doc["trace_covered"] = replay["covered"].covered()
    return doc


def _job_problems(j: dict) -> list:
    out = []
    if j["digest_match"] is False:
        out.append(
            f"job {j['job']}: journaled coverage digest "
            f"{j['digest_journal']} does not match the rebuild "
            f"{j['digest_rebuilt']} (torn or edited journal)")
    if j["trace_overlap"]:
        out.append(
            f"job {j['job']}: trace replay double-covered "
            f"{j['trace_overlap']} candidate(s) across "
            f"{j['trace_completes']} completions (stale lease past "
            "the guard, or a planted double-lease)")
    if j["hit_dupes"]:
        out.append(
            f"job {j['job']}: {j['hit_dupes']} hit record(s) "
            "duplicate an earlier (target, index) -- hits must be "
            "found exactly once")
    return out


def build_audit(session_path: str) -> Optional[dict]:
    """The machine-readable audit, or None when the session left no
    artifacts at all."""
    from dprf_tpu.runtime.session import SessionJournal
    journal = (SessionJournal.load(session_path)
               if os.path.exists(session_path) else None)
    spans = load_trace(trace_path(session_path))
    if journal is None and not spans:
        return None
    replay = _replay_trace(spans)
    jobs: list = []
    if journal is not None:
        default_jid = journal.default_job
        ks = journal.spec.get("keyspace") if journal.spec else None
        ks = int(ks) if ks else None
        jobs.append(_audit_job(
            default_jid, ks, journal.completed,
            journal.coverage.get(default_jid), journal.hits,
            replay.pop(default_jid, None)))
        for jid in sorted(journal.jobs):
            rec = journal.jobs[jid]
            spec = rec.get("spec") or {}
            jks = spec.get("keyspace")
            jobs.append(_audit_job(
                jid, int(jks) if jks else None,
                rec.get("completed") or [],
                rec.get("coverage_digest"), rec.get("hits") or [],
                replay.pop(jid, None)))
    # complete spans for jobs the journal never snapshotted still
    # carry overlap evidence (e.g. a journal lost to the fault being
    # audited)
    for jid in sorted(replay):
        jobs.append(_audit_job(jid, None, [], None, [], replay[jid]))
    problems: list = []
    for j in jobs:
        problems.extend(_job_problems(j))
    if problems:
        verdict = "dirty"
    elif any(j["fraction"] is not None and j["fraction"] < 1.0
             for j in jobs):
        verdict = "incomplete"
    else:
        verdict = "clean"
    return {
        "session": session_path,
        "jobs": jobs,
        "spans": len(spans),
        "problems": problems,
        "verdict": verdict,
    }


def render_audit(doc: dict) -> str:
    """The human half: a sectioned text audit (stdout of ``dprf
    audit``; the CI audit tier uploads it as an artifact)."""
    lines = [f"dprf audit — {doc['session']}",
             f"{len(doc['jobs'])} job(s) | {doc['spans']} trace "
             f"spans | verdict {doc['verdict'].upper()}"]
    for j in doc["jobs"]:
        lines.append("")
        lines.append(f"job {j['job']}")
        if j["keyspace"]:
            frac = j["fraction"]
            lines.append(f"  keyspace   {j['keyspace']:,}")
            lines.append(f"  covered    {j['covered']:,}"
                         + (f"  ({100 * frac:.2f}%)"
                            if frac is not None else ""))
            gap = j["gap_total"] or 0
            if gap:
                shown = ", ".join(f"[{s},{e})" for s, e in j["gaps"])
                lines.append(f"  GAPS       {gap:,} candidate(s): "
                             f"{shown}")
        else:
            lines.append(f"  covered    {j['covered']:,} "
                         "(keyspace not journaled)")
        if j["digest_journal"]:
            mark = {True: "match", False: "MISMATCH",
                    None: "n/a"}[j["digest_match"]]
            lines.append(f"  digest     journal {j['digest_journal']} "
                         f"| rebuilt {j['digest_rebuilt']} "
                         f"[{mark}]")
        if j["trace_completes"]:
            lines.append(
                f"  trace      {j['trace_completes']} completion "
                f"span(s), {j['trace_covered']:,} candidates, "
                f"{j['trace_overlap']} double-covered")
        lines.append(f"  hits       {j['hits']}"
                     + (f"  ({j['hit_dupes']} DUPLICATE)"
                        if j["hit_dupes"] else ""))
    if doc["problems"]:
        lines.append("")
        lines.append("problems")
        for p in doc["problems"]:
            lines.append(f"  - {p}")
    return "\n".join(lines)
