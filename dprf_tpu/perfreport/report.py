"""``dprf report SESSION``: one-shot performance report from session
artifacts alone.

Reads the journal family a run leaves behind -- ``<session>`` (job
identity + per-job records), ``<session>.trace.jsonl`` (lifecycle +
phase spans), ``<session>.telemetry.jsonl`` (periodic registry
snapshots) -- and renders what a perf post-mortem needs without a
live coordinator: throughput, per-phase p50/p95 breakdown, device
busy fraction per worker, compile-cache behavior, pipeline depth,
and per-job fair-share actual-vs-weight.
"""

from __future__ import annotations

import os
from typing import Optional

from dprf_tpu.telemetry.perf import PHASES, roofline_fraction
from dprf_tpu.telemetry.snapshot import load_snapshots, telemetry_path
from dprf_tpu.telemetry.trace import load_trace, trace_path


def _pct(vals: list, q: float) -> float:
    """Nearest-rank percentile of a sorted list."""
    if not vals:
        return 0.0
    i = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return vals[i]


def _metric_values(snapshot: Optional[dict], name: str) -> list:
    if not snapshot:
        return []
    m = (snapshot.get("metrics") or {}).get(name)
    if not isinstance(m, dict):
        return []
    return m.get("values") or []


def _counter_total(snapshot, name: str, **labels) -> float:
    total = 0.0
    for v in _metric_values(snapshot, name):
        lv = v.get("labels") or {}
        if all(lv.get(k) == val for k, val in labels.items()):
            total += float(v.get("value") or 0.0)
    return total


def _phase_stats(spans: list, sample_scale: float = 1.0) -> dict:
    """phase -> {count, p50_s, p95_s, total_s, share, per_cand_ns}.
    The generate/h2d/device/d2h durations come from SAMPLED probes
    (every Nth unit) while ``verify`` comes from every hit batch's
    hit_verify span, so the share denominator scales the sampled
    totals by the observed cadence (units / probed units) -- without
    it, verify's share would inflate by the sampling factor.
    ``total_s``/p50/p95/count stay the observed values.

    ``per_cand_ns`` divides each phase's observed time by the
    candidates its probed units actually hashed (the ``cands`` attr
    the probe records since ISSUE 19).  A Pallas superstep unit runs
    many inner batches per probe while the baseline probes one batch,
    so raw per-unit totals are incomparable across ``--impl``; the
    per-candidate cost is the number that lines up."""
    by_phase: dict = {}
    cands_by_phase: dict = {}
    for s in spans:
        if s.get("name") != "phase":
            continue
        a = s.get("attrs") or {}
        ph = a.get("phase")
        if ph:
            by_phase.setdefault(str(ph), []).append(
                float(s.get("dur", 0.0)))
            try:
                cands_by_phase[str(ph)] = (
                    cands_by_phase.get(str(ph), 0)
                    + int(a.get("cands") or 0))
            except (TypeError, ValueError):
                pass
    # hit_verify spans carry the verify cost for EVERY hit batch
    for s in spans:
        if s.get("name") == "hit_verify":
            by_phase.setdefault("verify", []).append(
                float(s.get("dur", 0.0)))
    scale = max(1.0, float(sample_scale))

    def scaled(ph: str) -> float:
        t = sum(by_phase.get(ph, ()))
        return t if ph == "verify" else t * scale

    total_all = sum(scaled(ph) for ph in by_phase) or 1.0
    out = {}
    for ph in PHASES:
        durs = sorted(by_phase.get(ph, ()))
        if not durs:
            continue
        cands = cands_by_phase.get(ph, 0)
        out[ph] = {"count": len(durs),
                   "p50_s": round(_pct(durs, 0.50), 6),
                   "p95_s": round(_pct(durs, 0.95), 6),
                   "total_s": round(sum(durs), 6),
                   "share": round(scaled(ph) / total_all, 4),
                   "per_cand_ns": (round(sum(durs) / cands * 1e9, 3)
                                   if cands else None)}
    return out


def _busy_by_worker(spans: list) -> dict:
    """worker -> busy fraction over its own active span: union
    coverage / (first sweep start .. last sweep end) -- the offline
    form of the live dprf_device_busy_fraction gauge, same union-hole
    math as tools/trace_overlap.py."""
    from dprf_tpu.telemetry.trace import overlap_report
    rep = overlap_report(spans)
    sweeps_by_proc: dict = {}
    for s in spans:
        if s.get("name") == "sweep":
            sweeps_by_proc.setdefault(str(s.get("proc")), []).append(s)
    out = {}
    for proc, w in rep["workers"].items():
        sw = sweeps_by_proc.get(proc, [])
        if not sw:
            continue
        t0 = min(float(s.get("ts", 0.0)) for s in sw)
        t1 = max(float(s.get("ts", 0.0)) + float(s.get("dur", 0.0))
                 for s in sw)
        span = t1 - t0
        if span <= 0:
            out[proc] = 1.0
            continue
        out[proc] = round(max(0.0, span - w["idle_s"]) / span, 4)
    return out


def _throughput(spans: list, snapshot: Optional[dict]) -> dict:
    """H/s two ways: swept keyspace over the sweep-span wall window
    (trace-derived), and the candidates counter over the snapshot's
    elapsed time (telemetry-derived)."""
    sw = [s for s in spans if s.get("name") == "sweep"]
    out: dict = {"trace_hs": None, "telemetry_hs": None,
                 "candidates": 0}
    lengths = [int((s.get("attrs") or {}).get("length") or 0)
               for s in sw]
    if sw and sum(lengths) > 0:
        t0 = min(float(s.get("ts", 0.0)) for s in sw)
        t1 = max(float(s.get("ts", 0.0)) + float(s.get("dur", 0.0))
                 for s in sw)
        if t1 > t0:
            out["trace_hs"] = sum(lengths) / (t1 - t0)
        out["candidates"] = sum(lengths)
    if snapshot:
        cands = _counter_total(snapshot,
                               "dprf_candidates_hashed_total")
        elapsed = float(snapshot.get("elapsed_s") or 0.0)
        if cands and elapsed > 0:
            out["telemetry_hs"] = cands / elapsed
            out["candidates"] = max(out["candidates"], int(cands))
    return out


def _health_section(session_path: str, journal) -> Optional[dict]:
    """Fleet health post-mortem (ISSUE 10): fold the session's
    ``.alerts.jsonl`` transition stream and the journal's
    ``worker_health`` records into fired-per-rule counts, the alerts
    that never resolved, and each worker's final state.  None when
    the session left neither artifact (pre-health sessions)."""
    from dprf_tpu.telemetry.alerts import alerts_path, load_alerts
    events = load_alerts(alerts_path(session_path))
    health_events = (journal.health_events or []) if journal else []
    if not events and not health_events:
        return None
    fired: dict = {}
    last_state: dict = {}    # (rule, label key) -> last event
    for e in events:
        key = (str(e.get("rule")),
               tuple(sorted((e.get("labels") or {}).items())))
        last_state[key] = e
        if e.get("state") == "firing":
            fired[key[0]] = fired.get(key[0], 0) + 1
    # only FIRING counts as unresolved: a trailing "pending" event
    # usually means the condition cleared before the sustain window
    # (the engine drops those silently), and reporting it would be a
    # false post-mortem signal
    unresolved = sorted({
        f"{k[0]}({','.join(str(v) for _, v in k[1])})"
        if k[1] else k[0]
        for k, e in last_state.items()
        if e.get("state") == "firing"})
    workers: dict = {}
    for h in health_events:
        w = h.get("worker")
        if w is not None:
            workers[str(w)] = str(h.get("to"))
    return {"alert_events": len(events),
            "fired": fired,
            "unresolved": unresolved,
            "worker_transitions": len(health_events),
            "workers": workers}


def _memory_section(snapshot: Optional[dict]) -> Optional[dict]:
    """Device memory & program costs (ISSUE 13), reconstructed from
    the session's telemetry snapshots alone: the HBM gauges the
    devstats poller wrote (absent on backends without memory stats),
    the per-program peak-bytes gauge, and the analyzed-vs-hand
    roofline divergence cross-check.  None when the session recorded
    none of them (pre-introspection sessions)."""
    devices = {}
    for name, field in (("dprf_hbm_bytes_in_use", "in_use"),
                        ("dprf_hbm_bytes_limit", "limit"),
                        ("dprf_hbm_bytes_peak", "peak")):
        for v in _metric_values(snapshot, name):
            dev = (v.get("labels") or {}).get("device", "?")
            devices.setdefault(dev, {})[field] = int(
                v.get("value") or 0)
    programs = []
    for v in _metric_values(snapshot, "dprf_program_peak_bytes"):
        lv = v.get("labels") or {}
        programs.append({"engine": lv.get("engine", "?"),
                         "attack": lv.get("attack", "?"),
                         "peak_bytes": int(v.get("value") or 0)})
    programs.sort(key=lambda p: (p["engine"], p["attack"]))
    divergence = {}
    for v in _metric_values(snapshot, "dprf_roofline_model_divergence"):
        eng = (v.get("labels") or {}).get("engine", "?")
        divergence[eng] = round(float(v.get("value") or 0.0), 3)
    if not devices and not programs and not divergence:
        return None
    return {"devices": devices, "programs": programs,
            "model_divergence": divergence}


def _profile_section(journal) -> Optional[list]:
    """Kernel-profile captures (ISSUE 15): the ``{"type":
    "profile"}`` summaries the serve plane journaled when workers
    pushed their on-demand / alert-triggered capture windows.  None
    when the session recorded none (pre-profiling sessions, or
    nothing ever fired)."""
    records = (journal.profiles or []) if journal else []
    if not records:
        return None
    out = []
    for r in records:
        s = r.get("summary") or {}
        out.append({"worker": str(r.get("worker", "?")),
                    "trigger": s.get("trigger"),
                    "ts": s.get("ts"),
                    "engine": s.get("engine"),
                    "device_s": s.get("device_s"),
                    "fractions": s.get("fractions"),
                    "phases": s.get("phases"),
                    "top_ops": (s.get("top_ops") or [])[:5],
                    "divergence": s.get("divergence"),
                    "error": s.get("error")})
    return out


def _coverage_section(session_path: str) -> Optional[dict]:
    """Coverage audit summary (ISSUE 19): the offline auditor's
    per-job fraction / overlap / gap / digest-match rows plus its
    verdict, so the perf report answers "did we actually try
    everything?" next to "how fast?".  None when the auditor finds no
    artifacts (the full story lives in ``dprf audit``)."""
    from dprf_tpu.perfreport.audit import build_audit
    doc = build_audit(session_path)
    if doc is None:
        return None
    jobs = [{"job": j["job"],
             "fraction": j["fraction"],
             "gap_total": j["gap_total"],
             "overlap": j["trace_overlap"],
             "digest_match": j["digest_match"],
             "hit_dupes": j["hit_dupes"]}
            for j in doc["jobs"]]
    return {"verdict": doc["verdict"], "jobs": jobs}


def _fair_share(spans: list, journal) -> list:
    """Per-job lease share vs fair-share weight, from the lease spans
    and the journal's job records (the default job's priority is 1
    unless journaled otherwise)."""
    leases: dict = {}
    for s in spans:
        if s.get("name") != "lease":
            continue
        jid = (s.get("attrs") or {}).get("job")
        if jid is not None:
            leases[str(jid)] = leases.get(str(jid), 0) + 1
    if not leases:
        return []
    prio = {}
    if journal is not None:
        for jid, rec in (journal.jobs or {}).items():
            try:
                prio[str(jid)] = max(1, int(rec.get("priority") or 1))
            except (TypeError, ValueError):
                prio[str(jid)] = 1
    total = sum(leases.values())
    weight_total = sum(prio.get(j, 1) for j in leases)
    out = []
    for jid in sorted(leases):
        w = prio.get(jid, 1)
        out.append({"job": jid, "leases": leases[jid],
                    "actual_share": round(leases[jid] / total, 4),
                    "weight_share": round(w / weight_total, 4),
                    "priority": w})
    return out


def build_report(session_path: str) -> Optional[dict]:
    """The machine-readable report, or None when the session left no
    artifacts at all."""
    from dprf_tpu.runtime.session import SessionJournal
    spans = load_trace(trace_path(session_path))
    snaps = load_snapshots(telemetry_path(session_path))
    journal = (SessionJournal.load(session_path)
               if os.path.exists(session_path) else None)
    if not spans and not snaps and journal is None:
        return None
    last = snaps[-1] if snaps else None
    engine = (journal.spec.get("engine") if journal
              and journal.spec else None)
    thr = _throughput(spans, last)
    rate = thr.get("trace_hs") or thr.get("telemetry_hs")
    hits = _counter_total(last, "dprf_compile_cache_hits_total")
    misses = _counter_total(last, "dprf_compile_cache_misses_total")
    depth_vals = _metric_values(last, "dprf_worker_pipeline_depth")
    sweeps = [s for s in spans if s.get("name") == "sweep"]
    probed = sum(1 for s in sweeps
                 if (s.get("attrs") or {}).get("probed"))
    sample_scale = (len(sweeps) / probed) if probed else 1.0
    return {
        "session": session_path,
        "engine": engine,
        "spans": len(spans),
        "units": len(sweeps),
        "probed_units": probed,
        "throughput": {
            "hs": rate,
            "trace_hs": thr["trace_hs"],
            "telemetry_hs": thr["telemetry_hs"],
            "candidates": thr["candidates"],
            "roofline_frac": (roofline_fraction(engine, rate)
                              if engine and rate else None),
        },
        "phases": _phase_stats(spans, sample_scale=sample_scale),
        "busy": _busy_by_worker(spans),
        "compile_cache": {
            "hits": int(hits), "misses": int(misses),
            "hit_rate": (round(hits / (hits + misses), 4)
                         if hits + misses else None),
        },
        "pipeline_depth": (float(depth_vals[-1]["value"])
                           if depth_vals else None),
        "fair_share": _fair_share(spans, journal),
        "coverage": _coverage_section(session_path),
        "health": _health_section(session_path, journal),
        "memory": _memory_section(last),
        "profiles": _profile_section(journal),
    }


def _fmt_hs(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    for unit, div in (("GH/s", 1e9), ("MH/s", 1e6), ("kH/s", 1e3)):
        if v >= div:
            return f"{v / div:,.2f} {unit}"
    return f"{v:,.0f} H/s"


def render_report(doc: dict) -> str:
    """The human half: a sectioned text report (stdout of ``dprf
    report``; CI uploads it as an artifact)."""
    lines = [f"dprf report — {doc['session']}",
             f"engine {doc.get('engine') or '?'} | "
             f"{doc['units']} units ({doc['probed_units']} probed) | "
             f"{doc['spans']} spans"]
    thr = doc["throughput"]
    roof = thr.get("roofline_frac")
    lines.append("")
    lines.append("throughput")
    lines.append(f"  swept      {thr['candidates']:,} candidates")
    lines.append(f"  rate       {_fmt_hs(thr.get('hs'))}"
                 + (f"  (roofline {roof:.2f})" if roof else ""))
    if thr.get("telemetry_hs") and thr.get("trace_hs"):
        lines.append(f"  telemetry  {_fmt_hs(thr['telemetry_hs'])}")
    phases = doc.get("phases") or {}
    if phases:
        lines.append("")
        lines.append("phase breakdown (sampled probes)")
        lines.append(f"  {'PHASE':9s} {'COUNT':>6s} {'P50':>10s} "
                     f"{'P95':>10s} {'TOTAL':>10s} {'SHARE':>6s} "
                     f"{'PER-CAND':>10s}")
        for ph in PHASES:
            st = phases.get(ph)
            if not st:
                continue
            pc = st.get("per_cand_ns")
            lines.append(
                f"  {ph:9s} {st['count']:>6d} "
                f"{st['p50_s'] * 1e3:>8.2f}ms "
                f"{st['p95_s'] * 1e3:>8.2f}ms "
                f"{st['total_s']:>9.3f}s "
                f"{100 * st['share']:>5.1f}% "
                + (f"{pc:>8.2f}ns" if pc is not None
                   else f"{'-':>10s}"))
    cov = doc.get("coverage")
    if cov:
        lines.append("")
        lines.append(f"coverage (audit verdict "
                     f"{cov['verdict'].upper()})")
        for j in cov.get("jobs") or ():
            frac = j.get("fraction")
            gap = j.get("gap_total")
            mark = {True: "match", False: "MISMATCH",
                    None: "n/a"}[j.get("digest_match")]
            lines.append(
                f"  {j['job'][:10]:10s} fraction "
                + (f"{frac:.4f}" if frac is not None else "   n/a")
                + f"  gaps {gap if gap is not None else '?'}"
                + f"  overlap {j.get('overlap', 0)}"
                + f"  digest {mark}"
                + (f"  hit dupes {j['hit_dupes']}"
                   if j.get("hit_dupes") else ""))
    busy = doc.get("busy") or {}
    if busy:
        lines.append("")
        lines.append("device busy fraction (sweep-span union)")
        for w in sorted(busy):
            lines.append(f"  {w:24s} {100 * busy[w]:>5.1f}%")
    cc = doc.get("compile_cache") or {}
    lines.append("")
    lines.append(
        "compile cache  hits "
        f"{cc.get('hits', 0)} / misses {cc.get('misses', 0)}"
        + (f"  (hit rate {100 * cc['hit_rate']:.0f}%)"
           if cc.get("hit_rate") is not None else ""))
    if doc.get("pipeline_depth") is not None:
        lines.append(f"pipeline depth {doc['pipeline_depth']:.0f}")
    health = doc.get("health")
    if health:
        lines.append("")
        lines.append("fleet health & alerts")
        fired = health.get("fired") or {}
        if fired:
            for rule in sorted(fired):
                lines.append(f"  fired {rule:24s} x{fired[rule]}")
        else:
            lines.append(f"  no alerts fired "
                         f"({health.get('alert_events', 0)} events)")
        unresolved = health.get("unresolved") or []
        if unresolved:
            lines.append("  UNRESOLVED at shutdown: "
                         + ", ".join(unresolved))
        workers = health.get("workers") or {}
        for w in sorted(workers):
            lines.append(f"  worker {w:20s} last transition -> "
                         f"{workers[w]}")
    memory = doc.get("memory")
    if memory:
        lines.append("")
        lines.append("device memory & program costs")
        for dev in sorted(memory.get("devices") or {}):
            rec = memory["devices"][dev]

            def _mb(k):
                v = rec.get(k)
                return f"{v / (1 << 20):,.0f}M" if v else "-"

            lines.append(f"  {dev:12s} in_use {_mb('in_use'):>9s}  "
                         f"peak {_mb('peak'):>9s}  "
                         f"limit {_mb('limit'):>9s}")
        for p in memory.get("programs") or ():
            lines.append(
                f"  program {p['engine']:12s} {p['attack']:12s} "
                f"peak {p['peak_bytes'] / (1 << 20):,.1f}M")
        div = memory.get("model_divergence") or {}
        for eng in sorted(div):
            flag = "  (>2x: MODEL DRIFT)" if div[eng] > 2 else ""
            lines.append(f"  roofline model divergence {eng}: "
                         f"{div[eng]:.2f}x{flag}")
    profiles = doc.get("profiles") or []
    if profiles:
        lines.append("")
        lines.append("kernel profile (captured windows)")
        for p in profiles:
            head = (f"  {p['worker']:20s} trigger "
                    f"{p.get('trigger') or '?':12s}")
            if p.get("error"):
                lines.append(head + f" FAILED: {p['error']}")
                continue
            fr = p.get("fractions") or {}
            head += (f" device {p.get('device_s') or 0.0:.4f}s  "
                     f"compute {100 * fr.get('compute', 0.0):.0f}% "
                     f"coll {100 * fr.get('collective', 0.0):.0f}% "
                     f"copy {100 * fr.get('copy', 0.0):.0f}%")
            d = p.get("divergence")
            if d:
                head += f"  divergence {d:.2f}x"
            lines.append(head)
            for op in (p.get("top_ops") or [])[:3]:
                lines.append(f"      {op.get('self_s', 0.0):>9.4f}s  "
                             f"{str(op.get('name'))[:56]}")
    fs = doc.get("fair_share") or []
    if len(fs) > 1:
        lines.append("")
        lines.append("fair share (lease counts vs weights)")
        lines.append(f"  {'JOB':6s} {'PRIO':>4s} {'LEASES':>7s} "
                     f"{'ACTUAL':>7s} {'WEIGHT':>7s}")
        for row in fs:
            lines.append(
                f"  {row['job'][:6]:6s} {row['priority']:>4d} "
                f"{row['leases']:>7d} "
                f"{100 * row['actual_share']:>6.1f}% "
                f"{100 * row['weight_share']:>6.1f}%")
    return "\n".join(lines)
