"""Wordlist and wordlist+rules candidate generation (benchmark config 3).

Keyspace layout: index = word_index * n_rules + rule_index, so a
contiguous WorkUnit covers whole words (all rules of one word are
adjacent) and a device step over a word batch covers a contiguous index
range — the property the Dispatcher's interval ledger and session
resume rely on (SURVEY.md section 2: Dispatcher "contiguous shards").

Rejected candidates (a rule that rejects, or whose result overflows
max_len) are *holes* in the keyspace: `candidate()` returns None and
workers skip them.  The index->candidate map for non-rejected indices is
still a bijection onto the generated candidate multiset, and resume
bookkeeping only needs index ranges, so holes cost nothing.

The packed word arrays (uint8[N_pad, L] + int32 lengths) are built once
on the host and uploaded to HBM once per job; device steps slice them
with `lax.dynamic_slice`, so after upload no candidate material crosses
the host boundary.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from dprf_tpu.generators.base import CandidateGenerator
from dprf_tpu.rules.cpu import apply_rule as apply_rule_cpu
from dprf_tpu.rules.parser import Op, Opcode, load_rules

NOOP_RULE: tuple[Op, ...] = (Op(Opcode.NOOP),)


def load_words(path: str, max_len: int,
               encoding: str = "latin-1") -> tuple[list[bytes], int]:
    """Read a wordlist file -> (words, n_skipped_too_long).

    Lines are stripped of trailing CR/LF only (leading/interior spaces
    are part of the word).  Empty lines are dropped.  Words longer than
    max_len can never produce a <= max_len candidate through the
    common grow-only rule sets, but CAN through truncating rules — they
    are still skipped here (matching the fixed-width device layout) and
    counted so the CLI can report it.
    """
    words: list[bytes] = []
    skipped = 0
    with open(path, "rb") as fh:
        for raw in fh:
            word = raw.rstrip(b"\r\n")
            if not word:
                continue
            if len(word) > max_len:
                skipped += 1
                continue
            words.append(word)
    if not words:
        raise ValueError(f"wordlist {path!r} contains no usable words")
    return words, skipped


class WordlistRulesGenerator(CandidateGenerator):
    """words x rules keyspace with host oracle + packed device tables.

    Word storage is the packed pair (uint8[N, max_len] zero-padded rows,
    int32[N] lengths) -- the exact layout the device consumes -- built
    either from a list of words or directly by the native loader
    (dprf_tpu/native/wordlist.cpp) without ever materializing Python
    bytes objects.
    """

    def __init__(self, words: Optional[Sequence[bytes]] = None,
                 rules: Optional[Sequence[tuple[Op, ...]]] = None,
                 max_len: int = 55,
                 packed: Optional[tuple[np.ndarray, np.ndarray]] = None):
        if (words is None) == (packed is None):
            raise ValueError("pass exactly one of words / packed")
        self.rules = list(rules) if rules else [NOOP_RULE]
        self.max_len = self.max_length = max_len
        if packed is not None:
            buf, lens = packed
            if buf.ndim != 2 or buf.shape[1] != max_len or \
                    len(lens) != buf.shape[0]:
                raise ValueError("packed arrays disagree with max_len")
            self._buf = np.ascontiguousarray(buf, dtype=np.uint8)
            self._lens = np.asarray(lens, dtype=np.int32)
        else:
            if not words:
                raise ValueError("empty wordlist")
            if any(len(w) > max_len for w in words):
                raise ValueError(f"word longer than max_len={max_len}")
            self._buf = np.zeros((len(words), max_len), dtype=np.uint8)
            self._lens = np.zeros((len(words),), dtype=np.int32)
            for i, w in enumerate(words):
                self._buf[i, :len(w)] = np.frombuffer(w, dtype=np.uint8)
                self._lens[i] = len(w)
        self.n_words = self._buf.shape[0]
        if self.n_words == 0:
            raise ValueError("empty wordlist")
        self.n_rules = len(self.rules)
        self.keyspace = self.n_words * self.n_rules

    @classmethod
    def from_files(cls, wordlist_path: str,
                   rules_spec: Optional[str] = None,
                   max_len: int = 55) -> "WordlistRulesGenerator":
        """Build from files, preferring the native (C++) loader.  The
        count of skipped overlong lines lands on `gen.n_skipped_long`."""
        rules = load_rules(rules_spec, on_error="skip") if rules_spec else None
        from dprf_tpu import native
        loaded = native.load_words_packed(wordlist_path, max_len)
        if loaded is not None:
            buf, lens, skipped = loaded
            if len(lens) == 0:
                raise ValueError(
                    f"wordlist {wordlist_path!r} contains no usable words")
            gen = cls(rules=rules, max_len=max_len, packed=(buf, lens))
        else:
            words, skipped = load_words(wordlist_path, max_len)
            gen = cls(words, rules, max_len=max_len)
        gen.n_skipped_long = skipped
        return gen

    def content_id(self) -> str:
        """Digest of the word *content* (what an index decodes to), for
        job fingerprints: hashes the packed tables wholesale at memory
        bandwidth instead of a per-word Python loop."""
        import hashlib
        h = hashlib.sha256()
        h.update(b"dprf-wordlist-v2\0")
        h.update(str(self.n_words).encode())
        # feed the arrays' buffers directly: tobytes() would copy the
        # (potentially multi-GB) packed table just to hash it
        h.update(np.ascontiguousarray(self._lens))
        h.update(np.ascontiguousarray(self._buf))
        return h.hexdigest()[:16]

    # ---------------- host (oracle) path ----------------

    def word(self, w: int) -> bytes:
        return self._buf[w, :self._lens[w]].tobytes()

    def candidate(self, index: int) -> Optional[bytes]:
        """May return None: the (word, rule) pair rejected."""
        if not 0 <= index < self.keyspace:
            raise IndexError(f"index {index} outside keyspace {self.keyspace}")
        w, r = divmod(index, self.n_rules)
        return apply_rule_cpu(self.word(w), self.rules[r], self.max_len)

    def candidates(self, start: int, count: int) -> list:
        return [self.candidate(i)
                for i in range(start, min(start + count, self.keyspace))]

    def index_of(self, word_index: int, rule_index: int) -> int:
        return word_index * self.n_rules + rule_index

    # ---------------- device path ----------------

    def packed_words(self, pad_to: int = 1,
                     min_size: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """(uint8[N_pad, max_len], int32[N_pad]) with N_pad a multiple of
        pad_to and >= min_size.  Callers slicing windows of size W from
        arbitrary word offsets must pass min_size = n_words + W - 1:
        `lax.dynamic_slice` CLAMPS out-of-range starts instead of
        erroring, which would silently re-hash earlier words under wrong
        indices.  Padding lanes have length 0 and are masked by n_valid.
        """
        n_pad = max(pad_to, min_size,
                    -(-self.n_words // pad_to) * pad_to)
        n_pad = -(-n_pad // pad_to) * pad_to
        buf = np.zeros((n_pad, self.max_len), dtype=np.uint8)
        lens = np.zeros((n_pad,), dtype=np.int32)
        buf[:self.n_words] = self._buf
        lens[:self.n_words] = self._lens
        return buf, lens

    def __repr__(self) -> str:  # pragma: no cover
        return (f"<WordlistRulesGenerator words={self.n_words} "
                f"rules={self.n_rules} keyspace={self.keyspace}>")
