"""CandidateGenerator interface.

A generator defines a keyspace [0, N) and a bijection index -> candidate
password.  The Dispatcher splits [0, N) into WorkUnits by index range,
so generators must support random access by index -- this is what makes
work distribution embarrassingly parallel and resumable.

Device generators additionally decode *on device*: a jitted function
takes a unit's base index (as a mixed-radix digit vector, so all device
arithmetic stays int32 even for keyspaces far beyond 2^32) and
materializes a batch of candidates directly in HBM.
"""

from __future__ import annotations

import abc


class CandidateGenerator(abc.ABC):
    #: total number of candidates this generator can produce
    keyspace: int
    #: maximum candidate length in bytes
    max_length: int

    @abc.abstractmethod
    def candidate(self, index: int) -> bytes:
        """Host-side random access decode (oracle / verification path)."""

    def candidates(self, start: int, count: int) -> list[bytes]:
        return [self.candidate(i) for i in range(start, min(start + count,
                                                            self.keyspace))]
