"""Rank-ordered keyspace dispatch: the rank<->index bijection (ISSUE 20).

The Dispatcher splits, leases, resumes, and re-splits in **rank
space** -- rank 0 is the candidate the attack should try FIRST -- while
workers keep decoding by **index** (the mixed-radix position the
generator's device decode understands).  The bridge is a per-generator
``RankOrder``: an exact bijection between the two spaces plus the
interval calculus the dispatcher and journal need:

  - ``rank_to_index`` / ``index_to_rank``: the point maps;
  - ``index_spans(rank_start, rank_end)``: a rank interval as
    CONTIGUOUS index runs, in rank order -- what an OrderedWorker
    (runtime/worker.py) submits through the unchanged device pipeline
    (each run flows through the existing ``digits(base) + offset``
    decode, so sharded supersteps and Pallas kernels never see ranks);
  - ``index_image`` / ``rank_image``: canonical merged interval-set
    images -- journal snapshots and coverage digests canonicalize over
    the index image of the dispatcher's rank intervals, so
    exactly-once coverage and digest-checked resume survive
    reordering (a journal is always written in index space; the same
    sweep digests identically under any order).

``MarkovOrder`` is the first real ordering (OMEN-style): it composes
with a Markov-reordered ``MaskGenerator`` (generators/markov.py), whose
per-position charsets are already sorted by trained frequency -- so a
position's DIGIT is its frequency LEVEL, and the candidates most
likely overall are the ones with the smallest level SUM.  Enumerating
exact level-sum order over all positions would shatter every rank
interval into single indices; instead the order splits the mask into a
leading **prefix** (the k most-significant positions, ranked by
ascending level sum, ties lexicographic) and a **suffix block** (the
remaining positions, swept in plain index order within each prefix).
``rank = prefix_rank * B + suffix_offset`` with ``B = prod(radices[k:])``
keeps every rank interval inside a block one contiguous index run --
device batches stay dense -- while the prefix ranking still front-loads
the probable region of the keyspace: position 0 dominates real-world
structure, which is exactly what per-position Markov stats capture.

The split point k is chosen from two knobs (or pinned explicitly --
the wire job carries it, so a fleet can never fork the bijection on
divergent env):

  - ``DPRF_ORDER_BLOCK_MIN``: minimum suffix block size, so device
    batches/supersteps stay within blocks (steady-state H/s penalty
    bounded by the per-submit overhead amortized over >= this many
    candidates);
  - ``DPRF_ORDER_PREFIX_MAX``: maximum number of prefix blocks, so
    the index image of a rank interval -- and with it every journal
    snapshot and resume -- stays a bounded number of runs.

Prefix rank<->vector conversion is a standard DP unranking over
bounded compositions (count vectors below a level sum, then peel
positions); O(k * max_radix) per conversion, nothing materialized.
"""

from __future__ import annotations

from typing import Optional, Sequence

from dprf_tpu.utils import env as envreg

#: split-choice knobs (see module docstring); read via envreg getters
BLOCK_MIN_ENV = "DPRF_ORDER_BLOCK_MIN"
PREFIX_MAX_ENV = "DPRF_ORDER_PREFIX_MAX"

#: order kinds accepted on the wire / CLI ("index" = no reordering)
ORDER_KINDS = ("index", "markov")


def _merge(spans: list) -> list:
    """Sorted, merged [start, end) tuples from arbitrary spans."""
    out: list = []
    for s, e in sorted(spans):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


class IdentityOrder:
    """rank == index: wordlist/combinator order (until PRINCE lands),
    and the explicit ``--order index`` default.  ``build_order``
    returns None for it so nothing gets wrapped, but tests and the
    chaos harness use it to exercise order-generic code paths."""

    kind = "index"
    split = 0

    def __init__(self, keyspace: int):
        self.keyspace = int(keyspace)

    def rank_to_index(self, rank: int) -> int:
        return rank

    def index_to_rank(self, index: int) -> int:
        return index

    def index_spans(self, rank_start: int, rank_end: int) -> list:
        return ([(rank_start, rank_end)]
                if rank_end > rank_start else [])

    def index_image(self, intervals) -> list:
        return _merge(list(intervals))

    def rank_image(self, intervals) -> list:
        return _merge(list(intervals))


class MarkovOrder:
    """Level-sum block-permutation order over a mixed-radix keyspace.

    ``radices`` are the generator's per-position charset sizes with
    position 0 MOST significant (MaskGenerator.digits order).  The
    contract is compositional: digit value == probability level, which
    holds exactly when the generator's charsets were reordered by
    trained frequency (``MaskGenerator(mask, markov_counts=...)``).
    The bijection itself is valid for any radices -- it is just a
    permutation of [0, keyspace) -- so a mis-trained model can cost
    time-to-first-hit, never coverage.
    """

    kind = "markov"

    def __init__(self, radices: Sequence[int],
                 split: Optional[int] = None):
        self.radices = tuple(int(r) for r in radices)
        if not self.radices or any(r < 1 for r in self.radices):
            raise ValueError("radices must be positive and non-empty")
        self.keyspace = 1
        for r in self.radices:
            self.keyspace *= r
        n = len(self.radices)
        if split is not None:
            k = int(split)
            if not 1 <= k <= n:
                raise ValueError(
                    f"order split {k} outside [1, {n}] for a "
                    f"{n}-position mask")
        else:
            block_min = max(1, envreg.get_int(BLOCK_MIN_ENV))
            prefix_max = max(1, envreg.get_int(PREFIX_MAX_ENV))
            k, block = n, 1
            while k > 1 and (block < block_min
                             or self._prefix_prod(k) > prefix_max):
                k -= 1
                block *= self.radices[k]
        #: prefix length: positions [0, k) are rank-ordered, the
        #: suffix [k, n) sweeps in index order within each block
        self.split = k
        #: suffix block size B: rank = prefix_rank * B + offset
        self.block = 1
        for r in self.radices[k:]:
            self.block *= r
        #: number of prefix blocks (prefix keyspace)
        self.blocks = self.keyspace // self.block
        # DP table over bounded compositions of the prefix:
        # _count[p][L] = digit vectors for positions p..k-1 summing to
        # exactly L.  Row p has sum(r[i]-1 for i in p..k-1)+1 entries.
        counts = [[1]]
        for p in range(k - 1, -1, -1):
            nxt = counts[0]
            radix = self.radices[p]
            row = [0] * (len(nxt) + radix - 1)
            for d in range(radix):
                for L, c in enumerate(nxt):
                    row[d + L] += c
            counts.insert(0, row)
        self._count = counts
        #: cumulative prefix ranks below each level sum:
        #: _cum[s] = # of prefix vectors with level sum < s
        cum = [0]
        for c in counts[0]:
            cum.append(cum[-1] + c)
        self._cum = cum

    def _prefix_prod(self, k: int) -> int:
        p = 1
        for r in self.radices[:k]:
            p *= r
        return p

    # -- prefix rank <-> digit vector (DP unranking) ---------------------

    def _prefix_digits_of_rank(self, prank: int) -> list:
        cum = self._cum
        # level sum s: cum[s] <= prank < cum[s+1] (binary search)
        lo, hi = 0, len(cum) - 1
        while lo + 1 < hi:
            mid = (lo + hi) // 2
            if cum[mid] <= prank:
                lo = mid
            else:
                hi = mid
        s = lo
        rem = prank - cum[s]
        digits = []
        for p in range(self.split):
            row = self._count[p + 1]
            for d in range(min(self.radices[p] - 1, s) + 1):
                c = row[s - d] if s - d < len(row) else 0
                if rem < c:
                    digits.append(d)
                    s -= d
                    break
                rem -= c
        return digits

    def _prefix_rank_of_digits(self, digits: Sequence[int]) -> int:
        s = sum(digits)
        rank = self._cum[s]
        rem = s
        for p, dp in enumerate(digits):
            row = self._count[p + 1]
            for d in range(dp):
                if 0 <= rem - d < len(row):
                    rank += row[rem - d]
            rem -= dp
        return rank

    def _prefix_digits_of_index(self, pidx: int) -> list:
        out = [0] * self.split
        for p in range(self.split - 1, -1, -1):
            pidx, out[p] = divmod(pidx, self.radices[p])
        return out

    def _prefix_index_of_digits(self, digits: Sequence[int]) -> int:
        idx = 0
        for p, d in enumerate(digits):
            idx = idx * self.radices[p] + d
        return idx

    # -- the point maps --------------------------------------------------

    def rank_to_index(self, rank: int) -> int:
        if not 0 <= rank < self.keyspace:
            raise IndexError(
                f"rank {rank} outside keyspace {self.keyspace}")
        prank, off = divmod(rank, self.block)
        digits = self._prefix_digits_of_rank(prank)
        return self._prefix_index_of_digits(digits) * self.block + off

    def index_to_rank(self, index: int) -> int:
        if not 0 <= index < self.keyspace:
            raise IndexError(
                f"index {index} outside keyspace {self.keyspace}")
        pidx, off = divmod(index, self.block)
        digits = self._prefix_digits_of_index(pidx)
        return self._prefix_rank_of_digits(digits) * self.block + off

    # -- the interval calculus -------------------------------------------

    def index_spans(self, rank_start: int, rank_end: int) -> list:
        """The rank interval as contiguous [start, end) index runs, in
        RANK order (adjacent runs coalesced): what a worker sweeps, in
        the order the dispatcher meant.  At most one run per prefix
        block touched."""
        out: list = []
        r = rank_start
        while r < rank_end:
            prank, off = divmod(r, self.block)
            take = min(rank_end - r, self.block - off)
            digits = self._prefix_digits_of_rank(prank)
            s = (self._prefix_index_of_digits(digits) * self.block
                 + off)
            if out and out[-1][1] == s:
                out[-1] = (out[-1][0], s + take)
            else:
                out.append((s, s + take))
            r += take
        return out

    def index_image(self, intervals) -> list:
        """Canonical (sorted, merged) index-space image of rank-space
        intervals -- the journal/digest form."""
        spans: list = []
        for s, e in intervals:
            spans.extend(self.index_spans(s, e))
        return _merge(spans)

    def rank_image(self, intervals) -> list:
        """Canonical rank-space image of index-space intervals -- the
        resume direction (journaled index intervals back into the
        dispatcher's rank ledger).  Exact inverse of index_image."""
        spans: list = []
        for s, e in intervals:
            i = s
            while i < e:
                pidx, off = divmod(i, self.block)
                take = min(e - i, self.block - off)
                digits = self._prefix_digits_of_index(pidx)
                rs = (self._prefix_rank_of_digits(digits) * self.block
                      + off)
                spans.append((rs, rs + take))
                i += take
        return _merge(spans)


def build_order(kind: Optional[str], gen,
                split: Optional[int] = None):
    """The one order factory: an order kind from the CLI/wire spec plus
    the (already Markov-reordered, when applicable) generator.  Returns
    None for identity order -- the fast path: nothing is wrapped, the
    dispatcher ledger IS index space, and journals stay byte-identical
    to pre-order runs."""
    if kind in (None, "", "index"):
        return None
    if kind == "markov":
        radices = getattr(gen, "radices", None)
        if radices is None:
            raise ValueError(
                "--order markov needs a mask generator (per-position "
                "radices); wordlist/combinator attacks run in index "
                "order until PRINCE lands")
        return MarkovOrder(radices, split=split)
    raise ValueError(
        f"unknown candidate order {kind!r} (choices: "
        f"{', '.join(ORDER_KINDS)})")
