"""Per-position Markov ordering for mask attacks (hashcat's
--markov-classic semantics).

Password positions are not uniform: 'a' leads position 0 far more
often than '\\'.  Training counts byte frequencies per position over a
corpus; a mask generator given those stats visits each position's
charset in descending-frequency order, so low indices decode to likely
candidates and a partial keyspace sweep (or --limit window) catches
real passwords orders of magnitude sooner.  The keyspace and the
index<->candidate bijection machinery are untouched -- ordering is just
a permutation of each position's charset BEFORE the mixed-radix decode,
so every device path (XLA gather decode, sharded steps) works
unchanged.  Since r5 the Pallas kernels cover permuted charsets too:
positions that exceed the arithmetic segment budget decode through a
256-entry lane-axis LUT (ops/pallas_mask.charset_lut -- one
per-sublane gather, the krb5 S-box layout), so Markov-ordered mask
jobs run at kernel rates instead of the old XLA gather floor.

Stats format (.dprfstat): magic | uint16 max_len | uint64le counts
[max_len][256].  Positions past the trained length reuse the last
trained position's ordering.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Iterable, Optional, Sequence

import numpy as np

MAGIC = b"DPRFSTA1"
MAX_LEN = 32


def train_stats(words: Iterable[bytes], max_len: int = MAX_LEN) -> np.ndarray:
    """Corpus -> uint64[max_len, 256] per-position byte counts.
    Vectorized per chunk (np.add.at) -- a rockyou-size corpus is ~10^8
    (position, byte) increments, minutes in a Python loop."""
    if max_len < 1:
        raise ValueError("max_len must be >= 1")
    counts = np.zeros((max_len, 256), dtype=np.uint64)
    pos_chunk, byte_chunk = [], []

    def flush():
        if pos_chunk:
            np.add.at(counts,
                      (np.concatenate(pos_chunk),
                       np.concatenate(byte_chunk)), 1)
            pos_chunk.clear()
            byte_chunk.clear()

    pending = 0
    for w in words:
        w = w[:max_len]
        if not w:
            continue
        pos_chunk.append(np.arange(len(w), dtype=np.intp))
        byte_chunk.append(np.frombuffer(w, dtype=np.uint8))
        pending += len(w)
        if pending >= 1 << 20:
            flush()
            pending = 0
    flush()
    return counts


def train_file(path: str, max_len: int = MAX_LEN) -> np.ndarray:
    def lines():
        with open(path, "rb") as fh:
            for raw in fh:
                w = raw.rstrip(b"\r\n")
                if w:
                    yield w
    return train_stats(lines(), max_len)


def save_stats(path: str, counts: np.ndarray) -> None:
    counts = np.ascontiguousarray(counts, dtype="<u8")
    with open(path, "wb") as fh:
        fh.write(MAGIC)
        fh.write(struct.pack("<H", counts.shape[0]))
        fh.write(counts.tobytes())


def load_stats(path: str) -> np.ndarray:
    with open(path, "rb") as fh:
        data = fh.read()
    if not data.startswith(MAGIC):
        raise ValueError(f"{path}: not a dprf Markov stats file")
    (n,) = struct.unpack_from("<H", data, len(MAGIC))
    if n < 1:
        raise ValueError(f"{path}: stats file has no positions")
    body = data[len(MAGIC) + 2:]
    if len(body) != n * 256 * 8:
        raise ValueError(f"{path}: truncated stats ({len(body)} bytes "
                         f"for {n} positions)")
    return np.frombuffer(body, dtype="<u8").reshape(n, 256).astype(np.uint64)


def stats_digest(counts: np.ndarray) -> str:
    """Content fingerprint -- part of the job identity: different stats
    reorder the keyspace, so workers must agree on them exactly."""
    return hashlib.sha256(
        np.ascontiguousarray(counts, dtype="<u8").tobytes()).hexdigest()[:16]


def reorder_charsets(charsets: Sequence[bytes],
                     counts: np.ndarray) -> list[bytes]:
    """Each position's charset in descending trained frequency
    (ties by byte value, so ordering is deterministic)."""
    out = []
    last = counts.shape[0] - 1
    for pos, cs in enumerate(charsets):
        row = counts[min(pos, last)]
        out.append(bytes(sorted(cs, key=lambda b: (-int(row[b]), b))))
    return out
