"""Combinator attack candidate generation (hashcat -a 1, plus the
hybrid modes built on it).

Keyspace layout: index = left_index * n_right + right_index, a 2-digit
mixed-radix system (radices [n_left, n_right]) -- the same digit-vector
convention the mask generator uses, so workers drive combinator steps
with the identical (base_digits, n_valid) contract and 64-bit keyspaces
never need 64-bit device arithmetic.

A combined candidate longer than max_len is a *hole* (candidate() ->
None), exactly like a rejected rule in the wordlist path: device steps
mask those lanes invalid, host oracles skip them, and resume
bookkeeping stays pure index ranges.

Both word tables live packed in HBM (uint8[N, L] + int32[N]); the
device step gathers rows by index, so after the one-time upload no
candidate material crosses the host boundary.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from dprf_tpu.generators.base import CandidateGenerator


def _pack_table(words: Sequence[bytes]):
    if not words:
        raise ValueError("empty word table")
    width = max(1, max(len(w) for w in words))
    buf = np.zeros((len(words), width), dtype=np.uint8)
    lens = np.zeros((len(words),), dtype=np.int32)
    for i, w in enumerate(words):
        buf[i, :len(w)] = np.frombuffer(w, dtype=np.uint8)
        lens[i] = len(w)
    return buf, lens


class CombinatorGenerator(CandidateGenerator):
    """left words x right words -> left+right concatenations."""

    def __init__(self, left: Sequence[bytes], right: Sequence[bytes],
                 max_len: int = 55):
        self._lbuf, self._llens = _pack_table(left)
        self._rbuf, self._rlens = _pack_table(right)
        self.n_left = self._lbuf.shape[0]
        self.n_right = self._rbuf.shape[0]
        self.max_len = self.max_length = max_len
        self.keyspace = self.n_left * self.n_right
        #: mixed-radix radices, most-significant first (mask convention)
        self.radices = (self.n_left, self.n_right)

    # ---------------- host (oracle) path ----------------

    def digits(self, index: int) -> list[int]:
        if not 0 <= index < self.keyspace:
            raise IndexError(
                f"index {index} outside keyspace {self.keyspace}")
        li, ri = divmod(index, self.n_right)
        return [li, ri]

    def candidate(self, index: int) -> Optional[bytes]:
        li, ri = self.digits(index)
        w = (self._lbuf[li, :self._llens[li]].tobytes()
             + self._rbuf[ri, :self._rlens[ri]].tobytes())
        return w if len(w) <= self.max_len else None

    def candidates(self, start: int, count: int) -> list:
        return [self.candidate(i)
                for i in range(start, min(start + count, self.keyspace))]

    def index_of(self, candidate: bytes) -> int:
        """First (left, right) split producing `candidate` (test helper;
        splits are not necessarily unique)."""
        for li in range(self.n_left):
            lw = self._lbuf[li, :self._llens[li]].tobytes()
            if not candidate.startswith(lw):
                continue
            rest = candidate[len(lw):]
            for ri in range(self.n_right):
                if self._rbuf[ri, :self._rlens[ri]].tobytes() == rest:
                    return li * self.n_right + ri
        raise ValueError(f"{candidate!r} not in combinator keyspace")

    def content_id(self) -> str:
        import hashlib
        h = hashlib.sha256()
        h.update(b"dprf-combinator-v1\0")
        for buf, lens in ((self._lbuf, self._llens),
                          (self._rbuf, self._rlens)):
            h.update(str(len(lens)).encode() + b"\0")
            h.update(np.ascontiguousarray(lens))
            h.update(np.ascontiguousarray(buf))
        return h.hexdigest()[:16]

    # ---------------- device path ----------------

    def tables(self):
        """The packed (left_buf, left_lens, right_buf, right_lens)."""
        return self._lbuf, self._llens, self._rbuf, self._rlens

    def __repr__(self) -> str:  # pragma: no cover
        return (f"<CombinatorGenerator {self.n_left}x{self.n_right} "
                f"keyspace={self.keyspace}>")
