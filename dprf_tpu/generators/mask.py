"""Mask attack candidate generation.

Hashcat-style masks: ``?l?l?l?l?l?l`` is six lowercase letters,
``?a?a?a?a?a?a?a`` seven printable-ASCII characters.  Built-ins:

    ?l  a-z (26)          ?u  A-Z (26)         ?d  0-9 (10)
    ?s  printable symbols incl. space (33)     ?a  = ?l?u?d?s (95)
    ?b  all byte values 0x00-0xff (256)
    ?1..?4  user-defined custom charsets       ??  literal '?'

Any other character in the mask is a literal (radix-1 position).

The keyspace is the product of per-position charset sizes; the
index -> candidate map is a mixed-radix decode with the RIGHTMOST mask
position as the least-significant digit (odometer order).

TPU-first design: `decode_batch` materializes a whole batch of
candidates on device from a unit's *digit vector* plus each lane's
offset, using only int32 adds/mod/div plus a handful of vector
compare/selects per position (segment-mux decode; positions whose
charset exceeds MAX_SEGMENTS contiguous runs fall back to one gather
over the flat table) -- no 64-bit math, no host transfer of candidate
bytes, static shapes throughout.  Radices, charset offsets, and
segment tables are Python-level constants baked into the jitted
program.  The same segment model drives the Pallas kernels'
eligibility and in-kernel decode (ops/pallas_mask.py imports
`charset_segments` from here).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from dprf_tpu.generators.base import CandidateGenerator

_LOWER = bytes(range(ord("a"), ord("z") + 1))
_UPPER = bytes(range(ord("A"), ord("Z") + 1))
_DIGIT = bytes(range(ord("0"), ord("9") + 1))
# Printable ASCII symbols including space: 0x20-0x2F, 0x3A-0x40, 0x5B-0x60,
# 0x7B-0x7E (33 chars) -- matches hashcat's ?s.
_SYMBOL = bytes(range(0x20, 0x30)) + bytes(range(0x3A, 0x41)) + \
    bytes(range(0x5B, 0x61)) + bytes(range(0x7B, 0x7F))
_ALL95 = _LOWER + _UPPER + _DIGIT + _SYMBOL
_BYTES256 = bytes(range(256))

BUILTIN_CHARSETS = {
    "l": _LOWER, "u": _UPPER, "d": _DIGIT, "s": _SYMBOL,
    "a": _ALL95, "b": _BYTES256,
}


def parse_mask(mask: str,
               custom: Optional[Dict[int, bytes]] = None) -> list[bytes]:
    """Mask string -> per-position charsets (left to right)."""
    custom = custom or {}
    charsets: list[bytes] = []
    i = 0
    while i < len(mask):
        ch = mask[i]
        if ch == "?":
            if i + 1 >= len(mask):
                raise ValueError(f"dangling '?' at end of mask {mask!r}")
            sel = mask[i + 1]
            if sel == "?":
                charsets.append(b"?")
            elif sel in BUILTIN_CHARSETS:
                charsets.append(BUILTIN_CHARSETS[sel])
            elif sel.isdigit() and int(sel) in custom:
                cs = custom[int(sel)]
                if not cs:
                    raise ValueError(f"custom charset ?{sel} is empty")
                charsets.append(bytes(cs))
            else:
                raise ValueError(f"unknown mask token ?{sel} in {mask!r}")
            i += 2
        else:
            charsets.append(ch.encode("latin-1"))
            i += 1
    if not charsets:
        raise ValueError("empty mask")
    return charsets


#: segment-decode bound shared by the XLA mux and the Pallas kernels
#: (kernel eligibility: ops/pallas_mask.mask_supported).
MAX_SEGMENTS = 16


def charset_segments(charset: bytes):
    """Charset (digit order) -> [(start_digit, byte_delta)] pieces where
    byte = digit + delta for digit >= start_digit (until next piece).
    Single source of truth for the segment decode model: consumed by
    MaskGenerator.decode_batch's mux AND the Pallas kernel builders
    (ops/pallas_mask.py re-exports it)."""
    segs = []
    for d, byte in enumerate(charset):
        delta = byte - d
        if not segs or segs[-1][1] != delta:
            segs.append((d, delta))
    return segs


def segment_mux(digit, segs):
    """Vectorized piecewise charset lookup: digit array -> byte array.
    Piece starts are ascending, so the last satisfied select wins.
    Shared by decode_batch's XLA mux and the Pallas kernel decode
    (ops/pallas_mask._decode_byte)."""
    byte = digit + segs[0][1]
    for start, delta in segs[1:]:
        byte = jnp.where(digit >= start, digit + delta, byte)
    return byte


class MaskGenerator(CandidateGenerator):
    """index -> fixed-length candidate via mixed-radix decode."""

    def __init__(self, mask: str,
                 custom: Optional[Dict[int, bytes]] = None,
                 markov_counts: Optional[np.ndarray] = None):
        self.mask = mask
        self.charsets = parse_mask(mask, custom)
        if markov_counts is not None:
            # permute each position's charset into trained-frequency
            # order: low indices decode to likely candidates, keyspace
            # and bijection unchanged (generators/markov.py)
            from dprf_tpu.generators.markov import reorder_charsets
            self.charsets = reorder_charsets(self.charsets, markov_counts)
        self.length = len(self.charsets)
        self.max_length = self.length
        self.radices = tuple(len(cs) for cs in self.charsets)
        self.keyspace = 1
        for r in self.radices:
            self.keyspace *= r
        # Device tables: one flat uint8 charset array + per-position offsets.
        offsets, flat = [], bytearray()
        for cs in self.charsets:
            offsets.append(len(flat))
            flat.extend(cs)
        self._offsets = tuple(offsets)
        self._flat_np = np.frombuffer(bytes(flat), dtype=np.uint8)
        # segment-mux decode tables: a charset whose byte values form
        # few contiguous runs (every builtin: ?l/?u/?d/?b/?a are one
        # run, ?s is four) decodes with a handful of vector
        # compare/selects instead of a per-position batch-sized
        # gather -- the gather is the measured XLA mask bottleneck on
        # TPU (BASELINE.md).  None = too many runs (e.g.
        # markov-scrambled order): keep the gather.
        self._segments = tuple(
            segs if len(segs) <= MAX_SEGMENTS else None
            for segs in (charset_segments(cs) for cs in self.charsets))

    # ---------------- host (oracle) path ----------------

    def digits(self, index: int) -> list[int]:
        """Mixed-radix digit vector for a global index (arbitrary size int,
        handled in Python; rightmost position is least significant)."""
        if not 0 <= index < self.keyspace:
            raise IndexError(f"index {index} outside keyspace {self.keyspace}")
        out = [0] * self.length
        for p in range(self.length - 1, -1, -1):
            index, out[p] = divmod(index, self.radices[p])
        return out

    def candidate(self, index: int) -> bytes:
        return bytes(self.charsets[p][d]
                     for p, d in enumerate(self.digits(index)))

    def index_of(self, candidate: bytes) -> int:
        """Inverse map (host): candidate bytes -> global index."""
        if len(candidate) != self.length:
            raise ValueError("wrong candidate length for mask")
        index = 0
        for p, byte in enumerate(candidate):
            d = self.charsets[p].find(bytes([byte]))
            if d < 0:
                raise ValueError(
                    f"byte {byte:#x} not in charset for position {p}")
            index = index * self.radices[p] + d
        return index

    # ---------------- device path ----------------

    @property
    def flat_charsets(self) -> jnp.ndarray:
        return jnp.asarray(self._flat_np)

    def decode_batch(self, base_digits: jnp.ndarray, flat: jnp.ndarray,
                     batch: int, lane_offset=0) -> jnp.ndarray:
        """Materialize `batch` consecutive candidates on device.

        base_digits: int32[length] digit vector of the first candidate
        (from `digits()`, host-computed once per unit).  flat: the
        uint8 flat charset table (device-resident) -- consulted ONLY
        for positions whose charset exceeds MAX_SEGMENTS contiguous
        runs (markov-scrambled orders); every builtin charset decodes
        via the baked-in segment mux and ignores it.  lane_offset
        (int32 scalar, may be traced): decode candidates base+offset ..
        base+offset+batch -- the sharded path passes each chip's lane
        range start here.  Returns uint8[batch, length].  jit-traceable;
        radices/offsets/segments are baked in as constants so the
        per-position mod/div/selects lower to cheap int32 vector ops.
        """
        carry = lane_offset + jnp.arange(batch, dtype=jnp.int32)
        cols: list = [None] * self.length
        for p in range(self.length - 1, -1, -1):
            radix = self.radices[p]
            s = base_digits[p] + carry
            idx = s % radix
            segs = self._segments[p]
            if segs is not None:
                cols[p] = segment_mux(idx, segs).astype(jnp.uint8)
            else:
                cols[p] = flat[self._offsets[p] + idx]
            carry = s // radix
        # Lanes that carried past the most-significant digit wrapped around;
        # callers mask them out via the unit's valid-count.
        return jnp.stack(cols, axis=1)

    def __repr__(self) -> str:  # pragma: no cover
        return f"<MaskGenerator {self.mask!r} keyspace={self.keyspace}>"
