from dprf_tpu.generators.base import CandidateGenerator  # noqa: F401
from dprf_tpu.generators.mask import MaskGenerator  # noqa: F401
