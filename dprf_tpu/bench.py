"""Benchmark mode: candidates/sec through the fused crack pipeline.

Measures the exact production path (decode -> pack -> digest -> compare
-> compact) with an unmatchable target, so the number is what a real
job sustains, not a stripped-down kernel.
"""

from __future__ import annotations

import time
from typing import Optional

import jax

from dprf_tpu import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops.pipeline import make_mask_crack_step, target_words


def _publish(result: dict, mode: str) -> dict:
    """Every bench run reports through the SAME registry the runtime
    publishes into (ISSUE 1): a scrape or telemetry snapshot taken
    during/after a bench shows what was measured, at what rate, with
    how much compile time -- machine-checkable, not stdout-only.
    Compile metrics are NOT re-observed here: the compile site itself
    publishes (compile_observer in run_bench / worker warmup), and a
    second observation would double every dprf_compile_seconds count
    and hit/miss counter a report like tools/compile_report.py sums."""
    from dprf_tpu.telemetry import DEFAULT as metrics
    from dprf_tpu.telemetry import perf as perf_mod
    labels = dict(engine=result.get("engine", "?"),
                  impl=result.get("impl", mode),
                  device=result.get("device", "?"), mode=mode)
    metrics.gauge("dprf_bench_rate_hs",
                  "last measured bench rate (or efficiency fraction "
                  "for mode=scaling)",
                  labelnames=("engine", "impl", "device", "mode")
                  ).set(result["value"], **labels)
    metrics.counter("dprf_bench_runs_total", "bench invocations",
                    labelnames=("mode",)).inc(mode=mode)
    if mode == "scaling":
        # multichip accounting: per-chip H/s + scaling efficiency
        # next to the roofline gauge (ISSUE 9)
        perf_mod.publish_scaling(result.get("engine", "?"),
                                 float(result.get("per_chip") or 0.0),
                                 float(result["value"]),
                                 int(result.get("n_devices") or 1),
                                 registry=metrics)
    elif result.get("device") == "tpu":
        # roofline distance is only meaningful on the real chip; the
        # JSON carries the raw fraction, the gauge the smoothed one
        frac = perf_mod.roofline_fraction(result.get("engine", "?"),
                                          result["value"])
        if frac is not None:
            result.setdefault("roofline_frac", round(frac, 4))
            perf_mod.publish_roofline(result["engine"],
                                      result["value"],
                                      registry=metrics)
    return result


def _compile_fields(cache: str, seconds: float, warm_s=None) -> dict:
    """The machine-checkable compile-cost fields every bench result
    carries (ISSUE 3): the classification, the cold-compile cost when
    THIS run paid it, and the warm (cache-served) cost when measured.
    A hit run cannot know its cold cost, so compile_cold_s is None
    there rather than a made-up number.  ONE derivation site: both
    bench modes' JSON must keep the same field contract."""
    out = {"compile_cache": cache,
           "compile_cold_s": (round(seconds, 3)
                              if cache in ("miss", "off") else None),
           "compile_warm_s": (round(seconds, 3)
                              if cache == "hit" else None)}
    if warm_s is not None:
        out["compile_warm_s"] = round(warm_s, 3)
    return out


def _introspection_fields(engine: str, rate: float) -> dict:
    """Device-introspection fields every bench result carries (ISSUE
    13): the run's peak device-memory footprint -- the allocator's
    measured high-water mark where the backend has one, else the
    largest analyzed program footprint, tagged by ``peak_hbm_source``
    -- and the roofline fraction from the XLA-derived op model alone.
    The regression sentinel gates ``peak_hbm_bytes`` alongside
    throughput (perfreport/compare.py); records measured before ISSUE
    13 lack the field and gate as no-baseline, never as a crash."""
    from dprf_tpu.telemetry import devstats
    from dprf_tpu.telemetry import perf as perf_mod
    from dprf_tpu.telemetry import programs as programs_mod
    programs_mod.analyze_pending()    # outside every timed window
    devstats.poll()
    peak, source = devstats.peak_hbm_bytes()
    frac = perf_mod.analyzed_roofline_fraction(engine, rate)
    if frac is None and rate > 0 \
            and perf_mod.ops_per_candidate(engine) is None:
        # roofline-fallback seeding: engines whose optimized HLO
        # reports no flop count (gather/bitwise-only pipelines) and
        # have no hand entry would otherwise publish NO roofline at
        # all -- seed the measured-cost model from this bench's own
        # steady-state rate so the live fleet gets a dprf_roofline_frac
        # gauge (a later profiler capture window overwrites it with a
        # device-attributed measurement)
        perf_mod.record_measured_cost(engine, 1.0 / rate)
    return {"peak_hbm_bytes": peak,
            "peak_hbm_source": source,
            "analyzed_roofline": round(frac, 4) if frac else None}


def _tuned_or(batch, engine: str, device: str, fallback: int,
              attack: str = "mask", extras=None) -> tuple:
    """Bench-side ``--batch auto``: (resolved batch, tuned flag).  An
    explicit integer is pinned; "auto"/None warm-starts from the tuning
    cache written by ``dprf tune`` (environment-validated -- a stale
    entry reads as a miss) and otherwise uses `fallback`.  Every bench
    result carries the flag, so a reported rate is attributable to a
    tuned or a default batch -- machine-checkable, like `fresh`.
    extras: key dimensions beyond (engine, device, attack) -- see
    tune.lookup_tuned_batch."""
    if batch not in (None, "auto"):
        return int(batch), False
    from dprf_tpu.tune import lookup_tuned_batch
    b = lookup_tuned_batch(engine, attack=attack, device=device,
                           extras=extras)
    if b:
        return b, True
    return fallback, False


def calibrated_inner(probe_rate: float, batch: int,
                     target_s: float = 5.0, cap: int = 1 << 20) -> int:
    """Inner-loop length so one dispatch computes ~target_s of work.
    The cap only guards against a nonsense probe; fori_loop length does
    not affect compile time (the loop is not unrolled)."""
    want = max(1, int(probe_rate * target_s / batch))
    return min(cap, 1 << (want.bit_length() - 1))


def make_looped_step(step, inner: int):
    """Wrap a (base_digits, n_valid) crack step in a device-side
    fori_loop of `inner` iterations, returning only two accumulated
    scalars.  One host dispatch then covers inner*batch candidates --
    essential when the host<->device link is high-latency (the axon
    tunnel adds ~0.4 s per round trip, which would otherwise bound the
    measured rate at batch/latency regardless of chip speed).  The base
    digits are perturbed per iteration (the decoders renormalize any
    digit overflow) and both step outputs feed the carry, so XLA can
    neither hoist the body out of the loop nor dead-code the hit
    compaction."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def run(base, nv):
        def body(i, carry):
            c, l = carry
            out = step(base.at[-1].add(i), nv)
            return c + out[0].astype(jnp.int32), \
                l + out[1].sum().astype(jnp.int32)
        return lax.fori_loop(0, inner, body,
                             (jnp.int32(0), jnp.int32(0)))

    return run


def _build_mask_step(engine: str, eng, gen, impl: str, batch: int,
                     fake: bytes) -> tuple:
    """Step selection for run_bench (the same selection a real job
    makes); returns (step, use_pallas, tile-aligned batch).  Factored
    out so a second same-shape build can measure the warm
    (cache-served) compile cost."""
    use_pallas = False
    step = None
    rate = getattr(eng, "_rate", None)
    if rate is not None:
        # keccak family: its own sponge steps (the generic MD
        # pipeline's framing does not apply)
        import numpy as np

        from dprf_tpu.engines.device.sha3 import make_keccak_mask_step
        from dprf_tpu.ops.pallas_keccak import (
            SUBK, keccak_kernel_eligible, make_pallas_keccak_crack_step)
        tw = np.frombuffer(fake, ">u4").astype(np.uint32)
        from dprf_tpu.ops.pallas_mask import pallas_mode
        # auto honors the DPRF_PALLAS kill-switch via pallas_mode()
        kernel_on = (impl == "pallas" or pallas_mode() is not None)
        if (impl != "xla" and kernel_on
                and keccak_kernel_eligible(gen, 1, rate)):
            tile = SUBK * 128
            batch = max(tile, (batch // tile) * tile)
            step = make_pallas_keccak_crack_step(
                gen, tw, batch, eng._pad_byte, rate,
                eng.digest_size)
            use_pallas = True
        elif impl == "pallas":
            raise ValueError(
                "--impl pallas: keccak kernel not eligible -- it "
                "requires a real TPU backend, a mask the "
                "arithmetic charset decode supports, and a "
                f"candidate <= {rate - 1} bytes (rate {rate})")
        else:
            step = make_keccak_mask_step(
                gen, tw, batch, eng._pad_byte, rate=rate,
                out_bytes=eng.digest_size)
    elif impl != "xla":
        from dprf_tpu.ops import pallas_mask
        eligible = pallas_mask.kernel_eligible(engine, gen, 1)
        if impl == "pallas" and not eligible:
            raise ValueError(
                "--impl pallas requires a kernel-capable engine "
                f"({', '.join(sorted(pallas_mask.CORES))}) and a mask "
                "the arithmetic charset decode supports")
        mode = ({"interpret": jax.default_backend() != "tpu"}
                if impl == "pallas" else pallas_mask.pallas_mode())
        if eligible and mode is not None:
            batch = max(pallas_mask.TILE,
                        (batch // pallas_mask.TILE) * pallas_mask.TILE)
            import numpy as np
            dt = "<u4" if eng.little_endian else ">u4"
            step = pallas_mask.make_pallas_mask_crack_step(
                engine, gen,
                np.frombuffer(fake, dtype=dt).astype(np.uint32),
                batch, **mode)
            use_pallas = True
    if step is None:
        step = make_mask_crack_step(
            eng, gen, target_words(fake, eng.little_endian), batch,
            widen_utf16=getattr(eng, "widen_utf16", False))
    return step, use_pallas, batch


def _round_phases(phases: dict) -> dict:
    return {k: round(v, 6) for k, v in phases.items()}


def _step_phases(gen, step, batch: int) -> dict:
    """Per-phase breakdown of ONE per-batch step dispatch with forced
    sync boundaries (the bench-side analogue of the runtime's sampled
    probe, telemetry/perf.py): generate / h2d / device / d2h.  One
    dispatch outside the timed window -- the syncs that make the
    attribution honest must never touch the measured loop."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    t = {}
    t0 = time.perf_counter()
    digits = np.asarray(gen.digits(0), dtype=np.int32)
    t1 = time.perf_counter()
    t["generate"] = t1 - t0
    base = jax.device_put(digits)
    nv = jnp.int32(batch)
    jax.block_until_ready((base, nv))
    t2 = time.perf_counter()
    t["h2d"] = t2 - t1
    out = step(base, nv)
    jax.block_until_ready(out)
    t3 = time.perf_counter()
    t["device"] = t3 - t2
    if isinstance(out, (tuple, list)):
        for x in out:
            np.asarray(x)
    else:
        np.asarray(out)
    t["d2h"] = time.perf_counter() - t3
    return _round_phases(t)


def _timed_aot_compile(fn, *args):
    """Seconds to lower+compile `fn` at these args WITHOUT dispatching
    (None when the step cannot AOT-lower).  With the persistent cache
    populated by the run that just measured, this is the warm compile
    cost a same-shape job pays."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    t0 = time.perf_counter()
    lower(*args).compile()
    return time.perf_counter() - t0


def run_bench(engine: str = "md5", device: str = "jax",
              mask: str = "?a?a?a?a?a?a?a?a", batch="auto",
              seconds: float = 5.0, impl: str = "auto",
              inner: int = 1, log=None) -> dict:
    """impl: "xla" forces the generic fused pipeline, "pallas" forces
    the hand-written kernel (MD5 only), "auto" = pallas on TPU when
    eligible -- the same selection a real job makes.

    batch: an int pins the batch; "auto" (default) consumes the tuning
    cache (`dprf tune`) and falls back to 1<<20.  The result reports
    `tuned` accordingly.

    inner > 1 loops the step on device (see make_looped_step) and is
    the honest way to measure chip throughput over a high-latency
    link; inner = 1 measures the per-dispatch production path."""
    batch, tuned = _tuned_or(batch, engine, device, 1 << 20,
                             extras={"hit_cap": 64})
    gen = MaskGenerator(mask)
    # CPU-oracle path has no jit at all; the jax path overwrites
    compile_fields: dict = {"compile_cache": "off",
                            "compile_cold_s": None,
                            "compile_warm_s": None}
    # An all-0xFF digest can't be produced by these hash functions'
    # outputs for in-keyspace candidates (and a false hit would only add
    # one buffer readback anyway).
    if device == "jax":
        from dprf_tpu import compilecache
        compilecache.enable(log=log)
        eng = get_engine(engine, device="jax")
        fake = bytes([0xFF]) * eng.digest_size
        step, use_pallas, batch = _build_mask_step(engine, eng, gen,
                                                   impl, batch, fake)
        import jax.numpy as jnp

        fn = make_looped_step(step, inner) if inner > 1 else step

        def run_batch(i):
            base = jnp.asarray(gen.digits((i * batch) % max(
                gen.keyspace - batch, 1)), dtype=jnp.int32)
            return fn(base, jnp.int32(batch))

        from dprf_tpu.compilecache import compile_observer
        from dprf_tpu.utils.sync import hard_sync

        # Warmup / compile -- observed, classified hit/miss/off against
        # the persistent compilation cache.  Argument materialization
        # happens before the observer opens (it can write tiny cache
        # entries of its own).
        base0 = jnp.asarray(gen.digits(0), dtype=jnp.int32)
        t0 = time.perf_counter()
        with compile_observer(engine) as obs:
            hard_sync(fn(base0, jnp.int32(batch)))
        compile_s = time.perf_counter() - t0
        # Warm cost: a second same-shape build now loads the cached
        # executable; AOT (no dispatch), so the field is pure compile.
        warm_s = None
        if compilecache.enabled():
            step2, _, _ = _build_mask_step(engine, eng, gen, impl,
                                           batch, fake)
            fn2 = make_looped_step(step2, inner) if inner > 1 else step2
            warm_s = _timed_aot_compile(fn2, base0, jnp.int32(batch))
        compile_fields = _compile_fields(obs.cache, obs.seconds, warm_s)
        # program-registry capture (ISSUE 13): bench compiles outside
        # the worker factories, so it registers its step itself;
        # analysis runs in _introspection_fields after the timed loop
        from dprf_tpu.telemetry import programs as programs_mod
        programs_mod.register_program(engine, "mask", batch, step=step,
                                      args=(base0, jnp.int32(batch)))
        # per-phase attribution of one production dispatch (outside
        # the timed window; the step is already compiled)
        phases = _step_phases(gen, step, batch)
        if log:
            log.info("bench compiled", seconds=f"{compile_s:.1f}",
                     cache=obs.cache)
        # Timed with BOUNDED queue depth, synced by hard_sync (NOT
        # block_until_ready, which over the axon tunnel returns at
        # enqueue -- see utils/sync.py) so the wall-time window
        # reflects sustained throughput rather than enqueue speed (an
        # unbounded async queue over a slow link once enqueued 16k
        # batches in 10 s and drained for 108 min; the enqueue-speed
        # bug measured 1,671 "dispatches" in a 0.5 s window).
        # hard_sync also materializes real bytes, so a backend that
        # died mid-run cannot complete dispatches instantly with
        # poisoned buffers (once inflated a measurement to 1.3e15 H/s).
        n, t0 = 0, time.perf_counter()
        depth = 1 if inner > 1 else 8
        while time.perf_counter() - t0 < seconds:
            last = None
            for _ in range(depth):
                last = run_batch(n)
                n += 1
            hard_sync(last)
        elapsed = time.perf_counter() - t0
    else:
        eng = get_engine(engine, device="cpu")
        n, elapsed = 0, 0.0
        chunk = min(batch, 1 << 14)
        # coarse phase split for the oracle path: generation vs
        # hashing of one chunk (no device, so no h2d/d2h)
        tp = time.perf_counter()
        cands = [c for c in gen.candidates(0, chunk) if c is not None]
        tg = time.perf_counter()
        eng.hash_batch(cands)
        phases = _round_phases({"generate": tg - tp,
                                "device": time.perf_counter() - tg})
        # fresh candidates per iteration: a real job pays generation too,
        # and re-hashing one hot-cached chunk would inflate the number
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            start = (n * chunk) % max(gen.keyspace - chunk, 1)
            eng.hash_batch(gen.candidates(start, chunk))
            n += 1
        elapsed = time.perf_counter() - t0
        batch = chunk
        compile_s = 0.0
        use_pallas = False

    rate = n * batch * max(1, inner if device == "jax" else 1) / elapsed
    platform = jax.devices()[0].platform if device == "jax" else "cpu"
    return _publish({
        "metric": f"{engine} candidates/sec/chip",
        "value": rate,
        "unit": "H/s",
        "engine": engine,
        "impl": "pallas" if use_pallas else "xla",
        "device": platform,
        "mask": mask,
        "batch": batch,
        "tuned": tuned,
        "batches": n,
        "inner": inner,
        "elapsed_s": round(elapsed, 3),
        "compile_s": round(compile_s, 1),
        "phases": phases,
        **compile_fields,
        **_introspection_fields(engine, rate),
    }, mode="bench")


def run_targets_sweep(engine: str = "md5", mask: str = "?a?a?a?a?a?a",
                      sizes=(1_000, 10_000, 100_000, 1_000_000),
                      batch="auto", seconds: float = 3.0,
                      log=None) -> dict:
    """Target-set-size sweep through the probe-table step (ISSUE 16):
    the per-candidate cost of cracking against N digests must stay
    FLAT as N grows 10^3 -> 10^6 (10^7-ready on real silicon -- the
    sizes knob; the CPU backend caps at 10^6 to keep CI honest).

    Each size builds its device-resident probe table (blocked Bloom +
    sorted exact-verify buckets, dprf_tpu/targets/probe.py) from
    synthetic unmatchable digests and times the SAME fused mask step
    a real bulk job dispatches.  ``value`` is the H/s at the LARGEST
    size, so the gated trajectory number dips if the table ever stops
    being O(1) per candidate; ``flat_ratio`` (cost at max N / cost at
    min N) is the direct flatness assertion CI checks against 1.3x.
    """
    import jax.numpy as jnp
    import numpy as np

    from dprf_tpu import compilecache
    from dprf_tpu.compilecache import compile_observer
    from dprf_tpu.targets import build_probe_table
    from dprf_tpu.telemetry import programs as programs_mod
    from dprf_tpu.utils.sync import hard_sync

    batch, tuned = _tuned_or(batch, engine, "jax", 1 << 18,
                             extras={"hit_cap": 64})
    compilecache.enable(log=log)
    gen = MaskGenerator(mask)
    eng = get_engine(engine, device="jax")
    sizes = sorted(int(s) for s in sizes)
    rng = np.random.default_rng(0x7A17)

    per_size = []
    compile_fields: dict = {}
    for n_targets in sizes:
        # synthetic random digests: unmatchable in practice, and the
        # probe step's cost does not depend on whether probes hit
        words = rng.integers(0, 2**32, size=(n_targets,
                                             eng.digest_size // 4),
                             dtype=np.uint32)
        digests = [w.tobytes() for w in words]
        ptable = build_probe_table(
            digests, little_endian=eng.little_endian, log=log)
        step = make_mask_crack_step(
            eng, gen, ptable, batch,
            widen_utf16=getattr(eng, "widen_utf16", False))
        base0 = jnp.asarray(gen.digits(0), dtype=jnp.int32)
        t0 = time.perf_counter()
        with compile_observer(engine) as obs:
            hard_sync(step(base0, jnp.int32(batch)))
        compile_s = time.perf_counter() - t0
        if n_targets == sizes[-1]:
            # registry capture for the largest table's program (the
            # one a 10^6-target job runs); analysis happens in
            # _introspection_fields after the timed windows
            programs_mod.register_program(
                engine, "mask+probe", batch, step=step,
                args=(base0, jnp.int32(batch)))
            compile_fields = _compile_fields(obs.cache, obs.seconds)
        if log:
            log.info("targets sweep compiled", targets=n_targets,
                     mode=ptable.mode, table_mb=round(
                         ptable.nbytes / 2**20, 3),
                     seconds=f"{compile_s:.1f}", cache=obs.cache)
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            last = None
            for _ in range(8):       # bounded queue depth
                base = jnp.asarray(gen.digits(
                    (n * batch) % max(gen.keyspace - batch, 1)),
                    dtype=jnp.int32)
                last = step(base, jnp.int32(batch))
                n += 1
            hard_sync(last)
        elapsed = time.perf_counter() - t0
        rate = n * batch / elapsed
        per_size.append({
            "targets": n_targets,
            "rate_hs": rate,
            "s_per_cand": 1.0 / rate,
            "mode": ptable.mode,
            "table_bytes": ptable.nbytes,
            "fp_est": ptable.fp_est,
            "compile_s": round(compile_s, 1),
        })

    flat_ratio = (per_size[-1]["s_per_cand"]
                  / per_size[0]["s_per_cand"])
    rate_max = per_size[-1]["rate_hs"]
    platform = jax.devices()[0].platform
    return _publish({
        "metric": (f"{engine} probe-table H/s at "
                   f"{sizes[-1]:.0e} targets"),
        "value": rate_max,
        "unit": "H/s",
        "engine": engine,
        "mask": mask,
        "device": platform,
        "batch": batch,
        "tuned": tuned,
        "sizes": sizes,
        "per_size": per_size,
        # per-candidate flatness: the O(1) claim, machine-checkable
        "flat_ratio": round(flat_ratio, 4),
        **compile_fields,
        **_introspection_fields(engine, rate_max),
    }, mode="targets")


def _ttfh_first_hit(order, worker, keyspace: int, unit_size: int):
    """Drive a fresh Dispatcher + worker until the first hit: returns
    (candidates_tried, wall_seconds).  Candidate counting is exact --
    units are leased low-start-first, and the hit's position within
    its unit comes back through the order's own point map, so the
    number measures the DISPATCH order, not the sweep chunking."""
    from dprf_tpu.runtime.dispatcher import Dispatcher
    from dprf_tpu.runtime.worker import submit_or_process

    disp = Dispatcher(keyspace, unit_size, order=order)
    tested = 0
    t0 = time.perf_counter()
    while True:
        unit = disp.lease()
        if unit is None:
            raise RuntimeError(
                "ttfh: keyspace exhausted without a hit -- planted "
                "targets unreachable (bijection or oracle broken)")
        hits = submit_or_process(worker, unit).resolve()
        disp.complete(unit.unit_id)
        if hits:
            pos = min((order.index_to_rank(h.cand_index)
                       if order is not None else h.cand_index)
                      for h in hits) - unit.start
            return tested + pos + 1, time.perf_counter() - t0
        tested += unit.length


def _ttfh_steady_rate(worker, start: int, n_units: int,
                      unit_size: int) -> float:
    """Equal-work steady-state H/s: sweep n_units fixed spans (no
    early exit) through the worker's process path.  Ordered and
    linear runs get the SAME numeric spans, so the delta is exactly
    the rank->index decode + run-decomposition overhead."""
    from dprf_tpu.runtime.worker import submit_or_process
    from dprf_tpu.runtime.workunit import WorkUnit

    t0 = time.perf_counter()
    for u in range(n_units):
        submit_or_process(worker, WorkUnit(
            -(u + 1), start + u * unit_size, unit_size)).resolve()
    return n_units * unit_size / (time.perf_counter() - t0)


def run_ttfh(engine: str = "md5", mask: str = "?a?a?a?a?a?a?a?a",
             plants: int = 4, split: int = 2, log=None) -> dict:
    """Time-to-first-hit: rank-ordered vs linear dispatch (ISSUE 20).

    Plants passwords at KNOWN Markov ranks -- prefix digit vectors
    with a small frequency-level sum but a nonzero leading level,
    the shape real passwords take once charsets are frequency-
    reordered (probable everywhere, top-probable nowhere) -- then
    cracks the same job twice through the real Dispatcher + oracle
    worker path: once leasing low RANKS first (MarkovOrder +
    OrderedWorker), once in plain index order.  ``value`` is the
    candidates-to-first-hit SPEEDUP (linear / ordered, higher
    better); ``penalty`` is the steady-state H/s cost of rank
    decoding, from equal-work sweeps over a mid-rank region (where
    blocks scatter in index space -- near rank 0 the runs coalesce
    and would flatter the decode).  CPU-oracle by design: the
    ordering win is a dispatch property, not a backend property, so
    CI gates it without silicon.
    """
    from dprf_tpu.generators.order import MarkovOrder
    from dprf_tpu.runtime.worker import CpuWorker, OrderedWorker

    oracle = get_engine(engine, device="cpu")
    if oracle.salted:
        raise ValueError(
            "ttfh bench plants bare digests; use an unsalted engine")
    gen = MaskGenerator(mask)
    if gen.keyspace > (1 << 25) or len(gen.radices) <= split:
        # the linear sweep must REACH its first hit in CI time: the
        # bench-wide ?a^8 default is a device-scale keyspace, so the
        # ttfh mode substitutes an oracle-scale mask
        mask = "?l?l?l?l?l"
        gen = MaskGenerator(mask)
        if log:
            log.info("ttfh: substituting oracle-scale mask", mask=mask)
    order = MarkovOrder(gen.radices, split=split)
    block = order.block
    r1 = gen.radices[1] if split > 1 else 1

    # plants: leading level 1+i (never 0 -- a level-0 start is found
    # instantly in BOTH orders), small second level, low suffix
    # offset.  Known ranks by construction: plant 0 sits in prefix
    # block 2 of rank order but block 1*r1 of index order.
    plants = max(1, min(int(plants), 8))
    plant_indices = []
    for i in range(plants):
        d0 = min(1 + i, gen.radices[0] - 1)
        d1 = (3 * i) % min(4, r1) if split > 1 else 0
        pidx = d0 * r1 + d1 if split > 1 else d0
        for r in gen.radices[2:split]:
            pidx *= r
        plant_indices.append(pidx * block + (1237 * (i + 1)) % block)
    plains = [gen.candidate(ix) for ix in plant_indices]
    targets = [oracle.parse_target(d.hex())
               for d in oracle.hash_batch(plains)]

    unit_size = 2 * block
    linear_worker = CpuWorker(oracle, gen, targets)
    ordered_worker = OrderedWorker(CpuWorker(oracle, gen, targets),
                                   order)
    cands_lin, wall_lin = _ttfh_first_hit(None, linear_worker,
                                          gen.keyspace, unit_size)
    cands_ord, wall_ord = _ttfh_first_hit(order, ordered_worker,
                                          gen.keyspace, unit_size)
    speedup = cands_lin / cands_ord
    if log:
        log.info("ttfh first hit", ordered=cands_ord, linear=cands_lin,
                 speedup=f"{speedup:.1f}x")

    steady_units = 6
    steady_start = min(20 * unit_size,
                       gen.keyspace - steady_units * unit_size)
    hs_lin = _ttfh_steady_rate(linear_worker, steady_start,
                               steady_units, unit_size)
    hs_ord = _ttfh_steady_rate(ordered_worker, steady_start,
                               steady_units, unit_size)
    penalty = max(0.0, 1.0 - hs_ord / hs_lin)

    return _publish({
        "metric": (f"{engine} candidates-to-first-hit speedup, "
                   "markov rank order vs linear"),
        "value": round(speedup, 4),
        "unit": "x",
        "engine": engine,
        "mask": mask,
        "device": "cpu",
        "plants": plants,
        "planted": [{"index": ix, "rank": order.index_to_rank(ix)}
                    for ix in plant_indices],
        "split": order.split,
        "block": order.block,
        "unit_size": unit_size,
        "ordered": {"candidates_to_first_hit": cands_ord,
                    "first_hit_s": round(wall_ord, 4),
                    "steady_hs": round(hs_ord, 1)},
        "linear": {"candidates_to_first_hit": cands_lin,
                   "first_hit_s": round(wall_lin, 4),
                   "steady_hs": round(hs_lin, 1)},
        # steady-state H/s cost of rank decoding (acceptance: <0.10)
        "penalty": round(penalty, 4),
    }, mode="ttfh")


def run_scaling(engine: str = "md5", mask: str = "?a?a?a?a?a?a?a?a",
                n_devices: int = 8, batch_per_device="auto",
                seconds: float = 5.0, inner: int = 8,
                impl: str = "auto", ablate: bool = False,
                log=None) -> dict:
    """Scaling-efficiency mode over the ONE sharded runtime
    (parallel/sharded.py): superstep dispatches -- candidates
    generated on device per shard, device-resident hit buffer, one
    collective round per dispatch -- measured three ways:

      * ``rate_ndev``: aggregate H/s of the N-device mesh runtime;
      * ``rate_independent``: aggregate H/s of N INDEPENDENT
        single-device runtimes driven concurrently on the SAME
        devices (the paper's embarrassingly-parallel ideal: no mesh,
        no collectives -- what a HashKitty-style per-node fleet
        would sustain);
      * ``rate_1chip``: one device alone (the classic baseline).

    ``efficiency`` (= ``value``, the gated number and the
    ``dprf_scaling_efficiency`` gauge) is rate_ndev /
    rate_independent: the fraction of embarrassingly-parallel
    throughput the single sharded runtime sustains.  On isolated real
    chips the independent baseline IS ``N * rate_1chip``, so this
    reduces to the classic rate_N / (N * rate_1); on a VIRTUAL
    (shared-core) mesh the independent baseline contends for the same
    host cores the mesh does, so the ratio isolates the runtime's
    sharding overhead from core contention.  The classic unloaded
    ratio still rides along as ``efficiency_strict`` (meaningless on
    a virtual mesh, where it is bounded by cores/N; the note says
    so).

    ``inner`` batches fuse into each superstep dispatch (1 = the
    per-batch compat program).  The per-dispatch phase split rides
    along as ``phases``: with on-device generation, ``h2d`` is one
    digit vector per window and its share should read ~0.

    ``impl``: "xla" pins the generic sharded pipeline, "pallas" pins
    the fused Pallas shard-compute (kernel bodies generate + hash +
    compare per shard -- parallel/sharded.make_sharded_kernel_mask_step),
    "auto" takes the kernel when this backend/engine is eligible.
    ``ablate`` adds a per-batch (inner=1) mesh window after the main
    measurement and reports ``superstep_speedup`` -- the ISSUE 18
    dispatch-fusion ablation, measured on the same devices in the same
    process.
    """
    import jax
    import jax.numpy as jnp

    from dprf_tpu.ops import pallas_mask
    from dprf_tpu.parallel.mesh import make_mesh
    from dprf_tpu.parallel.sharded import (make_sharded_kernel_mask_step,
                                           make_sharded_mask_step)

    batch_per_device, tuned = _tuned_or(batch_per_device, engine, "jax",
                                        1 << 20,
                                        extras={"hit_cap": 64})
    from dprf_tpu import compilecache
    compilecache.enable(log=log)
    gen = MaskGenerator(mask)
    eng = get_engine(engine, device="jax")
    fake = bytes([0xFF]) * eng.digest_size   # unmatchable (see run_bench)
    tgt = target_words(fake, eng.little_endian)
    devices = jax.devices()
    if len(devices) < n_devices:
        raise ValueError(f"requested {n_devices} devices, only "
                         f"{len(devices)} present")
    inner = max(1, int(inner))
    widen = getattr(eng, "widen_utf16", False)

    kmode = pallas_mask.pallas_mode()
    eligible = (kmode is not None and engine in pallas_mask.CORES
                and pallas_mask.kernel_eligible(engine, gen, 1))
    if impl == "pallas" and not eligible:
        raise ValueError(
            "--impl pallas: sharded kernel compute not available here "
            "(needs a kernel-capable engine and DPRF_PALLAS on/auto-TPU)")
    use_kernel = impl == "pallas" or (impl == "auto" and eligible)
    if use_kernel:
        # shard batches are tile-quantized on the kernel path
        tile = pallas_mask.SUB * 128
        batch_per_device = max(tile,
                               (batch_per_device // tile) * tile)

    from dprf_tpu.utils.sync import hard_sync

    def build(devs, inner_n=None):
        inner_n = inner if inner_n is None else inner_n
        m = make_mesh(devices=list(devs))
        if use_kernel:
            step = make_sharded_kernel_mask_step(
                engine, gen, tgt, m, batch_per_device,
                interpret=bool(kmode.get("interpret", False)))
        else:
            step = make_sharded_mask_step(
                eng, gen, tgt, m, batch_per_device, widen_utf16=widen)
        fn = step.superstep(inner_n) if inner_n > 1 else step
        return fn, step.super_batch * inner_n

    def dispatch(fn, span, k):
        base = jnp.asarray(
            gen.digits((k * span) % max(gen.keyspace - span, 1)),
            dtype=jnp.int32)
        return fn(base, jnp.int32(span))

    def warm(builds, label: str) -> float:
        t0 = time.perf_counter()
        for fn, span in builds:
            hard_sync(dispatch(fn, span, 0))
        compile_s = time.perf_counter() - t0
        if log:
            log.info("scaling bench compiled", what=label,
                     runtimes=len(builds), seconds=f"{compile_s:.1f}")
        return compile_s

    def window(builds, budget: float) -> tuple:
        """One timed window: (candidates swept, elapsed seconds)."""
        k, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < budget:
            lasts = None
            for _ in range(2):       # bounded queue depth per stream
                lasts = [dispatch(fn, span, k) for fn, span in builds]
                k += 1
            for r in lasts:
                hard_sync(r)
        return (k * sum(span for _, span in builds),
                time.perf_counter() - t0)

    mesh_build = build(devices[:n_devices])
    solo_builds = [build([d]) for d in devices[:n_devices]]
    compile_mesh = warm([mesh_build], "mesh")
    compile_ind = warm(solo_builds, "independent")
    # program-registry capture of the mesh program (ISSUE 13); the
    # lower() is a cached trace after warm(), analysis runs after the
    # timed windows in _introspection_fields
    from dprf_tpu.telemetry import programs as programs_mod
    programs_mod.register_program(
        engine, "mask+sharded", mesh_build[1], step=mesh_build[0],
        args=(jnp.asarray(gen.digits(0), dtype=jnp.int32),
              jnp.int32(mesh_build[1])))
    # the mesh and independent windows ALTERNATE (3 rounds each) so
    # slow drift on the host -- thermal throttling, background load on
    # a shared box -- hits both sides of the efficiency ratio equally
    # instead of whichever happened to run second
    totals = {"mesh": [0.0, 0.0], "independent": [0.0, 0.0]}
    budget = max(0.5, seconds / 3.0)
    for _ in range(3):
        for label, builds in (("mesh", [mesh_build]),
                              ("independent", solo_builds)):
            w, t = window(builds, budget)
            totals[label][0] += w
            totals[label][1] += t
    many = {"rate": totals["mesh"][0] / totals["mesh"][1],
            "compile_s": round(compile_mesh, 1)}
    independent = {"rate": (totals["independent"][0]
                            / totals["independent"][1]),
                   "compile_s": round(compile_ind, 1)}
    w, t = window(solo_builds[:1], budget)
    one = {"rate": w / t}
    # superstep-vs-per-batch ablation (same devices, same process):
    # the fusion win of draining `inner` batches per collective round
    perbatch_rate = None
    if ablate and inner > 1:
        pb_build = build(devices[:n_devices], inner_n=1)
        warm([pb_build], "per-batch")
        w, t = window([pb_build], budget)
        perbatch_rate = w / t
    # per-dispatch phase attribution of the mesh runtime (outside the
    # timed windows, compiled already): with on-device generation the
    # h2d phase is one tiny digit-vector transfer per window
    phases = _step_phases(gen, mesh_build[0], mesh_build[1])
    total_s = sum(phases.values()) or 1.0

    platform = jax.devices()[0].platform
    eff_raw = many["rate"] / independent["rate"] if independent["rate"] \
        else 0.0
    # efficiency is a fraction of the ideal by definition: a raw ratio
    # above 1 means the INDEPENDENT baseline paid overhead the mesh
    # avoided (e.g. 8 oversubscribed dispatch streams on a shared-core
    # virtual mesh), not superlinear scaling -- clamp the gated value
    # so the committed trajectory stays comparable round to round, and
    # keep the raw ratio alongside.
    eff = min(1.0, eff_raw)
    out = {
        "metric": f"{engine} scaling efficiency 1->{n_devices}",
        "value": eff,
        "unit": "fraction",
        "engine": engine,
        "mask": mask,
        "n_devices": n_devices,
        "batch_per_device": batch_per_device,
        "tuned": tuned,
        "inner": inner,
        "superstep": inner > 1,
        "impl": "pallas" if use_kernel else "xla",
        "baseline": "independent",
        "rate_1chip": one["rate"],
        "rate_ndev": many["rate"],
        "rate_independent": independent["rate"],
        "per_chip": many["rate"] / n_devices,
        "efficiency": eff,
        "efficiency_raw": eff_raw,
        "efficiency_strict": (many["rate"] / (n_devices * one["rate"])
                              if one["rate"] else 0.0),
        "phases": phases,
        "h2d_share": round(phases.get("h2d", 0.0) / total_s, 6),
        "device": platform,
        # roofline is a PER-CHIP quantity: the aggregate mesh rate
        # against the single-chip ceiling would read ~n_devices-fold
        # over unity
        **_introspection_fields(engine, many["rate"] / n_devices),
    }
    if perbatch_rate:
        out["rate_ndev_perbatch"] = perbatch_rate
        out["superstep_speedup"] = round(many["rate"] / perbatch_rate, 4)
    if platform != "tpu":
        out["note"] = (
            "virtual CPU mesh: the 'devices' share the host cores, so "
            "efficiency_strict is bounded by cores/N and only the "
            "independent-baseline efficiency (the contention-fair "
            "form of the same ratio) is meaningful off-TPU")
    return _publish(out, mode="scaling")


# ---------------------------------------------------------------------------
# the five BASELINE.json acceptance workloads, measured through the
# REAL worker paths (engine.make_*_worker + worker.process), so the
# number includes candidate generation, compare, and hit readback --
# what a job sustains, not a stripped kernel.

def _unmatchable(engine) -> str:
    """A parseable target line no in-keyspace candidate can produce."""
    return "ff" * engine.digest_size


def _fake_bcrypt_line(cost: int) -> str:
    from dprf_tpu.engines.cpu.bcrypt import b64_encode
    salt = bytes(range(16))
    digest = bytes((7 * i + 3) % 256 for i in range(23))
    return (f"$2b${cost:02d}$" + b64_encode(salt)[:22]
            + b64_encode(digest)[:31])


def _fake_pmkid_line() -> str:
    pmkid = bytes((5 * i + 1) % 256 for i in range(16))
    return f"{pmkid.hex()}*0a1b2c3d4e5f*a0b1c2d3e4f5*{b'benchnet'.hex()}"


def _synthetic_words(n: int, length: int = 8) -> list:
    """Deterministic pseudo-wordlist (no RNG, no file I/O)."""
    alpha = b"abcdefghijklmnopqrstuvwxyz"
    out = []
    x = 12345
    for _ in range(n):
        x = (1103515245 * x + 12345) & 0x7FFFFFFF
        out.append(bytes(alpha[(x >> (3 * j)) % 26] for j in range(length)))
    return out


def _config_job(n: int, bcrypt_cost: int):
    """config number -> (engine_name, attack, generator, target lines)."""
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import load_rules

    if n == 1:     # MD5 single-hash, 6-char lowercase mask
        return "md5", "mask", MaskGenerator("?l?l?l?l?l?l"), None
    if n == 2:     # NTLM 1k-hash list, 7-char ?a mask, multi-target
        lines = ["%032x" % ((0x9E3779B97F4A7C15 * (i + 1)) & ((1 << 128) - 1))
                 for i in range(1000)]
        return "ntlm", "mask", MaskGenerator("?a?a?a?a?a?a?a"), lines
    if n == 3:     # SHA-256 wordlist + best64, on-device rule expansion
        # 1M words x 64 rules = a 67M keyspace, big enough that a
        # multi-stride unit amortizes link latency (see unit_strides).
        # max_len 24 is the MINIMUM that keeps every best64 expansion
        # of the 8-byte words identical to the 55-byte default
        # (computed against rules/cpu.py: two rules grow to 24 bytes
        # mid-rule before truncating) while keeping per-position rule
        # cost proportional to real candidate lengths.
        gen = WordlistRulesGenerator(_synthetic_words(1 << 20),
                                     load_rules("best64"), max_len=24)
        return "sha256", "wordlist", gen, None
    if n == 4:     # bcrypt wordlist, memory-hard path
        gen = WordlistRulesGenerator(_synthetic_words(1 << 12))
        return "bcrypt", "wordlist", gen, [_fake_bcrypt_line(bcrypt_cost)]
    if n == 5:     # WPA2-PMKID iterated-KDF sweep (8-char passphrases)
        return "wpa2-pmkid", "mask", MaskGenerator("?l?l?l?l?l?l?l?l"), \
            [_fake_pmkid_line()]
    raise ValueError(f"unknown config {n} (1-5)")


def run_config(config: int, device: str = "jax", seconds: float = 5.0,
               batch="auto", bcrypt_cost: int = 12,
               unit_strides: int = 1, log=None) -> dict:
    """Measure one acceptance workload end to end.  Returns the same
    JSON shape as run_bench, plus the config number.

    unit_strides: worker batches per WorkUnit.  Real jobs get units
    from the Dispatcher that span MANY device batches, and the worker
    pipelines their dispatches before reading hits back -- so over a
    high-latency link a one-stride unit measures the round trip, not
    the chip.  Pass enough strides for a few seconds of compute per
    process() call to reproduce the production shape."""
    import time as _time

    from dprf_tpu.runtime.worker import CpuWorker
    from dprf_tpu.runtime.workunit import WorkUnit

    engine_name, attack, gen, lines = _config_job(config, bcrypt_cost)
    batch, tuned = _tuned_or(batch, engine_name, device, 1 << 18,
                             attack=attack,
                             extras={"hit_cap": 64,
                                     **({"rules_n": gen.n_rules}
                                        if attack == "wordlist" else {})})
    oracle = get_engine(engine_name, device="cpu")
    targets = [oracle.parse_target(s)
               for s in (lines or [_unmatchable(oracle)])]
    from dprf_tpu import compilecache
    if device == "jax":
        compilecache.enable(log=log)
        eng = get_engine(engine_name, device="jax")
        maker = ("make_mask_worker" if attack == "mask"
                 else "make_wordlist_worker")
        worker = getattr(eng, maker)(gen, targets, batch=batch,
                                     hit_capacity=64, oracle=oracle)
        stride = worker.stride
    else:
        worker = CpuWorker(oracle, gen, targets)
        stride = min(1 << 12, gen.keyspace)

    unit_len = stride * max(1, unit_strides)
    # warmup/compile on a FULL unit so the super-step program (workers
    # fuse many batches into one dispatch for multi-stride units) is
    # compiled outside the timed window, not inside it.  Device workers
    # warm their per-batch step FIRST (a zero-work dispatch through the
    # observer gives a clean hit/miss classification); the full-unit
    # prime is then classified by cache-entry delta alone -- its wall
    # time is mostly real hashing, which must not read as a cold
    # compile.  The CPU-oracle path has no jit at all: always "off".
    t0 = _time.perf_counter()
    if device == "jax":
        if not getattr(worker, "_warmed", False):
            worker.warmup()
        before = compilecache.entry_count()
        worker.process(WorkUnit(-1, 0, min(unit_len, gen.keyspace)))
        prime = compilecache.classify_delta(before,
                                            compilecache.entry_count())
        # any cold compile anywhere in the fixed cost -- step warmup or
        # super/wide program build during the prime -- means this run
        # paid one
        wc = getattr(worker, "compile_cache", "off")
        compile_cache = "miss" if "miss" in (wc, prime) else wc
    else:
        worker.process(WorkUnit(-1, 0, min(unit_len, gen.keyspace)))
        compile_cache = "off"
    compile_s = _time.perf_counter() - t0
    if log:
        log.info("config compiled", config=config,
                 seconds=f"{compile_s:.1f}", cache=compile_cache)

    # per-phase attribution of one stride through the REAL worker
    # (telemetry/perf.py probe; outside the timed window, compiled
    # already) -- bench JSON carries the breakdown
    from dprf_tpu.telemetry.perf import probe_phases
    phases = _round_phases(probe_phases(
        worker, WorkUnit(-1, 0, min(stride, gen.keyspace))))

    from dprf_tpu.runtime.worker import submit_or_process

    tested = 0
    start = 0
    pending: list = []
    t0 = _time.perf_counter()
    # depth-2 submit/resolve pipeline -- the production Coordinator
    # shape -- so a unit's flag readback overlaps the next unit's
    # compute instead of serializing with it.
    # Always submit FULL-size units (wrapping to 0 early rather than
    # issuing a keyspace-tail remnant): an odd-sized tail unit would
    # pick super-step inner sizes the warmup never compiled, putting a
    # multi-second jit inside the timed window.
    length = min(unit_len, gen.keyspace)
    while True:
        in_window = _time.perf_counter() - t0 < seconds
        if in_window:
            if gen.keyspace - start < length:
                start = 0
            pending.append((length, submit_or_process(
                worker, WorkUnit(-1, start, length))))
            start += length
        if not pending:
            break
        if len(pending) >= 2 or not in_window:
            ulen, p = pending.pop(0)
            p.resolve()
            tested += ulen
    elapsed = _time.perf_counter() - t0

    import jax as _jax
    platform = (_jax.devices()[0].platform if device == "jax" else "cpu")
    return _publish({
        "metric": f"config{config} {engine_name} candidates/sec/chip",
        "value": tested / elapsed,
        "unit": "H/s",
        "config": config,
        "engine": engine_name,
        "attack": attack,
        "targets": len(targets),
        "device": platform,
        "batch": batch,
        "tuned": tuned,
        "unit_strides": max(1, unit_strides),
        "tested": tested,
        "elapsed_s": round(elapsed, 3),
        "compile_s": round(compile_s, 1),
        "phases": phases,
        **_compile_fields(compile_cache, compile_s),
        **_introspection_fields(engine_name, tested / elapsed),
    }, mode="config")
