"""Benchmark mode: candidates/sec through the fused crack pipeline.

Measures the exact production path (decode -> pack -> digest -> compare
-> compact) with an unmatchable target, so the number is what a real
job sustains, not a stripped-down kernel.
"""

from __future__ import annotations

import time
from typing import Optional

import jax

from dprf_tpu import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops.pipeline import make_mask_crack_step, target_words


def run_bench(engine: str = "md5", device: str = "jax",
              mask: str = "?a?a?a?a?a?a?a?a", batch: int = 1 << 20,
              seconds: float = 5.0, impl: str = "auto", log=None) -> dict:
    """impl: "xla" forces the generic fused pipeline, "pallas" forces
    the hand-written kernel (MD5 only), "auto" = pallas on TPU when
    eligible -- the same selection a real job makes."""
    gen = MaskGenerator(mask)
    # An all-0xFF digest can't be produced by these hash functions'
    # outputs for in-keyspace candidates (and a false hit would only add
    # one buffer readback anyway).
    if device == "jax":
        eng = get_engine(engine, device="jax")
        fake = bytes([0xFF]) * eng.digest_size
        use_pallas = False
        if impl != "xla":
            from dprf_tpu.ops import pallas_mask
            eligible = pallas_mask.kernel_eligible(engine, gen, 1)
            if impl == "pallas" and not eligible:
                raise ValueError(
                    "--impl pallas requires a kernel-capable engine "
                    f"({', '.join(sorted(pallas_mask.CORES))}) and a mask "
                    "the arithmetic charset decode supports")
            mode = ({"interpret": jax.default_backend() != "tpu"}
                    if impl == "pallas" else pallas_mask.pallas_mode())
            if eligible and mode is not None:
                batch = max(pallas_mask.TILE,
                            (batch // pallas_mask.TILE) * pallas_mask.TILE)
                import numpy as np
                dt = "<u4" if eng.little_endian else ">u4"
                step = pallas_mask.make_pallas_mask_crack_step(
                    engine, gen,
                    np.frombuffer(fake, dtype=dt).astype(np.uint32),
                    batch, **mode)
                use_pallas = True
        if not use_pallas:
            step = make_mask_crack_step(
                eng, gen, target_words(fake, eng.little_endian), batch,
                widen_utf16=getattr(eng, "widen_utf16", False))
        import jax.numpy as jnp

        def run_batch(i):
            base = jnp.asarray(gen.digits((i * batch) % max(
                gen.keyspace - batch, 1)), dtype=jnp.int32)
            return step(base, jnp.int32(batch))

        # Warmup / compile
        t0 = time.perf_counter()
        jax.block_until_ready(run_batch(0))
        compile_s = time.perf_counter() - t0
        if log:
            log.info("bench compiled", seconds=f"{compile_s:.1f}")
        # Timed: queue batches asynchronously, sync once at the end.
        n, t0 = 0, time.perf_counter()
        last = None
        while time.perf_counter() - t0 < seconds:
            last = run_batch(n)
            n += 1
        jax.block_until_ready(last)
        elapsed = time.perf_counter() - t0
    else:
        eng = get_engine(engine, device="cpu")
        n, elapsed = 0, 0.0
        chunk = min(batch, 1 << 14)
        cands = gen.candidates(0, chunk)
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            eng.hash_batch(cands)
            n += 1
        elapsed = time.perf_counter() - t0
        batch = chunk
        compile_s = 0.0
        use_pallas = False

    rate = n * batch / elapsed
    platform = jax.devices()[0].platform if device == "jax" else "cpu"
    return {
        "metric": f"{engine} candidates/sec/chip",
        "value": rate,
        "unit": "H/s",
        "engine": engine,
        "impl": "pallas" if use_pallas else "xla",
        "device": platform,
        "mask": mask,
        "batch": batch,
        "batches": n,
        "elapsed_s": round(elapsed, 3),
        "compile_s": round(compile_s, 1),
    }
