"""``dprf check``: the unified static-analysis suite (ISSUE 6, made
interprocedural in ISSUE 7).

One runner, nine analyzers, zero runtime dependencies -- the layer
that turns this repo's recurring concurrent/protocol/config bug
classes into lint failures instead of loopback-test flakes:

  markers           test modules using Pallas/device engines declare a
                    tier marker (absorbed from tools/check_markers.py)
  metrics           every dprf_* metric name declared at exactly one
                    site; every span literal is in SPAN_NAMES
                    (absorbed from tools/check_metrics.py)
  worker-contract   every process() override declares its pipelining
                    stance (absorbed from tools/check_worker_contract)
  locks             lock-discipline / guarded-by race detector over
                    the declared GUARDED_BY tables; blocking calls and
                    lock-order edges propagate through the call graph
                    (analysis/locks.py)
  protocol          RPC request/response contract: the dict keys each
                    op's clients build vs. the handler reads, both
                    directions, followed through helper functions
                    (analysis/protocol.py)
  env-knobs         every DPRF_* env read goes through the
                    utils/env.py registry; README table in sync
                    (analysis/envknobs.py)
  threads           thread join/daemon discipline, socket/file release
                    against module-level RELEASES tables, Condition
                    wait/notify rules (analysis/threads.py)
  retrace           JAX silent-recompile + host-sync lint over the
                    loops declared in HOT_PATHS tables, jit entries
                    resolved through the call graph
                    (analysis/retrace.py)
  coverage-events   every range-mutating site in the
                    COVERAGE_EVENT_SITES manifest calls the coverage
                    ledger event API; event literals in EVENT_NAMES
                    (analysis/coverage_events.py)

The shared interprocedural machinery -- whole-package call graph,
type resolution, per-function summaries, transitive closure -- lives
in analysis/callgraph.py, one instance per AnalysisContext.

Entry points: ``dprf check`` (cli.py), ``python -m dprf_tpu.analysis``,
``run_for_conftest()`` (one in-process pass at the top of every test
tier), and the legacy ``tools/check_*.py`` shims.  ``--explain
<check>`` prints a check's rules and its declaration tables as found
in the repo.

Suppressions are explicit and must carry a reason::

    self.found = x   # dprf: disable=locks -- server not started yet

The comment suppresses the named check(s) on its own line, or on the
next line when it stands alone.  A suppression with no reason, and a
suppression that matches no finding of a check that ran, are both
findings themselves -- stale or lazy suppressions rot into the silent
drift this suite exists to prevent.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize
from typing import Optional

#: suppression comment: ``disable=<checks> -- <reason>`` after a
#: ``dprf:`` marker.  Matched against COMMENT tokens only (tokenize),
#: so documentation showing the syntax inside a string/docstring never
#: trips the scanner.
SUPPRESS_RE = re.compile(
    r"#\s*dprf:\s*disable=([A-Za-z0-9_,-]+)\s*(?:--\s*(\S.*))?")


@dataclasses.dataclass
class Finding:
    check: str
    path: str            # repo-relative
    line: int
    message: str
    suppressed: bool = False
    reason: Optional[str] = None

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.location()}: [{self.check}] {self.message}{tag}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class FileIndex:
    """One walk's worth of per-file AST buckets.  Six analyzers over
    ~190 files re-walking every tree is what blew the first prototype
    past its budget; each file is now walked exactly once and the
    plugins iterate the typed buckets instead."""

    __slots__ = ("calls", "classes", "functions", "subscripts",
                 "assigns", "imports", "compares")

    def __init__(self, tree: ast.AST):
        self.calls: list = []
        self.classes: list = []
        self.functions: list = []
        self.subscripts: list = []
        self.assigns: list = []
        self.imports: list = []
        self.compares: list = []
        # exact-type dispatch: ast nodes are never subclassed, and a
        # dict probe beats a 7-way isinstance chain on ~10^6 nodes
        buckets = {ast.Call: self.calls, ast.ClassDef: self.classes,
                   ast.FunctionDef: self.functions,
                   ast.AsyncFunctionDef: self.functions,
                   ast.Subscript: self.subscripts,
                   ast.Assign: self.assigns,
                   ast.Import: self.imports,
                   ast.ImportFrom: self.imports,
                   ast.Compare: self.compares}
        # hand-rolled walk over node.__dict__ (~30% over ast.walk,
        # whose iter_fields pays a try/except getattr per field)
        AST = ast.AST
        stack = [tree]
        pop = stack.pop
        append = stack.append
        while stack:
            node = pop()
            b = buckets.get(type(node))
            if b is not None:
                b.append(node)
            for v in node.__dict__.values():
                if type(v) is list:
                    for x in v:
                        if isinstance(x, AST):
                            append(x)
                elif isinstance(v, AST):
                    append(v)


class AnalysisContext:
    """Shared parse state for one run: every analyzer reads sources,
    ASTs, and node indexes through the same cache, so a six-analyzer
    pass parses and walks each file once (the <2 s conftest budget,
    the <5 s CLI budget)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.package_dir = os.path.join(self.root, "dprf_tpu")
        self.tests_dir = os.path.join(self.root, "tests")
        self.tools_dir = os.path.join(self.root, "tools")
        self.readme = os.path.join(self.root, "README.md")
        self._sources: dict = {}
        self._trees: dict = {}
        self._indexes: dict = {}
        self.parse_failures: list = []   # [(path, message)]
        self.timings: dict = {}          # check -> seconds (last run)

    # -- file discovery --------------------------------------------------

    def rel(self, path: str) -> str:
        return os.path.relpath(path, self.root)

    def _walk(self, top: str) -> list:
        out = []
        for root, dirs, files in os.walk(top):
            dirs[:] = [d for d in dirs
                       if d != "__pycache__" and not d.startswith(".")]
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
        return out

    def package_files(self) -> list:
        return self._walk(self.package_dir)

    def test_files(self) -> list:
        if not os.path.isdir(self.tests_dir):
            return []
        return self._walk(self.tests_dir)

    def tools_files(self) -> list:
        if not os.path.isdir(self.tools_dir):
            return []
        return self._walk(self.tools_dir)

    def root_files(self) -> list:
        """Top-level driver scripts (bench.py & co) -- shallow."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            if name.endswith(".py"):
                out.append(os.path.join(self.root, name))
        return out

    # -- cached parse ----------------------------------------------------

    def source(self, path: str) -> str:
        src = self._sources.get(path)
        if src is None:
            with open(path, encoding="utf-8") as fh:
                src = self._sources[path] = fh.read()
        return src

    def tree(self, path: str) -> Optional[ast.AST]:
        """Parsed AST, or None on a syntax error (recorded once in
        parse_failures; the runner turns those into findings)."""
        if path in self._trees:
            return self._trees[path]
        try:
            t = ast.parse(self.source(path), filename=path)
        except (SyntaxError, OSError) as e:
            t = None
            self.parse_failures.append((self.rel(path), str(e)))
        self._trees[path] = t
        return t

    def index(self, path: str) -> Optional[FileIndex]:
        """The file's typed node buckets (None on a parse failure)."""
        if path not in self._indexes:
            tree = self.tree(path)
            self._indexes[path] = (FileIndex(tree)
                                   if tree is not None else None)
        return self._indexes[path]


# ---------------------------------------------------------------------------
# plugin registry

def _plugins() -> dict:
    """name -> module (imported lazily so a syntax error in one
    analyzer doesn't take the whole runner down at import time)."""
    from dprf_tpu.analysis import (coverage_events, envknobs, locks,
                                   markers, metrics, protocol,
                                   retrace, threads, worker_contract)
    mods = (markers, metrics, worker_contract, locks, protocol,
            envknobs, threads, retrace, coverage_events)
    return {m.NAME: m for m in mods}


def plugin_names() -> list:
    return list(_plugins())


def describe_plugins() -> list:
    return [(m.NAME, m.DESCRIPTION) for m in _plugins().values()]


def explain(root: str, check: str) -> str:
    """Human-readable rules + live declaration tables for one check
    (``dprf check --explain <check>``) -- the reference to read BEFORE
    writing a suppression or a new declaration.  The rules are the
    analyzer's module docstring; the tables are every module-level
    assignment in the package whose name the analyzer lists in its
    ``DECL_TABLES``, quoted from source with file:line locations."""
    plugins = _plugins()
    if check not in plugins:
        raise ValueError(f"unknown check {check!r} "
                         f"(have: {list(plugins)})")
    mod = plugins[check]
    out = [f"{mod.NAME}: {mod.DESCRIPTION}", ""]
    doc = (mod.__doc__ or "").strip()
    if doc:
        out += [doc, ""]
    tables = getattr(mod, "DECL_TABLES", ())
    if tables:
        ctx = AnalysisContext(root)
        out.append("Declarations in this repo:")
        found = False
        for path in ctx.package_files():
            try:
                src = ctx.source(path)
            except OSError:
                continue
            if not any(t in src for t in tables):
                continue
            tree = ctx.tree(path)
            if tree is None:
                continue
            lines = src.splitlines()
            for node in tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id in tables):
                    continue
                found = True
                out.append(f"\n  {ctx.rel(path)}:{node.lineno}")
                end = getattr(node, "end_lineno", node.lineno)
                for ln in lines[node.lineno - 1:end]:
                    out.append(f"    {ln}")
        if not found:
            out.append(f"  (none yet -- declare "
                       f"{' / '.join(tables)} in a runtime module)")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# suppressions

def _suppressions_for(ctx: AnalysisContext, path: str) -> list:
    """[(lines, {checks}, reason|None, comment_line)] -- the lines
    each suppression comment covers (its own line, plus the next line
    when the comment stands alone).  Only real COMMENT tokens count:
    the syntax shown inside a docstring or string literal is
    documentation, not a suppression."""
    out = []
    try:
        src = ctx.source(path)
    except OSError:
        return out
    if "dprf:" not in src:       # cheap prescan: most files have none
        return out
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(src).readline))
    except (tokenize.TokenError, SyntaxError, ValueError):
        return out               # unparsable files surface elsewhere
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        row, col = tok.start
        checks = {c.strip() for c in m.group(1).split(",") if c.strip()}
        reason = m.group(2)
        reason = reason.strip() if reason else None
        lines = [row]
        if tok.line[:col].strip() == "":
            lines.append(row + 1)   # standalone comment: covers next line
        out.append((lines, checks, reason, row))
    return out


def _apply_suppressions(ctx: AnalysisContext, findings: list,
                        ran: set) -> list:
    """Mark suppressed findings, and append framework findings for
    reasonless or unused suppressions.  Returns the full list."""
    by_path: dict = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    extra = []
    paths = set(by_path)
    # every file any ran check COULD have flagged may hold stale
    # suppressions; restrict the unused-scan to files we parsed (the
    # ones analyzers actually visited) to stay cheap and precise
    paths.update(ctx.rel(p) for p in ctx._sources)
    for rel in sorted(paths):
        abspath = os.path.join(ctx.root, rel)
        if not os.path.exists(abspath):
            continue
        for lines, checks, reason, cline in _suppressions_for(
                ctx, abspath):
            if reason is None:
                extra.append(Finding(
                    "suppression", rel, cline,
                    "suppression without a reason -- write "
                    "`# dprf: disable=<check> -- <why this is safe>`"))
                continue
            used = False
            for f in by_path.get(rel, ()):
                if (f.line in lines and f.check in checks
                        and not f.suppressed):
                    f.suppressed = True
                    f.reason = reason
                    used = True
            if not used and checks & ran:
                extra.append(Finding(
                    "suppression", rel, cline,
                    f"unused suppression for {sorted(checks & ran)} "
                    "-- the finding it silenced is gone; delete it"))
    return findings + extra


# ---------------------------------------------------------------------------
# runner

def run(root: str, only=None, skip=None,
        ctx: Optional[AnalysisContext] = None):
    """Run the selected analyzers; returns (findings, ran) where
    findings is every Finding (suppressed ones marked) and ran is the
    set of check names that executed."""
    plugins = _plugins()
    names = list(plugins)
    if only:
        unknown = set(only) - set(names)
        if unknown:
            raise ValueError(f"unknown checks: {sorted(unknown)} "
                             f"(have: {names})")
        names = [n for n in names if n in set(only)]
    if skip:
        unknown = set(skip) - set(plugins)
        if unknown:
            raise ValueError(f"unknown checks: {sorted(unknown)} "
                             f"(have: {list(plugins)})")
        names = [n for n in names if n not in set(skip)]
    if ctx is None:
        ctx = AnalysisContext(root)
    findings: list = []
    # per-analyzer wall time, exposed on the context (and in the CLI's
    # --json output) so the CI artifact makes budget regressions
    # visible per check, not just as one opaque suite total
    import time as _time
    ctx.timings = {}
    for name in names:
        t0 = _time.perf_counter()
        findings.extend(plugins[name].run(ctx))
        ctx.timings[name] = round(_time.perf_counter() - t0, 4)
    for rel, msg in ctx.parse_failures:
        findings.append(Finding("parse", rel, 1,
                                f"does not parse: {msg}"))
    findings = _apply_suppressions(ctx, findings, set(names))
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings, set(names)


def unsuppressed(findings: list) -> list:
    return [f for f in findings if not f.suppressed]


def run_for_conftest(root: str) -> Optional[str]:
    """One in-process pass over every analyzer (the conftest
    pytest_configure hook); returns a rendered failure message, or
    None when clean."""
    findings, _ = run(root)
    bad = unsuppressed(findings)
    if not bad:
        return None
    return ("dprf check found {n} violation(s):\n  ".format(n=len(bad))
            + "\n  ".join(f.render() for f in bad))


# ---------------------------------------------------------------------------
# CLI (dprf check / python -m dprf_tpu.analysis / tools shims)

def _default_root() -> str:
    # dprf_tpu/analysis/__init__.py -> the repo root two levels up
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def shim_main(check: str, legacy_dir_attr: str) -> int:
    """Entry point for the legacy ``tools/check_*.py`` shims.  The old
    tools took one optional positional directory (the package dir for
    metrics/worker-contract, the tests dir for markers); honor that by
    pointing the context's matching dir at it.  Flag-style argv passes
    straight through to the normal CLI."""
    argv = sys.argv[1:]
    if argv and not argv[0].startswith("-"):
        ctx = AnalysisContext(argv[0])
        setattr(ctx, legacy_dir_attr, ctx.root)
        findings, _ = run(ctx.root, only=[check], ctx=ctx)
        bad = unsuppressed(findings)
        for f in bad:
            print(f.render())
        return 1 if bad else 0
    return main(["--only", check] + argv)


def main(argv: Optional[list] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="dprf check",
        description="static analysis over the dprf_tpu repo")
    p.add_argument("--root", default=None,
                   help="repo root (default: the tree this package "
                   "is installed in)")
    p.add_argument("--only", action="append", default=None,
                   metavar="CHECK", help="run only these checks "
                   "(repeatable, or comma-separated)")
    p.add_argument("--skip", action="append", default=None,
                   metavar="CHECK", help="skip these checks")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--list", action="store_true",
                   help="list available checks and exit")
    p.add_argument("--explain", metavar="CHECK", default=None,
                   help="print one check's rules and its declaration "
                   "tables as found in the repo, then exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by inline "
                   "suppressions")
    p.add_argument("--write-env-docs", action="store_true",
                   help="regenerate the README env-knob table from "
                   "the utils/env.py registry, then run the checks")
    p.add_argument("--fix-skeletons", action="store_true",
                   help="after the checks run, print GUARDED_BY / "
                   "RELEASES declaration skeletons for undeclared "
                   "lock owners and the threads findings' undeclared "
                   "resources (paste-ready; nothing written to disk)")
    args = p.parse_args(argv)

    if args.list:
        for name, desc in describe_plugins():
            print(f"{name:16s} {desc}")
        return 0

    root = os.path.abspath(args.root or _default_root())

    if args.explain:
        try:
            print(explain(root, args.explain))
        except ValueError as e:
            print(f"dprf check: {e}", file=sys.stderr)
            return 2
        return 0

    if args.write_env_docs:
        from dprf_tpu.utils import env
        readme = os.path.join(root, "README.md")
        changed = env.write_readme_table(readme)
        state = "rewritten" if changed else "already in sync"
        print(f"env-knob table {state}: {readme}", file=sys.stderr)

    def _split(vals):
        if not vals:
            return None
        out = []
        for v in vals:
            out.extend(s.strip() for s in v.split(",") if s.strip())
        return out

    ctx = AnalysisContext(root)
    try:
        findings, ran = run(root, only=_split(args.only),
                            skip=_split(args.skip), ctx=ctx)
    except ValueError as e:
        print(f"dprf check: {e}", file=sys.stderr)
        return 2

    if args.fix_skeletons:
        from dprf_tpu.analysis import skeletons
        text = skeletons.render(ctx, findings)
        if text:
            print(text)
        else:
            print("fix-skeletons: every lock owner and acquired "
                  "resource is already declared", file=sys.stderr)

    bad = unsuppressed(findings)
    shown = findings if args.show_suppressed else bad
    if args.json:
        print(json.dumps({
            "root": root,
            "checks": sorted(ran),
            "findings": [f.as_dict() for f in shown],
            "total": len(bad),
            "suppressed": len(findings) - len(bad),
            "timings_s": ctx.timings,
        }, indent=2))
    else:
        for f in shown:
            print(f.render())
        n_sup = len(findings) - len(bad)
        print(f"dprf check: {len(bad)} finding(s), {n_sup} "
              f"suppressed, checks: {', '.join(sorted(ran))}",
              file=sys.stderr)
    return 1 if bad else 0
