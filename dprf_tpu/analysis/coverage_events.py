"""Coverage event-site discipline (ISSUE 19).

The bug class this makes impossible: a refactor moves or adds a code
path that mutates a unit's index range -- a new redrive, a different
resplit, a fresh submit loop -- and forgets to tell the coverage
ledger.  The audit plane then swears coverage is complete while
candidates silently leak.  Rules:

  1. every event-name literal passed to a ``<...>.coverage.event(``
     or ``coverage.note(`` call is a member of
     ``telemetry/coverage.py``'s ``EVENT_NAMES`` tuple (which holds
     no duplicates), and the name argument IS a literal -- a computed
     event name can't be audited statically;
  2. every ``(file, function)`` entry in ``COVERAGE_EVENT_SITES`` --
     the declared manifest of range-mutating sites -- exists, and
     EVERY function definition with that name in that file contains
     at least one event/note call (two classes sharing a method name
     must both report);
  3. the manifest is exhaustive: a package function OUTSIDE
     telemetry/coverage.py that calls the event API but is not
     declared in ``COVERAGE_EVENT_SITES`` is a finding -- new sites
     must be declared, so reviewers see coverage-plane changes in the
     one place ``--explain coverage-events`` renders.
"""

from __future__ import annotations

import ast
import os
import re

from dprf_tpu.analysis import Finding

NAME = "coverage-events"
DESCRIPTION = ("every declared range-mutating site calls the coverage "
               "ledger event API; every event literal is in "
               "EVENT_NAMES; every caller is declared in "
               "COVERAGE_EVENT_SITES")

DECL_TABLES = ("EVENT_NAMES", "COVERAGE_EVENT_SITES")

COVERAGE_REL = os.path.join("telemetry", "coverage.py")

#: parse prefilter: files without event/note call text can't matter
_RELEVANT_RE = re.compile(r"coverage\.event\s*\(|coverage\.note\s*\(|"
                          r"\.event\s*\(")


def _literal(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _tuple_of_str(node):
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = [_literal(e) for e in node.elts]
    return out if all(v is not None for v in out) else None


def _declared(idx):
    """(EVENT_NAMES list | None, COVERAGE_EVENT_SITES list | None)
    from coverage.py's module-level assignments."""
    names = sites = None
    if idx is None:
        return None, None
    for node in idx.assigns:
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id == "EVENT_NAMES":
                names = _tuple_of_str(node.value)
            elif t.id == "COVERAGE_EVENT_SITES":
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    sites = []
                    for elt in node.value.elts:
                        pair = _tuple_of_str(elt)
                        sites.append(tuple(pair)
                                     if pair and len(pair) == 2
                                     else None)
    return names, sites


def _receiver_name(func: ast.Attribute):
    """Trailing name of the call receiver: ``self.coverage.event`` ->
    'coverage', ``coverage.note`` -> 'coverage'."""
    v = func.value
    if isinstance(v, ast.Attribute):
        return v.attr
    if isinstance(v, ast.Name):
        return v.id
    return None


def _event_calls(body) -> list:
    """(event literal | None, lineno) for every ledger/note call in a
    function body, SKIPPING nested defs (a nested function is its own
    site for the manifest check)."""
    out = []
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in ("event", "note")
                    and _receiver_name(f) == "coverage"):
                first = node.args[0] if node.args else None
                out.append((_literal(first), node.lineno))
        for v in ast.iter_child_nodes(node):
            stack.append(v)
    return out


def run(ctx) -> list:
    out = []
    cov_py = os.path.join(ctx.package_dir, COVERAGE_REL)
    if not os.path.exists(cov_py):
        # a tree without the coverage module (fixture repos) has no
        # audit plane to keep honest -- nothing to check
        return out
    cov_rel = ctx.rel(cov_py)
    names, sites = _declared(ctx.index(cov_py))
    if names is None:
        out.append(Finding(
            NAME, cov_rel, 1,
            "EVENT_NAMES literal tuple not found in "
            "telemetry/coverage.py (it must stay a pure tuple of "
            "string literals so this check can read it)"))
        names = []
    elif len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        out.append(Finding(
            NAME, cov_rel, 1,
            f"duplicate EVENT_NAMES entries: {dupes}"))
    if sites is None:
        out.append(Finding(
            NAME, cov_rel, 1,
            "COVERAGE_EVENT_SITES literal tuple not found in "
            "telemetry/coverage.py (the manifest of range-mutating "
            "sites this check enforces)"))
        sites = []
    if any(s is None for s in sites):
        out.append(Finding(
            NAME, cov_rel, 1,
            "COVERAGE_EVENT_SITES entries must be literal "
            "(file, function) string pairs"))
        sites = [s for s in sites if s is not None]
    declared = set(sites)
    allowed = set(names)

    # file -> {function name -> [(def lineno, had_call)]}
    seen_sites: dict = {}
    for path in ctx.package_files():
        try:
            if not _RELEVANT_RE.search(ctx.source(path)):
                continue
        except OSError:
            continue
        rel = ctx.rel(path)
        idx = ctx.index(path)
        if idx is None:
            continue
        for fn in idx.functions:
            calls = _event_calls(fn.body)
            if not calls:
                continue
            seen_sites.setdefault(rel, {}).setdefault(
                fn.name, []).append(fn.lineno)
            for lit, lineno in calls:
                if lit is None:
                    out.append(Finding(
                        NAME, rel, lineno,
                        "coverage event name must be a string "
                        "literal -- a computed name can't be "
                        "statically audited"))
                elif lit not in allowed:
                    out.append(Finding(
                        NAME, rel, lineno,
                        f"coverage event {lit!r} not declared in "
                        "telemetry/coverage.py EVENT_NAMES"))
            # rule 3: the manifest must name every calling site
            if (rel != cov_rel and (rel, fn.name) not in declared):
                out.append(Finding(
                    NAME, rel, fn.lineno,
                    f"function {fn.name!r} calls the coverage event "
                    "API but is not declared in "
                    "COVERAGE_EVENT_SITES -- declare the site in "
                    "telemetry/coverage.py"))

    # rule 2: every declared site exists and every same-named def
    # in that file actually reports
    for file_rel, func in sorted(declared):
        path = os.path.join(ctx.root, file_rel)
        if not os.path.exists(path):
            out.append(Finding(
                NAME, cov_rel, 1,
                f"COVERAGE_EVENT_SITES names missing file "
                f"{file_rel!r}"))
            continue
        idx = ctx.index(path)
        if idx is None:
            continue
        defs = [fn for fn in idx.functions if fn.name == func]
        if not defs:
            out.append(Finding(
                NAME, file_rel, 1,
                f"COVERAGE_EVENT_SITES names {func!r} but no such "
                "function is defined here -- stale manifest entry"))
            continue
        reported = seen_sites.get(file_rel, {}).get(func, [])
        for fn in defs:
            if fn.lineno not in reported:
                out.append(Finding(
                    NAME, file_rel, fn.lineno,
                    f"{func!r} is a declared coverage event site but "
                    "this definition never calls "
                    "coverage.event()/coverage.note() -- a range "
                    "mutation the audit plane cannot see"))
    return out
