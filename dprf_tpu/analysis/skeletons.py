"""``dprf check --fix-skeletons``: declaration skeletons for the
locks/threads analyzers' tables.

The locks analyzer verifies the GUARDED_BY tables a module DECLARES
but stays silent about lock-owning classes that never declared one --
a new class with a ``threading.Lock()`` in ``__init__`` (the
TargetStore ingest layer was the motivating case) silently opts out
of the race detector.  The threads analyzer does raise a finding for
undeclared acquired resources, but leaves writing the table to the
reader.  This emitter closes both gaps mechanically:

* **GUARDED_BY skeletons** -- its own scan: every class assigning a
  ``threading.Lock`` / ``RLock`` / ``Condition`` to an attribute in
  ``__init__`` while no module-level GUARDED_BY entry names the class.
  The guarded-attr tuple is pre-filled with the attributes the class
  actually assigns under ``with self.<lock>:`` blocks (the analyzer's
  own evidence of intent), or left empty with a TODO marker.

* **RELEASES skeletons** -- parsed from the threads findings of the
  run that just completed (the ``... holds an acquired resource but
  is not declared in a module-level RELEASES table`` message), with
  the releaser slot pre-filled when the class has an obvious
  shutdown-shaped method.

Output is paste-ready source grouped per module, on stdout; nothing
is written to disk -- the declarations belong next to the class, and
deciding WHAT a lock guards is still the author's job.  The emitted
skeleton makes the class visible to the analyzers, which then verify
the actual discipline.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

#: threading constructors whose product is a guard the locks analyzer
#: can track (mirrors analysis/locks.py's notion of a lock attr)
LOCK_CTORS = {"Lock", "RLock", "Condition"}

#: method names that look like a class's shutdown path -- the
#: pre-filled releaser suggestion for RELEASES skeletons
RELEASER_HINTS = ("close", "shutdown", "stop", "server_close",
                  "terminate", "__exit__")

_RELEASES_FINDING = re.compile(
    r"^(\w+)\.(\w+) holds an acquired resource but is not declared "
    r"in a module-level RELEASES table")


def _ctor_name(call: ast.AST) -> Optional[str]:
    """'Lock' for ``threading.Lock()`` / ``Lock()`` style calls."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    else:
        return None
    return name if name in LOCK_CTORS else None


def _self_attr(target: ast.AST) -> Optional[str]:
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return target.attr
    return None


def _declared_classes(tree: ast.AST) -> set:
    """Class names any module-level GUARDED_BY literal already
    covers (malformed literals are the locks analyzer's problem)."""
    out: set = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "GUARDED_BY"
                        for t in node.targets)):
            continue
        try:
            spec = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            continue
        if isinstance(spec, dict):
            out.update(k for k in spec if isinstance(k, str))
    return out


def _init_locks(cls: ast.ClassDef) -> list:
    """[(attr, line)] for every lock-like ctor assigned to a self
    attribute in ``__init__``."""
    out = []
    for node in cls.body:
        if not (isinstance(node, ast.FunctionDef)
                and node.name == "__init__"):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            if _ctor_name(sub.value) is None:
                continue
            for t in sub.targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.append((attr, sub.lineno))
    return out


def _guarded_candidates(cls: ast.ClassDef, lock_attr: str) -> list:
    """Attributes the class assigns inside ``with self.<lock_attr>:``
    blocks -- the evidence-based pre-fill for the guarded tuple."""
    found: list = []
    for node in ast.walk(cls):
        if not isinstance(node, ast.With):
            continue
        if not any(_self_attr(item.context_expr) == lock_attr
                   for item in node.items):
            continue
        for sub in node.body:
            for inner in ast.walk(sub):
                targets = []
                if isinstance(inner, ast.Assign):
                    targets = inner.targets
                elif isinstance(inner, (ast.AugAssign, ast.AnnAssign)):
                    targets = [inner.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None and attr not in found:
                        found.append(attr)
    return found


def _method_names(cls: ast.ClassDef) -> set:
    return {n.name for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _guarded_by_skeletons(ctx) -> dict:
    """{rel_path: [skeleton text]} for undeclared lock owners."""
    out: dict = {}
    for path in ctx.package_files():
        tree = ctx.tree(path)
        if tree is None:
            continue
        declared = _declared_classes(tree)
        idx = ctx.index(path)
        for cls in idx.classes:
            if cls.name in declared:
                continue
            locks = _init_locks(cls)
            if not locks:
                continue
            entries = []
            for attr, _line in locks:
                guarded = _guarded_candidates(cls, attr)
                if guarded:
                    tup = ("(" + ", ".join(f'"{g}"' for g in guarded)
                           + ("," if len(guarded) == 1 else "") + ")")
                    note = ""
                else:
                    tup = "()"
                    note = ("   # TODO: list the attrs "
                            f"{attr!r} guards")
                entries.append(f'        "{attr}": {tup},{note}')
            text = ("GUARDED_BY = {\n"
                    + f'    "{cls.name}": {{\n'
                    + "\n".join(entries)
                    + "\n    },\n}")
            out.setdefault(ctx.rel(path), []).append(
                f"# class {cls.name} (line {cls.lineno})\n{text}")
    return out


def _releases_skeletons(ctx, findings) -> dict:
    """{rel_path: [skeleton text]} from the threads analyzer's
    undeclared-resource findings of the run that just completed."""
    grouped: dict = {}
    for f in findings:
        if f.check != "threads" or f.suppressed:
            continue
        m = _RELEASES_FINDING.match(f.message)
        if not m:
            continue
        cls_name, attr = m.group(1), m.group(2)
        grouped.setdefault(f.path, {}).setdefault(
            cls_name, []).append(attr)
    out: dict = {}
    for rel, classes in grouped.items():
        # resolve releaser hints from the class body when parseable
        abspath = os.path.join(ctx.root, rel)
        methods: dict = {}
        tree = ctx.tree(abspath)
        if tree is not None:
            for cls in ctx.index(abspath).classes:
                methods[cls.name] = _method_names(cls)
        entries = []
        for cls_name in sorted(classes):
            hint = next((h for h in RELEASER_HINTS
                         if h in methods.get(cls_name, ())),
                        None)
            rel_lines = []
            for attr in sorted(set(classes[cls_name])):
                val = (f'"{hint}"' if hint
                       else '"<releaser method>"   # TODO')
                rel_lines.append(f'        "{attr}": {val},')
            entries.append(f'    "{cls_name}": {{\n'
                           + "\n".join(rel_lines) + "\n    },")
        out[rel] = ["RELEASES = {\n" + "\n".join(entries) + "\n}"]
    return out


def render(ctx, findings) -> str:
    """The full paste-ready skeleton report for one completed run;
    empty string when every lock owner and resource holder is already
    declared."""
    guarded = _guarded_by_skeletons(ctx)
    releases = _releases_skeletons(ctx, findings)
    if not guarded and not releases:
        return ""
    out = ["# declaration skeletons (dprf check --fix-skeletons)",
           "# paste next to the named class, then fill the TODOs:",
           "# the tables make the class VISIBLE to the analyzers,",
           "# which then verify the actual discipline.", ""]
    for rel in sorted(set(guarded) | set(releases)):
        out.append(f"# ---- {rel}")
        for block in guarded.get(rel, []) + releases.get(rel, []):
            out.append(block)
            out.append("")
    return "\n".join(out).rstrip() + "\n"
