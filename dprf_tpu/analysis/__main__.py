import sys

from dprf_tpu.analysis import main

sys.exit(main())
