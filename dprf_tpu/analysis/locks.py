"""Lock-discipline / guarded-by race detector.

The runtime is a ``ThreadingTCPServer`` whose every worker connection
mutates shared coordinator state under one by-convention lock, plus a
completion-sender thread, snapshotter and trace-recorder threads.  The
convention is made machine-checkable here: a module DECLARES which
attributes of which classes are guarded by which lock in a
module-level ``GUARDED_BY`` table::

    GUARDED_BY = {
        "CoordinatorState": {"lock": ("found", "dispatcher", ...)},
        "_CompletionSender": {"<atomic>": ("error", "stop_seen")},
    }
    # and in the class body, for methods called with the lock held:
    def _stopped(self): ...
    _stopped._holds_lock = "lock"

Lock names are instance attributes holding a ``threading.Lock``.  Two
special pseudo-locks:

  ``<atomic>``   single-writer latched flags (GIL-atomic reference
                 assignments read cross-thread by design).  Reads are
                 free; the checker enforces the single-writer shape:
                 at most ONE method outside ``__init__`` ever assigns
                 the attribute, and never from outside the class.
  ``<extern>``   the whole class is serialized by its CALLER's lock
                 (Dispatcher under CoordinatorState.lock).  The class
                 itself must not acquire any declared lock -- hidden
                 acquisition would be invisible to callers' lock-order
                 reasoning -- and owners declare the reference to it
                 as a guarded attribute.

Checks:

  1. every read/write of a guarded attribute is statically inside a
     ``with <owner>.<lock>`` block over the SAME owner expression, or
     in a method annotated ``_holds_lock``, or in ``__init__``
     (construction happens-before publication);
  2. no blocking call (socket send/recv, RPC ``.call``, ``time.sleep``,
     jax compile entry points, subprocess) while any declared lock is
     held;
  3. lock-acquisition-order: acquiring (directly, or transitively via
     a method call the checker can type-resolve) lock B while holding
     lock A records the edge A->B; any cycle in that graph is an
     inversion waiting for its third thread, and fails the check.

Type resolution is deliberately simple and STATIC: ``self`` inside a
class; parameters, locals, and instance attributes with class
annotations; direct constructions ``x = ClassName(...)``; and calls to
functions whose return annotation names a known class (e.g.
``get_tracer() -> "TraceRecorder"``).  An expression the checker
cannot type is not checked -- the declared tables cover the
concurrent surfaces, and fixtures in tests/test_analysis.py pin the
surfaces it must see.
"""

from __future__ import annotations

import ast
from typing import Optional

from dprf_tpu.analysis import Finding

NAME = "locks"
DESCRIPTION = ("guarded-by discipline, blocking-calls-under-lock, and "
               "lock-order cycles over declared GUARDED_BY tables")

ATOMIC = "<atomic>"
EXTERN = "<extern>"

#: method-attribute calls that block (or compile) -- forbidden while a
#: declared lock is held
BLOCKING_ATTRS = {"sendall", "recv", "recv_into", "readline", "accept",
                  "connect", "makefile", "call", "aot_compile",
                  "ensure_warm", "warmup", "drain"}
#: bare-name calls that block
BLOCKING_NAMES = {"send_msg", "recv_msg", "sleep"}
#: module-qualified calls that block
BLOCKING_QUALIFIED = {("time", "sleep"), ("socket", "create_connection"),
                      ("subprocess", "run"), ("subprocess", "check_call"),
                      ("subprocess", "check_output"), ("jax", "jit"),
                      ("jax", "pmap")}


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _expr_key(node) -> Optional[str]:
    """Normalize a Name/Attribute chain ('self', 'self.state', ...);
    None for anything the guard matcher should not try to compare."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_key(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _ann_name(node) -> Optional[str]:
    """A class name out of an annotation: ``X``, ``"X"``, or
    ``Optional[X]``-style subscripts are reduced to X."""
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    s = _const_str(node)
    if s:
        return s.strip().strip('"').strip("'")
    if isinstance(node, ast.Subscript):
        # Optional[X] / "Optional[X]": dig for the inner name
        inner = node.slice
        return _ann_name(inner)
    return None


# ---------------------------------------------------------------------------
# declaration + class-table collection

class _ClassSpec:
    def __init__(self, name: str, rel: str, line: int):
        self.name = name
        self.rel = rel
        self.line = line
        self.declared = False        # has a GUARDED_BY entry
        self.guards: dict = {}       # attr -> lock name
        self.atomic: set = set()
        self.extern = False
        self.locks: set = set()      # declared lock attr names
        self.holds: dict = {}        # method -> lock name
        self.attr_types: dict = {}   # self-attr -> class name
        self.methods: dict = {}      # name -> ast.FunctionDef
        self.init_assigned: set = set()   # attrs assigned in __init__


def _parse_guarded_by(node: ast.Assign, rel: str, out: dict,
                      findings: list) -> None:
    v = node.value
    if not isinstance(v, ast.Dict):
        findings.append(Finding(NAME, rel, node.lineno,
                                "GUARDED_BY must be a dict literal"))
        return
    for ck, cv in zip(v.keys, v.values):
        cname = _const_str(ck)
        if cname is None or not isinstance(cv, ast.Dict):
            findings.append(Finding(
                NAME, rel, node.lineno,
                "GUARDED_BY entries must map a class-name string to "
                "a {lock: (attrs...)} dict literal"))
            continue
        spec = out.setdefault(cname, {"rel": rel, "line": node.lineno,
                                      "locks": {}})
        for lk, lv in zip(cv.keys, cv.values):
            lname = _const_str(lk)
            attrs = []
            if isinstance(lv, (ast.Tuple, ast.List)):
                attrs = [_const_str(e) for e in lv.elts]
            if lname is None or any(a is None for a in attrs):
                findings.append(Finding(
                    NAME, rel, node.lineno,
                    f"GUARDED_BY[{cname!r}] must map lock-name "
                    "strings to tuples of attribute-name strings"))
                continue
            spec["locks"][lname] = attrs


def _walk_scope(node):
    """ast.walk that does NOT descend into nested function/class
    scopes (they are analyzed separately, with their own env)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _collect(ctx):
    """(specs: name -> _ClassSpec for EVERY class (guards filled only
    for GUARDED_BY-declared ones), class_nodes, returns, findings)."""
    findings: list = []
    declared: dict = {}      # class name -> raw decl
    class_nodes: dict = {}   # class name -> (node, rel)
    returns: dict = {}       # function name -> class name

    # staged parsing: a file whose SOURCE never names a declared class
    # (or GUARDED_BY, or a factory returning one) cannot define, type,
    # or touch anything this checker reasons about -- typing always
    # needs the name in source (construction, annotation, factory
    # call), so skipping its parse drops no finding.
    files = ctx.package_files()
    srcs = {}
    for path in files:
        try:
            srcs[path] = ctx.source(path)
        except OSError:
            pass
    files = [p for p in files if p in srcs]

    for path in files:
        if "GUARDED_BY" not in srcs[path]:
            continue
        tree = ctx.tree(path)
        if tree is None:
            continue
        rel = ctx.rel(path)
        for node in tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "GUARDED_BY"):
                _parse_guarded_by(node, rel, declared, findings)

    def _scan(path):
        idx = ctx.index(path)
        if idx is None:
            return
        rel = ctx.rel(path)
        for node in idx.classes:
            class_nodes[node.name] = (node, rel)
        for node in idx.functions:
            r = _ann_name(node.returns)
            if r:
                returns[node.name] = r

    needles = set(declared) | {"GUARDED_BY"}
    scanned = set()
    for path in files:
        if any(n in srcs[path] for n in needles):
            scanned.add(path)
            _scan(path)
    # one widening round: factories returning a declared class pull in
    # the files that only ever touch it through the factory
    factories = {f for f, c in returns.items() if c in declared}
    if factories:
        for path in files:
            if path not in scanned \
                    and any(f in srcs[path] for f in factories):
                _scan(path)

    # keep only return annotations that name a class we know about
    returns = {k: v for k, v in returns.items() if v in class_nodes}

    specs: dict = {}
    for cname, (node, rel) in class_nodes.items():
        spec = _ClassSpec(cname, rel, node.lineno)
        decl = declared.pop(cname, None)
        if decl is not None:
            spec.declared = True
            for lname, attrs in decl["locks"].items():
                if lname == ATOMIC:
                    spec.atomic.update(attrs)
                elif lname == EXTERN:
                    spec.extern = True
                else:
                    spec.locks.add(lname)
                    for a in attrs:
                        if a in spec.guards:
                            findings.append(Finding(
                                NAME, rel, node.lineno,
                                f"{cname}.{a} declared guarded by "
                                "two locks"))
                        spec.guards[a] = lname
        _scan_class_body(spec, node, returns, class_nodes, findings)
        specs[cname] = spec
    for cname, decl in declared.items():
        findings.append(Finding(
            NAME, decl["rel"], decl["line"],
            f"GUARDED_BY declares unknown class {cname!r}"))
    return specs, class_nodes, returns, findings


def _infer_call_type(call: ast.Call, returns: dict,
                     class_nodes: dict) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in class_nodes:
            return f.id                 # direct construction
        return returns.get(f.id)        # annotated factory
    if isinstance(f, ast.Attribute):
        return returns.get(f.attr)      # module.factory()
    return None


def _scan_class_body(spec: _ClassSpec, node: ast.ClassDef,
                     returns: dict, class_nodes: dict,
                     findings: list) -> None:
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spec.methods[item.name] = item
        elif isinstance(item, ast.Assign) and len(item.targets) == 1:
            # method._holds_lock = "lock" annotations
            t = item.targets[0]
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.attr == "_holds_lock"):
                lock = _const_str(item.value)
                if lock:
                    spec.holds[t.value.id] = lock
    init = spec.methods.get("__init__")
    if init is not None:
        # parameter annotations: self.X = <annotated param>
        ann = {}
        args = init.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            n = _ann_name(a.annotation)
            if n in class_nodes:
                ann[a.arg] = n
        for st in _walk_scope(init):
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                t = st.targets[0]
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    spec.init_assigned.add(t.attr)
                    ty = None
                    if isinstance(st.value, ast.Name):
                        ty = ann.get(st.value.id)
                    elif isinstance(st.value, ast.Call):
                        ty = _infer_call_type(st.value, returns,
                                              class_nodes)
                    if ty:
                        spec.attr_types[t.attr] = ty
            elif isinstance(st, ast.AnnAssign):
                t = st.target
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    spec.init_assigned.add(t.attr)
                    ty = _ann_name(st.annotation)
                    if ty in class_nodes:
                        spec.attr_types[t.attr] = ty
    for lock in spec.locks:
        if init is None or lock not in spec.init_assigned:
            findings.append(Finding(
                NAME, spec.rel, spec.line,
                f"{spec.name}: declared lock {lock!r} is never "
                "assigned in __init__ -- the guard would silently "
                "never exist"))


# ---------------------------------------------------------------------------
# per-function analysis

class _FnAnalysis:
    """One function/method walk: guarded-access, blocking-call, and
    lock-edge collection under a lexical held-locks stack."""

    def __init__(self, checker: "_Checker", fn, rel: str,
                 cls: Optional[_ClassSpec], fname: str):
        self.c = checker
        self.fn = fn
        self.rel = rel
        self.cls = cls
        self.fname = fname
        self.env: dict = {}          # name -> class name
        if cls is not None:
            self.env["self"] = cls.name
        self._build_env()

    def _learn(self, name: str, ty: Optional[str]) -> None:
        if ty is None:
            return
        cur = self.env.get(name)
        if cur is not None and cur != ty:
            self.env[name] = None    # conflicting: stop trusting it
        elif cur is None and name in self.env:
            pass                     # already poisoned
        else:
            self.env[name] = ty

    def _build_env(self) -> None:
        args = self.fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            n = _ann_name(a.annotation)
            if n in self.c.class_nodes:
                self._learn(a.arg, n)
        for node in _walk_scope(self.fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self._learn(node.targets[0].id,
                            self._type_of(node.value))
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                n = _ann_name(node.annotation)
                if n in self.c.class_nodes:
                    self._learn(node.target.id, n)

    def _type_of(self, node) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._type_of(node.value)
            if base is not None:
                spec = self.c.classes.get(base)
                if spec is not None:
                    return spec.attr_types.get(node.attr)
            return None
        if isinstance(node, ast.Call):
            return _infer_call_type(node, self.c.returns,
                                    self.c.class_nodes)
        return None

    # -- the walk --------------------------------------------------------

    def analyze(self) -> None:
        held: list = []
        if self.cls is not None:
            lock = self.cls.holds.get(self.fname)
            if lock:
                held = [(self.cls.name, lock, "self")]
        self._visit_body(self.fn.body, held)

    def _lock_of_with(self, expr):
        """(class, lock, owner_key) when the with-context is
        ``<typed expr>.<declared lock>``."""
        if not isinstance(expr, ast.Attribute):
            return None
        ty = self._type_of(expr.value)
        spec = self.c.classes.get(ty) if ty else None
        if spec is not None and expr.attr in spec.locks:
            return (ty, expr.attr, _expr_key(expr.value))
        return None

    def _visit_body(self, stmts, held) -> None:
        for st in stmts:
            self._visit_stmt(st, held)

    def _visit_stmt(self, st, held) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return                      # analyzed as its own scope
        if isinstance(st, (ast.With, ast.AsyncWith)):
            new = list(held)
            for item in st.items:
                self._scan_expr(item.context_expr, held)
                acq = self._lock_of_with(item.context_expr)
                if acq is not None:
                    for h in new:
                        if (h[0], h[1]) != (acq[0], acq[1]):
                            self.c.add_edge((h[0], h[1]),
                                            (acq[0], acq[1]),
                                            self.rel, st.lineno)
                        else:
                            self.c.findings.append(Finding(
                                NAME, self.rel, st.lineno,
                                f"re-acquiring {acq[0]}.{acq[1]} "
                                "while already held (deadlock with a "
                                "non-reentrant Lock)"))
                    new.append(acq)
            self._visit_body(st.body, new)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.stmt):
                self._visit_stmt(child, held)
            elif isinstance(child, ast.excepthandler):
                if child.type is not None:
                    self._scan_expr(child.type, held)
                self._visit_body(child.body, held)
            else:
                self._scan_expr(child, held)

    # -- expression-level checks -----------------------------------------

    def _scan_expr(self, node, held) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return       # nested scopes get their own analysis
        if isinstance(node, ast.Attribute):
            self._check_attr(node, held)
        elif isinstance(node, ast.Call):
            self._check_call(node, held)
        for child in ast.iter_child_nodes(node):
            self._scan_expr(child, held)

    def _check_attr(self, node: ast.Attribute, held) -> None:
        ty = self._type_of(node.value)
        spec = self.c.classes.get(ty) if ty else None
        if spec is None:
            return
        attr = node.attr
        owner = _expr_key(node.value)
        in_own_init = (self.cls is not None and self.cls.name == ty
                       and self.fname == "__init__" and owner == "self")
        if attr in spec.atomic:
            if isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and not in_own_init:
                self.c.atomic_writes.setdefault(
                    (ty, attr), []).append(
                        (self.rel, node.lineno, self.cls.name
                         if self.cls else None, self.fname))
            return
        lock = spec.guards.get(attr)
        if lock is None:
            return
        if in_own_init:
            return
        if owner is not None and (ty, lock, owner) in held:
            return
        # a with over the same (class, lock) pair but a DIFFERENT
        # owner expression still guards when both expressions can
        # only denote the same instance (self.state.lock vs a local
        # alias) -- too clever to verify statically, so require the
        # exact owner match and let suppressions document aliases.
        self.c.findings.append(Finding(
            NAME, self.rel, node.lineno,
            f"{ty}.{attr} is guarded by {ty}.{lock!r} but accessed "
            f"without it (owner expr "
            f"{owner or '<unresolved>'}; wrap in `with "
            f"{owner or '<owner>'}.{lock}:` or annotate the method "
            f"`_holds_lock = {lock!r}`)"))

    def _blocking_reason(self, node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Name) and f.id in BLOCKING_NAMES:
            return f.id
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) \
                    and (f.value.id, f.attr) in BLOCKING_QUALIFIED:
                return f"{f.value.id}.{f.attr}"
            if f.attr in BLOCKING_ATTRS:
                return f".{f.attr}()"
        return None

    def _check_call(self, node: ast.Call, held) -> None:
        if held:
            why = self._blocking_reason(node)
            if why is not None:
                locks = ", ".join(f"{c}.{l}" for c, l, _ in held)
                self.c.findings.append(Finding(
                    NAME, self.rel, node.lineno,
                    f"blocking call {why} while holding {locks} -- "
                    "move the slow work outside the lock"))
        # lock-order edges through resolvable method calls
        if held:
            callee = self._resolve_method(node)
            if callee is not None:
                for acq in self.c.transitive_acquires(callee):
                    for h in held:
                        if (h[0], h[1]) != acq:
                            self.c.add_edge((h[0], h[1]), acq,
                                            self.rel, node.lineno)

    def _resolve_method(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            ty = self._type_of(f.value)
            if ty in self.c.classes and f.attr in \
                    self.c.classes[ty].methods:
                return (ty, f.attr)
        return None


# ---------------------------------------------------------------------------
# whole-package checker

class _Checker:
    def __init__(self, ctx):
        self.ctx = ctx
        self.findings: list = []
        (self.classes, self.class_nodes, self.returns,
         decl_findings) = _collect(ctx)
        self.findings.extend(decl_findings)
        self.atomic_writes: dict = {}
        self.edges: dict = {}        # (A)->(B) : first site
        self._acq_cache: dict = {}
        self._direct_cache: dict = {}

    # -- transitive lock acquisition per declared method -----------------

    def _direct_info(self, key):
        """(direct acquires, callees) for (class, method), memoized
        (cycle members get re-walked across top-level queries)."""
        cached = self._direct_cache.get(key)
        if cached is not None:
            return cached
        cname, mname = key
        spec = self.classes[cname]
        fn = spec.methods[mname]
        ana = _FnAnalysis(self, fn, spec.rel, spec, mname)
        acquires: set = set()
        callees: set = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    acq = ana._lock_of_with(item.context_expr)
                    if acq is not None:
                        acquires.add((acq[0], acq[1]))
            elif isinstance(node, ast.Call):
                callee = ana._resolve_method(node)
                if callee is not None:
                    callees.add(callee)
        self._direct_cache[key] = (acquires, callees)
        return acquires, callees

    def transitive_acquires(self, key) -> set:
        out, _ = self._walk_acquires(key, set())
        return out

    def _walk_acquires(self, key, visiting):
        """(acquire set, tainted?) -- tainted means a cycle back-edge
        truncated the recursion somewhere below, so the set may be
        incomplete for THIS node and must not be cached (caching a
        mid-cycle placeholder would permanently hide a cycle member's
        locks from later call sites -- a missed inversion).  The
        root's union is always complete: every reachable node's direct
        acquires are folded in exactly once."""
        cached = self._acq_cache.get(key)
        if cached is not None:
            return cached, False
        if key in visiting:
            return set(), True
        visiting.add(key)
        acq, callees = self._direct_info(key)
        out = set(acq)
        tainted = False
        for c in callees:
            if c != key:
                sub, t = self._walk_acquires(c, visiting)
                out |= sub
                tainted = tainted or t
        visiting.discard(key)
        if not tainted or not visiting:
            self._acq_cache[key] = out   # complete at the root too
        return out, tainted

    def add_edge(self, a, b, rel, line) -> None:
        self.edges.setdefault((a, b), (rel, line))

    # -- the run ---------------------------------------------------------

    def run(self) -> list:
        if not any(s.declared for s in self.classes.values()):
            return self.findings     # nothing declared, nothing to do
        # a file that never NAMES a declared class (or a factory whose
        # return annotation is one, or a GUARDED_BY table) cannot type
        # an expression to one, so it can neither access a guarded
        # attribute nor hold a declared lock -- skip its (expensive)
        # per-function analysis entirely.  Typing always needs the
        # name in source: construction, annotation, or factory call.
        declared_names = {s.name for s in self.classes.values()
                          if s.declared}
        needles = set(declared_names) | {"GUARDED_BY"}
        needles.update(f for f, c in self.returns.items()
                       if c in declared_names)
        for path in self.ctx.package_files():
            try:
                src = self.ctx.source(path)
            except OSError:
                continue
            if not any(n in src for n in needles):
                continue        # (before tree(): skips the parse too)
            tree = self.ctx.tree(path)
            if tree is None:
                continue
            rel = self.ctx.rel(path)
            self._analyze_scopes(tree, rel, None)
        self._check_extern()
        self._check_atomic_writers()
        self._check_cycles()
        return self.findings

    def _analyze_scopes(self, node, rel, cls) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                spec = self.classes.get(child.name)
                self._analyze_scopes(child, rel, spec)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                _FnAnalysis(self, child, rel, cls,
                            child.name).analyze()
                # nested defs (closures) are separate, lock-free scopes
                self._analyze_scopes(child, rel, None)

    def _check_extern(self) -> None:
        for spec in self.classes.values():
            if not spec.extern:
                continue
            for mname, fn in spec.methods.items():
                ana = _FnAnalysis(self, fn, spec.rel, spec, mname)
                for node in ast.walk(fn):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            if ana._lock_of_with(item.context_expr):
                                self.findings.append(Finding(
                                    NAME, spec.rel, node.lineno,
                                    f"{spec.name} is declared "
                                    "<extern> (serialized by its "
                                    "caller) but acquires a declared "
                                    "lock itself -- invisible to the "
                                    "callers' lock ordering"))

    def _check_atomic_writers(self) -> None:
        for (cname, attr), sites in sorted(self.atomic_writes.items()):
            writers = {(c, f) for (_, _, c, f) in sites}
            outside = [(r, ln) for (r, ln, c, _) in sites
                       if c != cname]
            if outside:
                r, ln = outside[0]
                self.findings.append(Finding(
                    NAME, r, ln,
                    f"{cname}.{attr} is <atomic> (single-writer "
                    "latched flag) but assigned from outside "
                    f"{cname} -- promote it to a guarded attribute"))
            if len(writers) > 1:
                r, ln = sites[0][0], sites[0][1]
                self.findings.append(Finding(
                    NAME, r, ln,
                    f"{cname}.{attr} is <atomic> but written from "
                    f"{len(writers)} methods "
                    f"({sorted(f for _, f in writers)}) -- the "
                    "single-writer exemption no longer holds; guard "
                    "it with a lock"))

    def _check_cycles(self) -> None:
        graph: dict = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        state: dict = {}       # node -> 1 (on stack) / 2 (done)
        stack: list = []

        def dfs(n):
            state[n] = 1
            stack.append(n)
            for m in sorted(graph.get(n, ())):
                if state.get(m) == 1:
                    cyc = stack[stack.index(m):] + [m]
                    names = " -> ".join(f"{c}.{l}" for c, l in cyc)
                    rel, line = self.edges[(n, m)]
                    self.findings.append(Finding(
                        NAME, rel, line,
                        f"lock-order cycle: {names} -- two threads "
                        "taking these locks in opposite order "
                        "deadlock"))
                elif state.get(m) is None:
                    dfs(m)
            stack.pop()
            state[n] = 2

        for n in sorted(graph):
            if state.get(n) is None:
                dfs(n)


def run(ctx) -> list:
    return _Checker(ctx).run()
