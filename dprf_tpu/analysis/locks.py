"""Lock-discipline / guarded-by race detector.

The runtime is a ``ThreadingTCPServer`` whose every worker connection
mutates shared coordinator state under one by-convention lock, plus a
completion-sender thread, snapshotter and trace-recorder threads.  The
convention is made machine-checkable here: a module DECLARES which
attributes of which classes are guarded by which lock in a
module-level ``GUARDED_BY`` table::

    GUARDED_BY = {
        "CoordinatorState": {"lock": ("found", "dispatcher", ...)},
        "_CompletionSender": {"<atomic>": ("error", "stop_seen")},
        "<module>": {"_lock": ("_state",)},
    }
    # and in the class body, for methods called with the lock held:
    def _stopped(self): ...
    _stopped._holds_lock = "lock"

Lock names are instance attributes holding a ``threading.Lock`` or
``threading.RLock`` (reentrant: re-acquiring an RLock already held is
NOT a self-deadlock, and never a lock-order edge against itself).
Three special keys:

  ``<atomic>``   single-writer latched flags (GIL-atomic reference
                 assignments read cross-thread by design).  Reads are
                 free; the checker enforces the single-writer shape:
                 at most ONE method outside ``__init__`` ever assigns
                 the attribute, and never from outside the class.
  ``<extern>``   the whole class is serialized by its CALLER's lock
                 (Dispatcher under CoordinatorState.lock).  The class
                 itself must not acquire any declared lock -- hidden
                 acquisition would be invisible to callers' lock-order
                 reasoning -- and owners declare the reference to it
                 as a guarded attribute.
  ``<module>``   module-GLOBAL state guarded by a module-global lock
                 (the compilecache ``_state`` under ``_lock`` shape):
                 every function in the declaring module touching the
                 global must hold ``with <lock>:`` (or carry
                 ``func._holds_lock = "<lock>"``).

Checks:

  1. every read/write of a guarded attribute is statically inside a
     ``with <owner>.<lock>`` block over the SAME owner expression, or
     in a method annotated ``_holds_lock``, or in ``__init__``
     (construction happens-before publication);
  2. no blocking call (socket send/recv, RPC ``.call``, ``time.sleep``,
     jax compile entry points, subprocess) while any declared lock is
     held -- including blocking calls REACHED through the call graph
     (analysis/callgraph.py): a helper that sleeps is as much a stall
     under the lock as an inline sleep;
  3. lock-acquisition-order: acquiring (directly, or transitively via
     any call the graph can resolve -- methods AND module functions)
     lock B while holding lock A records the edge A->B; any cycle in
     that graph is an inversion waiting for its third thread, and
     fails the check.

Type resolution is the call graph's (callgraph.TypeScope): ``self``
inside a class; parameters, locals, and instance attributes with
class annotations; direct constructions; annotated factory calls.  An
expression the checker cannot type is not checked -- the declared
tables cover the concurrent surfaces, and fixtures in
tests/test_analysis.py pin the surfaces it must see.
"""

from __future__ import annotations

import ast
from typing import Optional

from dprf_tpu.analysis import Finding
from dprf_tpu.analysis import callgraph as cg
from dprf_tpu.analysis.callgraph import (ann_name, blocking_reason,
                                         const_str, expr_key,
                                         walk_scope)

NAME = "locks"
DESCRIPTION = ("guarded-by discipline, blocking-calls-under-lock "
               "(direct and through the call graph), and lock-order "
               "cycles over declared GUARDED_BY tables")
#: declaration tables --explain renders for this check
DECL_TABLES = ("GUARDED_BY",)

ATOMIC = "<atomic>"
EXTERN = "<extern>"
MODULE = "<module>"

#: re-exported for compatibility (the shared tables live in the
#: call-graph core now)
BLOCKING_ATTRS = cg.BLOCKING_ATTRS
BLOCKING_NAMES = cg.BLOCKING_NAMES
BLOCKING_QUALIFIED = cg.BLOCKING_QUALIFIED


# ---------------------------------------------------------------------------
# declaration + class-table collection

class _ClassSpec:
    def __init__(self, name: str, rel: str, line: int):
        self.name = name
        self.rel = rel
        self.line = line
        self.guards: dict = {}       # attr -> lock name
        self.atomic: set = set()
        self.extern = False
        self.locks: set = set()      # declared lock attr names
        self.rlocks: set = set()     # declared locks that are RLocks
        self.holds: dict = {}        # method -> lock name


def _parse_guarded_by(node: ast.Assign, rel: str, out: dict,
                      module_out: dict, findings: list) -> None:
    v = node.value
    if not isinstance(v, ast.Dict):
        findings.append(Finding(NAME, rel, node.lineno,
                                "GUARDED_BY must be a dict literal"))
        return
    for ck, cv in zip(v.keys, v.values):
        cname = const_str(ck)
        if cname is None or not isinstance(cv, ast.Dict):
            findings.append(Finding(
                NAME, rel, node.lineno,
                "GUARDED_BY entries must map a class-name string to "
                "a {lock: (attrs...)} dict literal"))
            continue
        if cname == MODULE:
            spec = module_out.setdefault(rel, {"line": node.lineno,
                                               "locks": {}})
        else:
            spec = out.setdefault(cname, {"rel": rel,
                                          "line": node.lineno,
                                          "locks": {}})
        for lk, lv in zip(cv.keys, cv.values):
            lname = const_str(lk)
            attrs = []
            if isinstance(lv, (ast.Tuple, ast.List)):
                attrs = [const_str(e) for e in lv.elts]
            if lname is None or any(a is None for a in attrs):
                findings.append(Finding(
                    NAME, rel, node.lineno,
                    f"GUARDED_BY[{cname!r}] must map lock-name "
                    "strings to tuples of attribute-name strings"))
                continue
            spec["locks"][lname] = attrs


def _is_rlock_call(node) -> bool:
    """``threading.RLock()`` / ``RLock()``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "RLock"
    return isinstance(f, ast.Attribute) and f.attr == "RLock"


class _ModuleGuard:
    """One file's <module> declaration: global names guarded by
    module-global locks."""

    def __init__(self, rel: str, line: int):
        self.rel = rel
        self.line = line
        self.guards: dict = {}       # global name -> lock name
        self.locks: set = set()
        self.rlocks: set = set()
        self.holds: dict = {}        # function name -> lock name


def _collect(ctx, graph: "cg.CallGraph"):
    """(specs: declared-class name -> _ClassSpec, module_guards:
    rel -> _ModuleGuard, findings).  Files register into the shared
    call graph; staged needle parsing keeps untouched files unparsed
    (a file whose SOURCE never names a declared class, GUARDED_BY, or
    a factory returning one cannot define, type, or touch anything
    this checker reasons about)."""
    findings: list = []
    declared: dict = {}      # class name -> raw decl
    module_decl: dict = {}   # rel -> raw decl

    files = ctx.package_files()
    srcs = {}
    for path in files:
        try:
            srcs[path] = ctx.source(path)
        except OSError:
            pass
    files = [p for p in files if p in srcs]

    for path in files:
        if "GUARDED_BY" not in srcs[path]:
            continue
        mod = graph.load_file(path)
        if mod is None:
            continue
        rel = ctx.rel(path)
        for node in mod.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "GUARDED_BY"):
                _parse_guarded_by(node, rel, declared, module_decl,
                                  findings)

    needles = set(declared) | {"GUARDED_BY"}
    loaded = set()
    for path in files:
        if any(n in srcs[path] for n in needles):
            loaded.add(path)
            graph.load_file(path)
    # one widening round: factories returning a declared class pull in
    # the files that only ever touch it through the factory
    factories = {f for f, c in graph.returns.items() if c in declared}
    if factories:
        for path in files:
            if path not in loaded \
                    and any(f in srcs[path] for f in factories):
                loaded.add(path)
                graph.load_file(path)

    specs: dict = {}
    for cname, decl in declared.items():
        ci = graph.classes.get(cname)
        if ci is None:
            findings.append(Finding(
                NAME, decl["rel"], decl["line"],
                f"GUARDED_BY declares unknown class {cname!r}"))
            continue
        spec = _ClassSpec(cname, ci.rel, ci.line)
        for lname, attrs in decl["locks"].items():
            if lname == ATOMIC:
                spec.atomic.update(attrs)
            elif lname == EXTERN:
                spec.extern = True
            else:
                spec.locks.add(lname)
                for a in attrs:
                    if a in spec.guards:
                        findings.append(Finding(
                            NAME, ci.rel, ci.line,
                            f"{cname}.{a} declared guarded by two "
                            "locks"))
                    spec.guards[a] = lname
        for mname, marks in ci.method_marks.items():
            lock = marks.get("_holds_lock")
            if isinstance(lock, str):
                spec.holds[mname] = lock
        # lock existence + RLock detection from __init__ (attr_types
        # fills init_assigned as a side effect)
        graph.attr_types(ci)
        init = ci.methods.get("__init__")
        rlock_attrs = set()
        if init is not None:
            for st in walk_scope(init.node):
                if isinstance(st, ast.Assign) and len(st.targets) == 1:
                    t = st.targets[0]
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and _is_rlock_call(st.value)):
                        rlock_attrs.add(t.attr)
        spec.rlocks = spec.locks & rlock_attrs
        for lock in spec.locks:
            if lock not in ci.init_assigned:
                findings.append(Finding(
                    NAME, ci.rel, ci.line,
                    f"{cname}: declared lock {lock!r} is never "
                    "assigned in __init__ -- the guard would silently "
                    "never exist"))
        specs[cname] = spec

    module_guards: dict = {}
    for rel, decl in module_decl.items():
        mg = _ModuleGuard(rel, decl["line"])
        for lname, attrs in decl["locks"].items():
            mg.locks.add(lname)
            for a in attrs:
                mg.guards[a] = lname
        mod = graph.modules.get(rel)
        if mod is not None:
            lock_assigned = set()
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    lock_assigned.add(name)
                    if _is_rlock_call(node.value):
                        mg.rlocks.add(name)
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    pass
                # func._holds_lock = "<lock>" at module level
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute) \
                        and isinstance(node.targets[0].value,
                                       ast.Name) \
                        and node.targets[0].attr == "_holds_lock":
                    lock = const_str(node.value)
                    if lock:
                        mg.holds[node.targets[0].value.id] = lock
            for lname in mg.locks:
                if lname not in lock_assigned:
                    findings.append(Finding(
                        NAME, rel, decl["line"],
                        f"<module> lock {lname!r} is never assigned "
                        "at module level -- the guard would silently "
                        "never exist"))
        module_guards[rel] = mg
    return specs, module_guards, findings


# ---------------------------------------------------------------------------
# per-function analysis

class _FnAnalysis:
    """One function/method walk: guarded-access, blocking-call, and
    lock-edge collection under a lexical held-locks stack.  Held
    entries are (class name | ("<module>", rel), lock name, owner
    expr key)."""

    def __init__(self, checker: "_Checker", fn, rel: str,
                 cls: Optional[_ClassSpec], fname: str,
                 scope: "cg.TypeScope"):
        self.c = checker
        self.fn = fn
        self.rel = rel
        self.cls = cls
        self.fname = fname
        self.scope = scope
        self.mg: Optional[_ModuleGuard] = \
            checker.module_guards.get(rel)

    # -- the walk --------------------------------------------------------

    def analyze(self) -> None:
        held: list = []
        if self.cls is not None:
            lock = self.cls.holds.get(self.fname)
            if lock:
                held = [(self.cls.name, lock, "self")]
        if self.mg is not None:
            lock = self.mg.holds.get(self.fname)
            if lock:
                held = held + [((MODULE, self.rel), lock, lock)]
        self._visit_body(self.fn.body, held)

    def _lock_of_with(self, expr):
        """(class-or-module key, lock, owner_key) when the
        with-context is ``<typed expr>.<declared lock>`` or a bare
        module-lock name."""
        if isinstance(expr, ast.Attribute):
            ty = self.scope.type_of(expr.value)
            spec = self.c.specs.get(ty) if ty else None
            if spec is not None and expr.attr in spec.locks:
                return (ty, expr.attr, expr_key(expr.value))
            return None
        if isinstance(expr, ast.Name) and self.mg is not None \
                and expr.id in self.mg.locks:
            return ((MODULE, self.rel), expr.id, expr.id)
        return None

    def _is_rlock(self, acq) -> bool:
        key, lock = acq[0], acq[1]
        if isinstance(key, tuple):
            # (MODULE, rel): resolve through the run-wide table so a
            # transitive acquire in ANOTHER module answers correctly
            mg = self.c.module_guards.get(key[1])
            return mg is not None and lock in mg.rlocks
        spec = self.c.specs.get(key)
        return spec is not None and lock in spec.rlocks

    def _visit_body(self, stmts, held) -> None:
        for st in stmts:
            self._visit_stmt(st, held)

    def _visit_stmt(self, st, held) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return                      # analyzed as its own scope
        if isinstance(st, (ast.With, ast.AsyncWith)):
            new = list(held)
            for item in st.items:
                self._scan_expr(item.context_expr, held)
                acq = self._lock_of_with(item.context_expr)
                if acq is not None:
                    reacquired = False
                    for h in new:
                        if (h[0], h[1]) != (acq[0], acq[1]):
                            self.c.add_edge((h[0], h[1]),
                                            (acq[0], acq[1]),
                                            self.rel, st.lineno)
                        elif self._is_rlock(acq):
                            # reentrant by construction: not a
                            # deadlock, and no self-edge
                            reacquired = True
                        else:
                            self.c.findings.append(Finding(
                                NAME, self.rel, st.lineno,
                                f"re-acquiring {self._lname(acq)} "
                                "while already held (deadlock with a "
                                "non-reentrant Lock)"))
                    if not reacquired:
                        new.append(acq)
            self._visit_body(st.body, new)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.stmt):
                self._visit_stmt(child, held)
            elif isinstance(child, ast.excepthandler):
                if child.type is not None:
                    self._scan_expr(child.type, held)
                self._visit_body(child.body, held)
            else:
                self._scan_expr(child, held)

    @staticmethod
    def _lname(entry) -> str:
        key, lock = entry[0], entry[1]
        if isinstance(key, tuple):
            return f"{key[1]}:{lock}"
        return f"{key}.{lock}"

    # -- expression-level checks -----------------------------------------

    def _scan_expr(self, node, held) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return       # nested scopes get their own analysis
        if isinstance(node, ast.Attribute):
            self._check_attr(node, held)
        elif isinstance(node, ast.Call):
            self._check_call(node, held)
        elif isinstance(node, ast.Name):
            self._check_global(node, held)
        for child in ast.iter_child_nodes(node):
            self._scan_expr(child, held)

    def _check_global(self, node: ast.Name, held) -> None:
        if self.mg is None:
            return
        lock = self.mg.guards.get(node.id)
        if lock is None:
            return
        if ((MODULE, self.rel), lock, lock) in held:
            return
        self.c.findings.append(Finding(
            NAME, self.rel, node.lineno,
            f"module global {node.id!r} is guarded by module lock "
            f"{lock!r} but accessed without it (wrap in `with "
            f"{lock}:` or annotate the function "
            f"`_holds_lock = {lock!r}`)"))

    def _check_attr(self, node: ast.Attribute, held) -> None:
        ty = self.scope.type_of(node.value)
        spec = self.c.specs.get(ty) if ty else None
        if spec is None:
            return
        attr = node.attr
        owner = expr_key(node.value)
        in_own_init = (self.cls is not None and self.cls.name == ty
                       and self.fname == "__init__" and owner == "self")
        if attr in spec.atomic:
            if isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and not in_own_init:
                self.c.atomic_writes.setdefault(
                    (ty, attr), []).append(
                        (self.rel, node.lineno, self.cls.name
                         if self.cls else None, self.fname))
            return
        lock = spec.guards.get(attr)
        if lock is None:
            return
        if in_own_init:
            return
        if owner is not None and (ty, lock, owner) in held:
            return
        # a with over the same (class, lock) pair but a DIFFERENT
        # owner expression still guards when both expressions can
        # only denote the same instance (self.state.lock vs a local
        # alias) -- too clever to verify statically, so require the
        # exact owner match and let suppressions document aliases.
        self.c.findings.append(Finding(
            NAME, self.rel, node.lineno,
            f"{ty}.{attr} is guarded by {ty}.{lock!r} but accessed "
            f"without it (owner expr "
            f"{owner or '<unresolved>'}; wrap in `with "
            f"{owner or '<owner>'}.{lock}:` or annotate the method "
            f"`_holds_lock = {lock!r}`)"))

    def _check_call(self, node: ast.Call, held) -> None:
        if not held:
            return
        why = blocking_reason(node)
        if why is not None:
            locks = ", ".join(self._lname(h) for h in held)
            self.c.findings.append(Finding(
                NAME, self.rel, node.lineno,
                f"blocking call {why} while holding {locks} -- "
                "move the slow work outside the lock"))
        # interprocedural: lock-order edges AND blocking calls through
        # everything the call graph can resolve (methods + module
        # functions + imported helpers)
        callee = self.c.graph.resolve_call(node, self.scope)
        if callee is None:
            return
        closure = self.c.graph.closure(callee)
        for acq in self.c.declared_acquires(closure):
            for h in held:
                if (h[0], h[1]) != acq:
                    self.c.add_edge((h[0], h[1]), acq,
                                    self.rel, node.lineno)
                elif not self._is_rlock(acq):
                    # same lock re-acquired somewhere inside the
                    # callee: the interprocedural twin of the lexical
                    # re-acquire check above
                    self.c.findings.append(Finding(
                        NAME, self.rel, node.lineno,
                        f"re-acquiring {self._lname(h)} via "
                        f"{callee.qualname}() while already held "
                        "(deadlock with a non-reentrant Lock)"))
        if why is None:       # don't double-report a direct block
            locks = ", ".join(self._lname(h) for h in held)
            seen = set()
            for reason, via, _ in closure.blocking:
                via = via or callee.qualname
                if (reason, via) in seen:
                    continue
                seen.add((reason, via))
                self.c.findings.append(Finding(
                    NAME, self.rel, node.lineno,
                    f"blocking call {reason} reached via {via}() "
                    f"while holding {locks} -- move the slow work "
                    "outside the lock"))


# ---------------------------------------------------------------------------
# whole-package checker

class _Checker:
    def __init__(self, ctx):
        self.ctx = ctx
        self.graph = cg.get(ctx)
        self.findings: list = []
        (self.specs, self.module_guards,
         decl_findings) = _collect(ctx, self.graph)
        self.findings.extend(decl_findings)
        self.atomic_writes: dict = {}
        self.edges: dict = {}        # (A)->(B) : first site

    def declared_acquires(self, closure: "cg.Closure") -> set:
        """The subset of a closure's acquisitions this checker
        reasons about: declared class locks + declared module locks."""
        out = set()
        for ty, attr in closure.acquires:
            spec = self.specs.get(ty)
            if spec is not None and attr in spec.locks:
                out.add((ty, attr))
        for rel, name in closure.global_acquires:
            mg = self.module_guards.get(rel)
            if mg is not None and name in mg.locks:
                out.add(((MODULE, rel), name))
        return out

    def add_edge(self, a, b, rel, line) -> None:
        self.edges.setdefault((a, b), (rel, line))

    # -- the run ---------------------------------------------------------

    def run(self) -> list:
        if not self.specs and not self.module_guards:
            return self.findings     # nothing declared, nothing to do
        # a file that never NAMES a declared class (or a factory whose
        # return annotation is one, or a GUARDED_BY table) cannot type
        # an expression to one, so it can neither access a guarded
        # attribute nor hold a declared lock -- skip its (expensive)
        # per-function analysis entirely.  Typing always needs the
        # name in source: construction, annotation, or factory call.
        needles = set(self.specs) | {"GUARDED_BY"}
        needles.update(f for f, c in self.graph.returns.items()
                       if c in self.specs)
        for path in self.ctx.package_files():
            try:
                src = self.ctx.source(path)
            except OSError:
                continue
            if not any(n in src for n in needles):
                continue        # (before parse: skips the parse too)
            mod = self.graph.load_file(path)
            if mod is None:
                continue
            rel = self.ctx.rel(path)
            self._analyze_scopes(mod.tree, rel, mod, None)
        self._check_extern()
        self._check_atomic_writers()
        self._check_cycles()
        return self.findings

    def _analyze_scopes(self, node, rel, mod, cls) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                spec = self.specs.get(child.name)
                self._analyze_scopes(child, rel, mod, spec)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                scope = cg.TypeScope(
                    self.graph, child, mod,
                    cls.name if cls is not None else None)
                _FnAnalysis(self, child, rel, cls, child.name,
                            scope).analyze()
                # nested defs (closures) are separate, lock-free scopes
                self._analyze_scopes(child, rel, mod, None)

    def _check_extern(self) -> None:
        for spec in self.specs.values():
            if not spec.extern:
                continue
            ci = self.graph.classes.get(spec.name)
            if ci is None:
                continue
            for mname, fi in ci.methods.items():
                scope = self.graph.scope(fi)
                ana = _FnAnalysis(self, fi.node, spec.rel, spec,
                                  mname, scope)
                for node in ast.walk(fi.node):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            if ana._lock_of_with(item.context_expr):
                                self.findings.append(Finding(
                                    NAME, spec.rel, node.lineno,
                                    f"{spec.name} is declared "
                                    "<extern> (serialized by its "
                                    "caller) but acquires a declared "
                                    "lock itself -- invisible to the "
                                    "callers' lock ordering"))

    def _check_atomic_writers(self) -> None:
        for (cname, attr), sites in sorted(self.atomic_writes.items()):
            writers = {(c, f) for (_, _, c, f) in sites}
            outside = [(r, ln) for (r, ln, c, _) in sites
                       if c != cname]
            if outside:
                r, ln = outside[0]
                self.findings.append(Finding(
                    NAME, r, ln,
                    f"{cname}.{attr} is <atomic> (single-writer "
                    "latched flag) but assigned from outside "
                    f"{cname} -- promote it to a guarded attribute"))
            if len(writers) > 1:
                r, ln = sites[0][0], sites[0][1]
                self.findings.append(Finding(
                    NAME, r, ln,
                    f"{cname}.{attr} is <atomic> but written from "
                    f"{len(writers)} methods "
                    f"({sorted(f for _, f in writers)}) -- the "
                    "single-writer exemption no longer holds; guard "
                    "it with a lock"))

    def _check_cycles(self) -> None:
        graph: dict = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
        state: dict = {}       # node -> 1 (on stack) / 2 (done)
        stack: list = []

        def _name(n):
            c, l = n
            if isinstance(c, tuple):
                return f"{c[1]}:{l}"
            return f"{c}.{l}"

        def dfs(n):
            state[n] = 1
            stack.append(n)
            for m in sorted(graph.get(n, ()), key=_name):
                if state.get(m) == 1:
                    cyc = stack[stack.index(m):] + [m]
                    names = " -> ".join(_name(c) for c in cyc)
                    rel, line = self.edges[(n, m)]
                    self.findings.append(Finding(
                        NAME, rel, line,
                        f"lock-order cycle: {names} -- two threads "
                        "taking these locks in opposite order "
                        "deadlock"))
                elif state.get(m) is None:
                    dfs(m)
            stack.pop()
            state[n] = 2

        for n in sorted(graph, key=_name):
            if state.get(n) is None:
                dfs(n)


def run(ctx) -> list:
    return _Checker(ctx).run()
