"""Worker pipelining-contract hygiene (absorbed from
tools/check_worker_contract.py).

``runtime/worker.py``'s ``submit_or_process`` pipelines a worker only
when its ``process`` carries ``_submit_based = True``; everything else
runs serially.  Every class in the package defining a ``process``
method must declare its stance in its own body, exactly one of:

  1. ``process._submit_based = True`` -- and then the class must also
     define ``submit`` itself (an inherited submit under an
     overridden process bypasses the override's sweep logic);
  2. ``process._serial_only = True`` -- an explicit "do not pipeline
     this worker".
"""

from __future__ import annotations

import ast

from dprf_tpu.analysis import Finding

NAME = "worker-contract"
DESCRIPTION = ("every process() override declares _submit_based "
               "(with its own submit) or _serial_only")


def _marker_assignments(cls: ast.ClassDef):
    """The ``process.<attr> = True`` statements in a class body."""
    for node in cls.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "process"
                and isinstance(node.value, ast.Constant)
                and node.value.value is True):
            yield t.attr


def run(ctx) -> list:
    out = []
    for path in ctx.package_files():
        try:
            if "def process" not in ctx.source(path):
                continue     # parse prefilter: no override, no finding
        except OSError:
            continue
        idx = ctx.index(path)
        if idx is None:
            continue
        rel = ctx.rel(path)
        for node in idx.classes:
            defs = {n.name for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
            if "process" not in defs:
                continue
            markers = set(_marker_assignments(node))
            where = f"class {node.name}"
            if "_submit_based" in markers and "_serial_only" in markers:
                out.append(Finding(
                    NAME, rel, node.lineno,
                    f"{where} marks process BOTH _submit_based and "
                    "_serial_only -- pick one"))
            elif "_submit_based" in markers:
                if "submit" not in defs:
                    out.append(Finding(
                        NAME, rel, node.lineno,
                        f"{where} marks process._submit_based but "
                        "defines no submit() of its own -- an "
                        "inherited submit bypasses the overridden "
                        "process; define submit or mark "
                        "process._serial_only"))
            elif "_serial_only" not in markers:
                out.append(Finding(
                    NAME, rel, node.lineno,
                    f"{where} overrides process() without declaring "
                    "its pipelining stance -- set `process."
                    "_submit_based = True` (and define submit) or "
                    "`process._serial_only = True` after the def; an "
                    "unmarked override silently degrades "
                    "submit_or_process to the serial path"))
    return out
