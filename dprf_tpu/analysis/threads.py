"""Thread & resource lifecycle analyzer.

The serve plane and elastic fleet multiply threads, sockets, and file
streams; every one of those is a leak or a hang waiting for a missed
release.  The discipline, machine-checked:

**Threads** -- every ``threading.Thread(...)`` constructed must be

  - ``daemon=True`` at construction (or ``t.daemon = True`` before
    start), so process exit can never hang on it; or
  - joined: a local thread needs a ``t.join()`` in the same function
    (or be returned / stored on ``self`` -- ownership transfers); a
    ``self.x = Thread(...)`` needs a ``self.x.join()`` in SOME method
    of the class (the shutdown path).
  - an unbound non-daemon ``Thread(...).start()`` can never be
    joined: a leak by construction.

**Resources** -- ``open(...)``, ``socket.socket(...)``,
``socket.create_connection(...)``, and ``<sock>.makefile(...)``
acquired OUTSIDE a ``with`` must be released:

  - a local must be ``.close()``d in a ``finally`` or unconditionally
    (a close only SOME branches reach is flagged: the other path
    leaks), or returned / stored on ``self`` (ownership transfer);
  - a ``self.attr = <acquire>`` must be declared in the module-level
    ``RELEASES`` table and the declared releaser must actually close
    it::

        RELEASES = {"CoordinatorClient": {"_sock": "close",
                                          "_fh": "close"}}

    maps attr -> the method that releases it.  The analyzer verifies
    the declared method exists and contains a
    ``self.<attr>.close()``-style call (close/server_close/shutdown/
    terminate/release/detach).  Stale declarations (unknown class,
    unknown method, releaser that never releases) are findings too;
  - an acquire that is immediately chained (``open(p).close()``), a
    ``with`` context, or a ``return`` value is fine by construction;
    one passed straight into another call (``json.load(open(p))``)
    leaks on that call's exceptions and is flagged.

**Condition variables** -- for every ``threading.Condition(...)``
(class attr or local):

  - ``.wait()`` must be called with the condition held (lexically
    inside ``with <cond>:``, or in a method annotated
    ``_holds_lock = "<cond attr>"``) AND inside a ``while`` re-check
    loop -- an ``if``-guarded wait misses spurious wakeups;
    ``.wait_for()`` carries its own predicate and is exempt from the
    ``while`` rule;
  - ``.notify()`` / ``.notify_all()`` must be called with the
    condition held.

Only DIRECT constructions are tracked (``x = Thread(...)``, ``self.cv
= threading.Condition()``); a thread built by a helper is the
helper's to discipline.  ``Event.wait`` is not ``Condition.wait``:
only objects the analyzer saw constructed as Conditions are checked.
"""

from __future__ import annotations

import ast
import re

from dprf_tpu.analysis import Finding
from dprf_tpu.analysis import callgraph as cg
from dprf_tpu.analysis.callgraph import (const_str, expr_key,
                                         walk_expr, walk_scope)

NAME = "threads"
DESCRIPTION = ("thread join/daemon discipline, socket/file release "
               "(RELEASES tables), and Condition wait/notify rules")
#: declaration tables --explain renders for this check
DECL_TABLES = ("RELEASES",)

#: method names that count as releasing a resource
RELEASE_CALLS = {"close", "server_close", "shutdown", "terminate",
                 "release", "detach"}

#: word-boundary only -- a lookbehind here (to reject ``.open(``)
#: costs ~0.25 s over the package; a false prefilter hit only costs
#: one cached parse, the walker itself ignores attribute ``open`` calls
_PREFILTER_RE = re.compile(
    r"\b(?:Thread|Condition|open|makefile|create_connection)\s*\(|"
    r"\bsocket\s*\.\s*socket\s*\(|\bRELEASES\b")


def _is_call_to(node, names: set, qualified: set) -> bool:
    """Call whose func is a bare Name in ``names`` or a
    ``mod.attr`` / ``.attr`` pair in ``qualified`` (module part None
    matches any base -- the ``.makefile()`` shape)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in names
    if isinstance(f, ast.Attribute):
        if (None, f.attr) in qualified:
            return True
        if isinstance(f.value, ast.Name):
            return (f.value.id, f.attr) in qualified
    return False


def _is_thread_ctor(node) -> bool:
    return _is_call_to(node, {"Thread"}, {("threading", "Thread")})


def _is_condition_ctor(node) -> bool:
    return _is_call_to(node, {"Condition"},
                       {("threading", "Condition")})


def _is_acquire(node) -> bool:
    return _is_call_to(
        node, {"open"},
        {("socket", "socket"), ("socket", "create_connection"),
         (None, "makefile")})


def _kw_true(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _parse_releases(mod) -> tuple:
    """(releases: {class: {attr: (method, decl line)}}, findings)."""
    out: dict = {}
    findings: list = []
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "RELEASES"):
            continue
        v = node.value
        if not isinstance(v, ast.Dict):
            findings.append(Finding(
                NAME, mod.rel, node.lineno,
                "RELEASES must be a dict literal "
                '{"Class": {"attr": "method"}}'))
            continue
        for ck, cv in zip(v.keys, v.values):
            cname = const_str(ck)
            if cname is None or not isinstance(cv, ast.Dict):
                findings.append(Finding(
                    NAME, mod.rel, node.lineno,
                    "RELEASES entries must map a class-name string "
                    "to an {attr: method} dict literal"))
                continue
            spec = out.setdefault(cname, {})
            for ak, av in zip(cv.keys, cv.values):
                attr, meth = const_str(ak), const_str(av)
                if attr is None or meth is None:
                    findings.append(Finding(
                        NAME, mod.rel, node.lineno,
                        f"RELEASES[{cname!r}] must map attr-name "
                        "strings to releaser-method-name strings"))
                    continue
                spec[attr] = (meth, node.lineno)
    return out, findings


class _Walker:
    """One function body's lifecycle walk.  Tracks each site's
    control context: conditional depth (If/For/While/except nesting),
    ``finally`` membership, the ``with`` contexts held, and whether a
    ``while`` loop encloses it."""

    def __init__(self):
        self.threads: dict = {}      # local name -> (line, depth)
        self.resources: dict = {}    # local name -> (line, depth)
        self.attr_threads: list = []   # (attr key, line, daemon?)
        self.attr_resources: list = []  # (attr key, line)
        self.local_conds: set = set()
        self.joins: set = set()      # expr keys .join()ed
        self.daemon_sets: set = set()  # names with x.daemon = True
        self.closes: dict = {}       # expr key -> [(depth, in_fin)]
        self.returned: set = set()
        self.stored: set = set()     # locals moved onto attributes
        self.loose: list = []        # (kind, line): unbound ctors
        self.cond_uses: list = []  # (key, kind, line, withs, in_while)
        self._exempt: set = set()    # node ids consumed structurally

    def walk(self, fn) -> None:
        self._body(fn.body, 0, False, (), False)

    # -- statement walk ---------------------------------------------------

    def _body(self, stmts, depth, in_fin, withs, in_while) -> None:
        for st in stmts:
            self._stmt(st, depth, in_fin, withs, in_while)

    def _stmt(self, st, depth, in_fin, withs, in_while) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return                      # separate scopes
        if isinstance(st, ast.Assign) and len(st.targets) == 1:
            self._assign(st, depth, in_fin, withs, in_while)
            return
        if isinstance(st, ast.Return):
            if isinstance(st.value, ast.Name):
                self.returned.add(st.value.id)
            elif st.value is not None:
                # `return open(...)`: ownership moves to the caller
                self._exempt.add(id(st.value))
            if st.value is not None:
                self._exprs(st.value, depth, in_fin, withs, in_while)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            new_withs = list(withs)
            for item in st.items:
                k = expr_key(item.context_expr)
                if k is not None:
                    new_withs.append(k)
                # `with <acquire>(...) as x:` releases by construction
                self._exempt.add(id(item.context_expr))
                self._exprs(item.context_expr, depth, in_fin, withs,
                            in_while)
            self._body(st.body, depth, in_fin, tuple(new_withs),
                       in_while)
            return
        if isinstance(st, ast.Try):
            self._body(st.body, depth, in_fin, withs, in_while)
            for h in st.handlers:
                self._body(h.body, depth + 1, in_fin, withs, in_while)
            self._body(st.orelse, depth + 1, in_fin, withs, in_while)
            self._body(st.finalbody, depth, True, withs, in_while)
            return
        if isinstance(st, ast.While):
            self._exprs(st.test, depth, in_fin, withs, in_while)
            self._body(st.body, depth + 1, in_fin, withs, True)
            self._body(st.orelse, depth + 1, in_fin, withs, in_while)
            return
        if isinstance(st, (ast.If, ast.For, ast.AsyncFor)):
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.stmt):
                    self._stmt(child, depth + 1, in_fin, withs,
                               in_while)
                else:
                    self._exprs(child, depth, in_fin, withs, in_while)
            return
        self._exprs(st, depth, in_fin, withs, in_while)

    def _assign(self, st: ast.Assign, depth, in_fin, withs,
                in_while) -> None:
        t = st.targets[0]
        v = st.value
        # x.daemon = True  (post-construction daemonization)
        if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                and isinstance(t.value, ast.Name) \
                and isinstance(v, ast.Constant) and v.value is True:
            self.daemon_sets.add(t.value.id)
            return
        if isinstance(t, ast.Name):
            if _is_thread_ctor(v):
                self._exempt.add(id(v))
                if not _kw_true(v, "daemon"):
                    self.threads[t.id] = (v.lineno, depth)
            elif _is_acquire(v):
                self._exempt.add(id(v))
                self.resources[t.id] = (v.lineno, depth)
            elif _is_condition_ctor(v):
                self.local_conds.add(t.id)
        elif isinstance(t, ast.Attribute):
            key = expr_key(t)
            if _is_thread_ctor(v):
                self._exempt.add(id(v))
                if key is not None:
                    self.attr_threads.append(
                        (key, v.lineno, _kw_true(v, "daemon")))
            elif _is_acquire(v):
                self._exempt.add(id(v))
                if key is not None:
                    self.attr_resources.append((key, v.lineno))
            elif isinstance(v, ast.Name):
                self.stored.add(v.id)       # self.x = local: transfer
        self._exprs(st.value, depth, in_fin, withs, in_while)

    # -- expression walk --------------------------------------------------

    def _exprs(self, node, depth, in_fin, withs, in_while) -> None:
        # walk_expr prunes nested def/lambda SUBTREES (their bodies
        # are not this function's control flow)
        for n in walk_expr(node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Attribute):
                base_key = expr_key(f.value)
                if f.attr == "join" and base_key is not None:
                    self.joins.add(base_key)
                elif f.attr in RELEASE_CALLS:
                    if _is_acquire(f.value):
                        # open(...).close() chain: fine by construction
                        self._exempt.add(id(f.value))
                    elif base_key is not None:
                        self.closes.setdefault(base_key, []).append(
                            (depth, in_fin))
                elif f.attr in ("wait", "wait_for", "notify",
                                "notify_all") and base_key is not None:
                    self.cond_uses.append(
                        (base_key, f.attr, n.lineno, withs, in_while))
                elif f.attr == "start" and _is_thread_ctor(f.value):
                    # Thread(...).start(): bindless; daemon or leak
                    self._exempt.add(id(f.value))
                    if not _kw_true(f.value, "daemon"):
                        self.loose.append(("thread", f.value.lineno))
            if id(n) in self._exempt:
                continue
            if _is_thread_ctor(n):
                if not _kw_true(n, "daemon"):
                    self.loose.append(("thread", n.lineno))
            elif _is_acquire(n):
                self.loose.append(("resource", n.lineno))

    # -- verdicts ---------------------------------------------------------

    def finish(self, rel: str, find) -> None:
        for kind, line in self.loose:
            if kind == "thread":
                find(rel, line,
                     "unbound non-daemon Thread can never be joined "
                     "-- bind it (to join on shutdown) or pass "
                     "daemon=True")
            else:
                find(rel, line,
                     "resource acquired and passed straight on -- "
                     "nothing can release it if the consumer raises; "
                     "bind it and use `with` or close it in a "
                     "finally")
        for name, (line, _depth) in self.threads.items():
            if name in self.daemon_sets or name in self.returned \
                    or name in self.stored or name in self.joins:
                continue
            find(rel, line,
                 f"non-daemon Thread {name!r} is never joined in this "
                 "function (and never returned) -- pass daemon=True "
                 "or join it on every shutdown path")
        for name, (line, depth) in self.resources.items():
            if name in self.returned or name in self.stored:
                continue
            closes = self.closes.get(name, [])
            if not closes:
                find(rel, line,
                     f"resource {name!r} acquired outside `with` is "
                     "never released here -- close it in a finally, "
                     "use `with`, or transfer ownership (return / "
                     "store on self with a RELEASES entry)")
            elif not any(fin or d <= depth for d, fin in closes):
                find(rel, line,
                     f"resource {name!r} is closed on only some "
                     "paths -- move the close() into a finally (or "
                     "an unconditional statement)")


def _check_cond_uses(w: _Walker, conds: set, holds, rel,
                     find) -> None:
    for key, kind, line, withs, in_while in w.cond_uses:
        if key not in conds:
            continue
        short = key.split(".", 1)[1] if key.startswith("self.") \
            else key
        held = key in withs or (isinstance(holds, str)
                                and holds in (key, short))
        if not held:
            find(rel, line,
                 f"Condition.{kind}() on {key!r} without holding it "
                 f"-- wrap in `with {key}:`")
            continue
        if kind == "wait" and not in_while:
            find(rel, line,
                 f"Condition.wait() on {key!r} outside a `while` "
                 "re-check loop -- spurious wakeups make an "
                 "if-guarded wait a race; re-check the predicate in "
                 "a while (or use wait_for)")


def _scan_class(ci, releases: dict, rel, find) -> None:
    """Class-level lifecycle: attr threads joined somewhere in the
    class, attr resources declared in RELEASES with a real releaser,
    Condition attrs checked across every method."""
    walkers: dict = {}
    attr_joins: set = set()
    cond_attrs: set = set()
    for mname, fi in ci.methods.items():
        w = _Walker()
        w.walk(fi.node)
        walkers[mname] = w
        attr_joins.update(w.joins)
        for st in walk_scope(fi.node):
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Attribute) \
                    and _is_condition_ctor(st.value):
                k = expr_key(st.targets[0])
                if k is not None:
                    cond_attrs.add(k)
    for mname, w in walkers.items():
        w.finish(rel, find)          # local lifecycle per method
        for attr, line, daemon in w.attr_threads:
            if daemon or attr in attr_joins:
                continue
            find(rel, line,
                 f"{ci.name}: non-daemon Thread stored on {attr!r} "
                 "is never joined by any method -- pass daemon=True "
                 "or join it on the shutdown path")
        for attr, line in w.attr_resources:
            short = attr.split(".", 1)[1] if "." in attr else attr
            decl = releases.get(ci.name, {}).get(short)
            if decl is None:
                find(rel, line,
                     f"{ci.name}.{short} holds an acquired resource "
                     "but is not declared in a module-level RELEASES "
                     "table -- declare RELEASES = "
                     f'{{"{ci.name}": {{"{short}": '
                     '"<releaser method>"}}')
                continue
            meth, dline = decl
            rw = walkers.get(meth)
            if rw is None:
                find(rel, dline,
                     f"RELEASES declares {ci.name}.{short} released "
                     f"by {meth!r}, but {ci.name} has no such method")
            elif attr not in rw.closes:
                mfi = ci.methods.get(meth)
                find(rel, mfi.node.lineno if mfi else ci.line,
                     f"RELEASES declares {ci.name}.{short} released "
                     f"by {meth}(), but {meth}() never closes it")
    for mname, w in walkers.items():
        holds = ci.method_marks.get(mname, {}).get("_holds_lock")
        _check_cond_uses(w, cond_attrs | w.local_conds, holds, rel,
                         find)


def run(ctx) -> list:
    findings: list = []

    def find(rel, line, msg):
        findings.append(Finding(NAME, rel, line, msg))

    graph = cg.get(ctx)
    for path in ctx.package_files():
        try:
            src = ctx.source(path)
        except OSError:
            continue
        if not _PREFILTER_RE.search(src):
            continue
        mod = graph.load_file(path)
        if mod is None:
            continue
        rel = ctx.rel(path)
        releases, shape_findings = _parse_releases(mod)
        findings.extend(shape_findings)
        for cname, spec in releases.items():
            if cname not in mod.classes and spec:
                _meth, dline = next(iter(spec.values()))
                find(rel, dline,
                     f"RELEASES declares unknown class {cname!r}")
        for ci in mod.classes.values():
            _scan_class(ci, releases, rel, find)
        for fi in mod.functions.values():
            w = _Walker()
            w.walk(fi.node)
            w.finish(rel, find)
            _check_cond_uses(w, set(w.local_conds), None, rel, find)
    return findings
