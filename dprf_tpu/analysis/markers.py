"""Tier-marker hygiene (absorbed from tools/check_markers.py).

The smoke tier promises <5 minutes (pytest.ini); its wall time is
runtime-guarded by tests/conftest.py.  What the runtime guard cannot
catch is a NEW test that compiles device pipelines and rides into a
tier nobody budgeted, because its author never declared a tier at all.

Rule: any test module that uses Pallas kernels or JAX device engines
-- statically imports ``dprf_tpu.ops.pallas_*`` /
``dprf_tpu.engines.device*`` anywhere (module or function level), or
requests ``device="jax"`` in source -- must carry at least one
``pytest.mark.smoke`` / ``pytest.mark.compileheavy`` /
``pytest.mark.slow`` marker.
"""

from __future__ import annotations

import ast
import os
import re

from dprf_tpu.analysis import Finding

NAME = "markers"
DESCRIPTION = ("test modules using Pallas/device engines declare an "
               "explicit tier marker")

HEAVY_PREFIXES = ("dprf_tpu.ops.pallas_", "dprf_tpu.engines.device")
TIER_MARK_RE = re.compile(r"pytest\.mark\.(smoke|compileheavy|slow)\b")
DEVICE_USE_RE = re.compile(r"""device\s*=\s*["']jax["']""")


def _imported_modules(import_nodes):
    """Every dotted module name the file imports, at any nesting depth
    (tests routinely import device engines inside test functions)."""
    for node in import_nodes:
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            yield node.module
            for alias in node.names:
                # `from dprf_tpu.ops import pallas_mask` names the
                # heavy module in the alias, not in node.module
                yield f"{node.module}.{alias.name}"


def run(ctx) -> list:
    out = []
    for path in ctx.test_files():
        if not os.path.basename(path).startswith("test_"):
            continue
        try:
            src = ctx.source(path)
        except OSError:
            continue
        if TIER_MARK_RE.search(src):
            continue     # marked: never a finding, and needs no parse
        idx = ctx.index(path)
        if idx is None:
            continue          # parse failure surfaces via the runner
        heavy = (any(m.startswith(HEAVY_PREFIXES)
                     for m in _imported_modules(idx.imports))
                 or DEVICE_USE_RE.search(src) is not None)
        if heavy:
            out.append(Finding(
                NAME, ctx.rel(path), 1,
                "uses Pallas/device engines but declares no tier "
                "marker -- add pytest.mark.smoke (fast, "
                "budget-checked), compileheavy, or slow"))
    return out
