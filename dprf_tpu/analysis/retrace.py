"""JAX retrace / host-sync lint over the declared hot paths.

The compile cache (PR 3) and the pipelined loops (PR 5) eliminated
compile cost and device idle -- but neither can see a SILENT
recompile (a jitted step handed a new argument shape every iteration)
or a host-sync stall (``.item()`` mid-sweep serializing the device
stream against the Python interpreter).  Both bug classes live in the
few functions that drive the device per work unit; this analyzer
checks exactly those, declared per module::

    HOT_PATHS = ("Coordinator.run", "worker_loop")

names functions / ``Class.method``s in the declaring module whose
LOOPS are device hot paths.  Stale entries (no such function) are
findings.  Inside any loop of a hot path:

**Host syncs** -- each of these forces the host to wait for the
device stream, turning the pipelined sweep back into lockstep:

  - ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` anywhere
    in the loop (array-only methods: flagged unconditionally);
  - ``bool()`` / ``int()`` / ``float()`` / ``np.asarray()`` /
    ``np.array()`` applied to a DEVICE value -- a name assigned from
    a jitted-entry call (or arithmetic on one) in the same function;
    ``jnp.*`` stays on device and is exempt;
  - an ``if``/``while`` truth-test directly on a device value (the
    implicit ``bool()``); ``x is None``-style comparisons are fine;
  - a call passing a device value into a helper that (transitively,
    over the call graph) performs one of the syncs above -- the
    helper-laundered ``.item()``.

The designed pattern -- accumulate the flag ON DEVICE across the
loop, ``copy_to_host_async()``, read it once per unit AFTER the loop
-- is untouched: only in-loop syncs are findings.

**Silent retraces** -- calls INTO a jitted/AOT entry point inside a
hot loop where:

  - an argument's SHAPE derives from a loop-varying Python value
    (``step(xs[:n])`` with ``n`` reassigned in the loop): every new
    shape is a full retrace+compile mid-sweep.  Pad to a fixed
    ladder, or make the size a static argument with a bounded set of
    values;
  - a loop-varying value lands on a ``static_argnums`` position: one
    retrace per distinct value -- fine for a bounded power-of-two
    ladder, a compile storm for ``range()`` counters; the finding
    asks for the bound.

A "jitted entry" is resolved interprocedurally: a function decorated
``@jax.jit`` (or ``@partial(jax.jit, ...)``); a name or ``self.attr``
assigned from ``jax.jit(...)``; or assigned from a FACTORY whose
return value the call graph resolves to a jit-wrapped closure (the
``make_*_crack_step`` idiom: an inner ``@jax.jit def step`` returned
by the factory).  ``static_argnums`` is read off whichever wrapper
declared it.

**Device taint** flows through plain names AND attribute targets:
``self._flag = self.step(...)`` taints ``self._flag`` exactly like
``flag = self.step(...)`` taints ``flag`` -- a later ``int(self._flag)``
or ``if self._flag:`` in the loop is the same silent sync.

**Sampled perf probes** (telemetry/perf.py) sync BY DESIGN: honest
per-phase attribution needs block_until_ready boundaries, and
sampling keeps them off the steady-state path.  A hot-path module
declares its probe helpers in an explicit ``PERF_PROBE`` table::

    PERF_PROBE = ("dprf_tpu.telemetry.perf.probe_pending",)

Entries are dotted ``package.module.function`` paths (or local
``func`` / ``Class.method`` names); calls that resolve to a declared
probe are exempt from the sync rules.  Stale entries (no such
function) are findings -- the table is a declaration, not a
suppression.

Scope: only modules declaring ``HOT_PATHS`` are analyzed, and only
loops inside the named functions -- warmup, decode-after-flag, and
CLI paths sync by design and stay out of the declaration.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from dprf_tpu.analysis import Finding
from dprf_tpu.analysis import callgraph as cg
from dprf_tpu.analysis.callgraph import (const_str, expr_key, walk_expr,
                                         walk_scope)

NAME = "retrace"
DESCRIPTION = ("silent-recompile and host-sync lint over the declared "
               "HOT_PATHS device loops (jit entries resolved through "
               "the call graph)")
#: declaration tables --explain renders for this check
DECL_TABLES = ("HOT_PATHS", "PERF_PROBE")

#: array-only methods that force a device sync
SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
#: builtins that force a host transfer when fed a device value
HOST_CONVERTERS = {"bool", "int", "float"}
#: host-numpy module aliases whose asarray/array sync a device value
NP_MODULES = {"np", "numpy", "onp"}
NP_SYNC_FUNCS = {"asarray", "array"}

#: helper-chain depth for the transitive sync walk
MAX_SYNC_DEPTH = 16

_PREFILTER_RE = re.compile(r"\bHOT_PATHS\b")


# ---------------------------------------------------------------------------
# jit-entry resolution

def _is_jit_ref(node) -> bool:
    """``jax.jit`` / bare ``jit``."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax")


def _static_from_kwargs(keywords) -> frozenset:
    for kw in keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        if kw.arg == "static_argnames":
            return frozenset()        # name-keyed: positions unknown
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return frozenset((v.value,))
        if isinstance(v, (ast.Tuple, ast.List)):
            out = set()
            for e in v.elts:
                if isinstance(e, ast.Constant) \
                        and isinstance(e.value, int):
                    out.add(e.value)
            return frozenset(out)
    return frozenset()


def _jit_wrapper(node) -> Optional[frozenset]:
    """If ``node`` evaluates to a jit-wrapped callable -- ``jax.jit``
    itself (a decorator ref), ``jax.jit(f, ...)``, or
    ``partial(jax.jit, ...)`` -- the static_argnums set; else None."""
    if _is_jit_ref(node):
        return frozenset()
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_ref(node.func):
        return _static_from_kwargs(node.keywords)
    f = node.func
    is_partial = (isinstance(f, ast.Name) and f.id == "partial") or \
        (isinstance(f, ast.Attribute) and f.attr == "partial")
    if is_partial and node.args and _is_jit_ref(node.args[0]):
        return _static_from_kwargs(node.keywords)
    return None


def _decorated_jit(fn) -> Optional[frozenset]:
    for deco in fn.decorator_list:
        st = _jit_wrapper(deco)
        if st is not None:
            return st
    return None


class _JitResolver:
    """Maps callables to their static_argnums when they are jit
    entries; factory returns resolved through the call graph."""

    def __init__(self, graph):
        self.g = graph
        self._factory_memo: dict = {}

    def factory_returns_jit(self, fi, depth: int = 0) \
            -> Optional[frozenset]:
        """static_argnums if calling ``fi`` yields a jit-wrapped
        callable: fi itself jit-decorated, ``return jax.jit(...)``,
        or returning an inner jit-decorated def / jit-assigned name
        (the ``make_*_step`` factories); one more factory hop via the
        summary's call-assignments."""
        if depth > MAX_SYNC_DEPTH:
            return None
        key = fi.key
        if key in self._factory_memo:
            return self._factory_memo[key]
        self._factory_memo[key] = None       # cycle guard
        st = _decorated_jit(fi.node)
        if st is None:
            st = self._scan_returns(fi, depth)
        self._factory_memo[key] = st
        return st

    def _scan_returns(self, fi, depth) -> Optional[frozenset]:
        inner_jits: dict = {}
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not fi.node:
                st = _decorated_jit(n)
                if st is not None:
                    inner_jits[n.name] = st
            elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                st = _jit_wrapper(n.value)
                if st is not None:
                    inner_jits[n.targets[0].id] = st
        s = self.g.summary(fi)
        for expr in s.return_exprs:
            st = _jit_wrapper(expr)
            if st is not None:
                return st
            if isinstance(expr, ast.Name):
                st = inner_jits.get(expr.id)
                if st is not None:
                    return st
                callee = s.name_calls.get(expr.id)
                if callee is not None:
                    st = self.factory_returns_jit(callee, depth + 1)
                    if st is not None:
                        return st
        return None

    def call_static(self, call: ast.Call, sc, local_jits: dict,
                    attr_jits: dict) -> Optional[frozenset]:
        """static_argnums if this call dispatches into a jit entry."""
        f = call.func
        if isinstance(f, ast.Name):
            st = local_jits.get(f.id)
            if st is not None:
                return st
        elif isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            st = attr_jits.get(f.attr)
            if st is not None:
                return st
        callee = self.g.resolve_call(call, sc)
        if callee is not None:
            return _decorated_jit(callee.node)
        return None


def _module_attr_jits(mod, graph, resolver) -> dict:
    """attr name -> static_argnums for every ``self.attr = <jit>``
    assignment in any class of the module (subclasses assign the step
    the base-class hot loop dispatches)."""
    out: dict = {}
    for ci in mod.classes.values():
        for fi in ci.methods.values():
            sc = None
            for st in walk_scope(fi.node):
                if not (isinstance(st, ast.Assign)
                        and len(st.targets) == 1):
                    continue
                t = st.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                stat = _jit_wrapper(st.value)
                if stat is None and isinstance(st.value, ast.Call):
                    if sc is None:
                        sc = graph.scope(fi)
                    callee = graph.resolve_call(st.value, sc)
                    if callee is not None:
                        stat = resolver.factory_returns_jit(callee)
                if stat is not None:
                    out.setdefault(t.attr, stat)
    return out


# ---------------------------------------------------------------------------
# transitive sync detection

def _syncs_directly(fn) -> Optional[str]:
    # walk_scope: a sync inside a nested def/lambda the function may
    # never call in-loop is not the function's own sync
    for n in walk_scope(fn):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Attribute):
            if f.attr in SYNC_ATTRS:
                return f".{f.attr}()"
            if f.attr in NP_SYNC_FUNCS and isinstance(f.value, ast.Name) \
                    and f.value.id in NP_MODULES:
                return f"{f.value.id}.{f.attr}()"
    return None


class _SyncWalker:
    def __init__(self, graph):
        self.g = graph
        self._memo: dict = {}

    def syncs(self, fi, depth: int = 0) -> Optional[str]:
        """A sync reason reachable from ``fi`` (its own body, or any
        callee the graph resolves, depth-bounded), else None."""
        if depth > MAX_SYNC_DEPTH:
            return None
        if fi.key in self._memo:
            return self._memo[fi.key]
        self._memo[fi.key] = None            # cycle guard
        why = _syncs_directly(fi.node)
        if why is None:
            s = self.g.summary(fi)
            for _key, (callee, _line) in s.callees.items():
                sub = self.syncs(callee, depth + 1)
                if sub is not None:
                    why = f"{sub} via {callee.qualname}"
                    break
        self._memo[fi.key] = why
        return why


# ---------------------------------------------------------------------------
# hot-path declarations

def _parse_hot_paths(mod) -> tuple:
    """([(qualname, line)], shape findings)."""
    out: list = []
    findings: list = []
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "HOT_PATHS"):
            continue
        v = node.value
        if not isinstance(v, (ast.Tuple, ast.List)):
            findings.append(Finding(
                NAME, mod.rel, node.lineno,
                'HOT_PATHS must be a tuple of "func" / '
                '"Class.method" strings'))
            continue
        for e in v.elts:
            s = const_str(e)
            if s is None:
                findings.append(Finding(
                    NAME, mod.rel, node.lineno,
                    "HOT_PATHS entries must be string literals"))
                continue
            out.append((s, node.lineno))
    return out, findings


def _resolve_hot(mod, qualname: str):
    if "." in qualname:
        cls, meth = qualname.split(".", 1)
        ci = mod.classes.get(cls)
        if ci is not None:
            return ci.methods.get(meth)
        return None
    return mod.functions.get(qualname)


def _parse_probe_table(mod) -> tuple:
    """([(entry, line)], shape findings) for the module's PERF_PROBE
    declaration (the sampled-probe sync exemption)."""
    out: list = []
    findings: list = []
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "PERF_PROBE"):
            continue
        v = node.value
        if not isinstance(v, (ast.Tuple, ast.List)):
            findings.append(Finding(
                NAME, mod.rel, node.lineno,
                'PERF_PROBE must be a tuple of "pkg.mod.func" / '
                '"func" / "Class.method" strings'))
            continue
        for e in v.elts:
            s = const_str(e)
            if s is None:
                findings.append(Finding(
                    NAME, mod.rel, node.lineno,
                    "PERF_PROBE entries must be string literals"))
                continue
            out.append((s, node.lineno))
    return out, findings


def _resolve_probe(graph, mod, entry: str):
    """A PERF_PROBE entry -> FuncInfo: a dotted in-package path
    ("dprf_tpu.telemetry.perf.probe_pending"), or a local "func" /
    "Class.method" name in the declaring module.  None = stale."""
    if entry.startswith(graph.pkg + "."):
        modpath, _, fname = entry.rpartition(".")
        target = graph.load_dotted(modpath)
        if target is None:
            # "pkg.mod.Class.method" form: one more split
            modpath2, _, cls = modpath.rpartition(".")
            target = graph.load_dotted(modpath2)
            if target is None:
                return None
            ci = target.classes.get(cls)
            return ci.methods.get(fname) if ci is not None else None
        return target.functions.get(fname)
    return _resolve_hot(mod, entry)


# ---------------------------------------------------------------------------
# one hot function's walk

def _target_names(t) -> list:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    return []


def _collect_loop_vars(fn) -> set:
    """Names assigned inside any For/While body of ``fn`` -- the
    loop-varying Python values whose flow into shapes/static args is
    the retrace hazard."""
    out: set = set()

    def stmts(body, in_loop):
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                out.update(_target_names(st.target))
                stmts(st.body, True)
                stmts(st.orelse, True)
            elif isinstance(st, ast.While):
                stmts(st.body, True)
                stmts(st.orelse, True)
            else:
                if in_loop:
                    if isinstance(st, ast.Assign):
                        for t in st.targets:
                            out.update(_target_names(t))
                    elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
                        out.update(_target_names(st.target))
                for attr in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(st, attr, None)
                    if sub:
                        stmts([h for h in sub] if attr != "handlers"
                              else [s for h in sub for s in h.body],
                              in_loop)

    stmts(fn.body, False)
    return out


def _mentions(expr, names: set) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in names:
            return True
    return False


def _varying_slice(expr, loop_vars: set) -> bool:
    """``xs[:n]``-style subscript whose slice bound is loop-varying --
    a new argument shape every iteration."""
    if not (isinstance(expr, ast.Subscript)
            and isinstance(expr.slice, ast.Slice)):
        return False
    for bound in (expr.slice.lower, expr.slice.upper, expr.slice.step):
        if bound is not None and _mentions(bound, loop_vars):
            return True
    return False


class _HotWalker:
    """Order-sensitive walk of one hot function: device-value taint
    flows forward through assignments; findings fire only inside
    loops."""

    def __init__(self, fi, graph, resolver, syncer, local_jits,
                 attr_jits, loop_vars, rel, find, probe_keys=()):
        self.fi = fi
        self.g = graph
        self.resolver = resolver
        self.syncer = syncer
        self.local_jits = local_jits
        self.attr_jits = attr_jits
        self.loop_vars = loop_vars
        self.rel = rel
        self.find = find
        #: FuncInfo keys of the module's declared PERF_PROBE helpers:
        #: calls resolving to these are exempt from the sync rules
        self.probe_keys = frozenset(probe_keys)
        self.sc = graph.scope(fi)
        #: tainted device values: plain names AND dotted attribute
        #: chains ("self._flag") -- expr_key normalized
        self.taint: set = set()
        #: names assigned from a loop-varying-shape slice in the loop
        self.vshape: set = set()

    def walk(self) -> None:
        self._stmts(self.fi.node.body, False)

    # -- statements -------------------------------------------------------

    def _stmts(self, body, in_loop) -> None:
        for st in body:
            self._stmt(st, in_loop)

    def _stmt(self, st, in_loop) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return
        if isinstance(st, ast.Assign):
            self._expr(st.value, in_loop)
            tainted = self._tainted(st.value)
            vshape = in_loop and (_varying_slice(st.value,
                                                 self.loop_vars))
            for t in st.targets:
                names = _target_names(t)
                if not names and isinstance(t, ast.Attribute):
                    # attribute targets carry taint too: ``self._flag
                    # = self.step(...)`` must not launder the device
                    # value out of the name-only set
                    k = expr_key(t)
                    if k is not None:
                        names = [k]
                for name in names:
                    (self.taint.add if tainted
                     else self.taint.discard)(name)
                    (self.vshape.add if vshape
                     else self.vshape.discard)(name)
            return
        if isinstance(st, ast.AugAssign):
            self._expr(st.value, in_loop)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter, in_loop)
            self._stmts(st.body, True)
            self._stmts(st.orelse, True)
            return
        if isinstance(st, ast.While):
            self._truth_test(st.test, True)
            self._expr(st.test, True)
            self._stmts(st.body, True)
            self._stmts(st.orelse, True)
            return
        if isinstance(st, ast.If):
            self._truth_test(st.test, in_loop)
            self._expr(st.test, in_loop)
            self._stmts(st.body, in_loop)
            self._stmts(st.orelse, in_loop)
            return
        if isinstance(st, ast.Try):
            self._stmts(st.body, in_loop)
            for h in st.handlers:
                self._stmts(h.body, in_loop)
            self._stmts(st.orelse, in_loop)
            self._stmts(st.finalbody, in_loop)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr, in_loop)
            self._stmts(st.body, in_loop)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, in_loop)

    def _tainted(self, expr) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in self.taint:
                return True
            if isinstance(n, ast.Attribute):
                k = expr_key(n)
                if k is not None and k in self.taint:
                    return True
            if isinstance(n, ast.Call) and self.resolver.call_static(
                    n, self.sc, self.local_jits,
                    self.attr_jits) is not None:
                return True
        return False

    def _truth_test(self, test, in_loop) -> None:
        """``if x:`` / ``while x:`` on a device value is an implicit
        bool() -- a sync.  Only direct names (and ``not x`` /
        ``x and y`` over them) fire; comparisons are value tests the
        author wrote deliberately."""
        if not in_loop:
            return
        nodes = [test]
        while nodes:
            n = nodes.pop()
            name = None
            if isinstance(n, ast.Name) and n.id in self.taint:
                name = n.id
            elif isinstance(n, ast.Attribute):
                k = expr_key(n)
                if k is not None and k in self.taint:
                    name = k
            if name is not None:
                self.find(self.rel, n.lineno,
                          f"implicit bool() on device value {name!r} "
                          "inside the hot loop -- a host sync every "
                          "iteration; accumulate the flag on device "
                          "and read it once after the loop")
                return
            if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
                nodes.append(n.operand)
            elif isinstance(n, ast.BoolOp):
                nodes.extend(n.values)

    # -- expressions ------------------------------------------------------

    def _expr(self, expr, in_loop) -> None:
        # walk_expr prunes nested def/lambda subtrees: a lambda built
        # in the loop but invoked later is not an in-loop sync
        for n in walk_expr(expr):
            if not isinstance(n, ast.Call):
                continue
            self._call(n, in_loop)

    def _call(self, call: ast.Call, in_loop) -> None:
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in SYNC_ATTRS and in_loop:
                self.find(self.rel, call.lineno,
                          f".{f.attr}() inside the hot loop forces a "
                          "device sync every iteration -- hoist it "
                          "after the loop (accumulate on device)")
                return
            if f.attr in NP_SYNC_FUNCS and in_loop \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in NP_MODULES \
                    and call.args and self._tainted(call.args[0]):
                self.find(self.rel, call.lineno,
                          f"{f.value.id}.{f.attr}() on a device value "
                          "inside the hot loop is a host transfer "
                          "every iteration -- decode after the loop, "
                          "behind the unit flag")
                return
        elif isinstance(f, ast.Name):
            if f.id in HOST_CONVERTERS and in_loop and call.args \
                    and self._tainted(call.args[0]):
                self.find(self.rel, call.lineno,
                          f"{f.id}() on a device value inside the hot "
                          "loop is a host sync every iteration -- "
                          "keep the value on device (jnp) or read it "
                          "once after the loop")
                return
        static = self.resolver.call_static(call, self.sc,
                                           self.local_jits,
                                           self.attr_jits)
        if static is not None:
            if in_loop:
                self._jit_args(call, static)
            return
        if not in_loop:
            return
        callee = self.g.resolve_call(call, self.sc)
        if callee is None or callee.key == self.fi.key:
            return
        if callee.key in self.probe_keys:
            # declared sampled perf probe (PERF_PROBE table): its
            # syncs are the measurement, not a bug
            return

        def _arg_tainted(a) -> bool:
            if isinstance(a, ast.Name):
                return a.id in self.taint
            if isinstance(a, ast.Attribute):
                k = expr_key(a)
                return k is not None and k in self.taint
            return False

        if any(_arg_tainted(a) for a in call.args):
            why = self.syncer.syncs(callee)
            if why is not None:
                self.find(self.rel, call.lineno,
                          f"{callee.qualname}() syncs the device "
                          f"value it is passed ({why}) inside the "
                          "hot loop -- resolve after the loop, or "
                          "keep the helper device-side")

    def _jit_args(self, call: ast.Call, static: frozenset) -> None:
        for i, a in enumerate(call.args):
            if _varying_slice(a, self.loop_vars) \
                    or (isinstance(a, ast.Name) and a.id in self.vshape):
                self.find(self.rel, call.lineno,
                          "jitted call argument has a loop-varying "
                          "shape -- a silent retrace+compile every "
                          "iteration; pad to a fixed-size ladder or "
                          "hoist the varying size to static_argnums "
                          "with a bounded value set")
                continue
            if i in static and _mentions(a, self.loop_vars):
                self.find(self.rel, call.lineno,
                          f"loop-varying value on static_argnums "
                          f"position {i} of a jitted call -- one "
                          "retrace per distinct value; bound the "
                          "ladder (powers of two) or make the "
                          "argument traced")


# ---------------------------------------------------------------------------

def run(ctx) -> list:
    findings: list = []

    def find(rel, line, msg):
        findings.append(Finding(NAME, rel, line, msg))

    graph = cg.get(ctx)
    resolver = _JitResolver(graph)
    syncer = _SyncWalker(graph)
    for path in ctx.package_files():
        try:
            src = ctx.source(path)
        except OSError:
            continue
        if not _PREFILTER_RE.search(src):
            continue
        mod = graph.load_file(path)
        if mod is None:
            continue
        rel = ctx.rel(path)
        hot, shape_findings = _parse_hot_paths(mod)
        findings.extend(shape_findings)
        probes, probe_findings = _parse_probe_table(mod)
        findings.extend(probe_findings)
        probe_keys = set()
        for entry, pline in probes:
            pfi = _resolve_probe(graph, mod, entry)
            if pfi is None:
                find(rel, pline,
                     f"PERF_PROBE declares unknown function "
                     f"{entry!r} -- stale declaration")
            else:
                probe_keys.add(pfi.key)
        if not hot:
            if probes:
                # a probe table with no hot paths exempts nothing
                find(rel, probes[0][1],
                     "PERF_PROBE declared in a module with no "
                     "HOT_PATHS -- the exemption applies to nothing")
            continue
        attr_jits = _module_attr_jits(mod, graph, resolver)
        for qualname, dline in hot:
            fi = _resolve_hot(mod, qualname)
            if fi is None:
                find(rel, dline,
                     f"HOT_PATHS declares unknown function "
                     f"{qualname!r} -- stale declaration")
                continue
            local_jits: dict = {}
            sc = graph.scope(fi)
            for st in walk_scope(fi.node):
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name):
                    stat = _jit_wrapper(st.value)
                    if stat is None and isinstance(st.value, ast.Call):
                        callee = graph.resolve_call(st.value, sc)
                        if callee is not None:
                            stat = resolver.factory_returns_jit(callee)
                    if stat is not None:
                        local_jits[st.targets[0].id] = stat
            loop_vars = _collect_loop_vars(fi.node)
            _HotWalker(fi, graph, resolver, syncer, local_jits,
                       attr_jits, loop_vars, rel, find,
                       probe_keys=probe_keys).walk()
    return findings
