"""RPC protocol contract checker.

The wire protocol (runtime/rpc.py) is newline-delimited JSON dicts:
clients build request dicts (``client.call("lease", worker_id=...,
ahead=...)``) and handlers read them (``op_lease`` reading
``msg.get("ahead")``); handlers build response dicts and clients read
those.  Nothing but convention kept the two sides' keys aligned --
protocol drift surfaced as loopback-test flakes, if at all.  This
checker extracts both sides from the AST and fails on:

  - a client calling an op with no ``op_<name>`` handler;
  - a handler reading a request key NO client ever sends;
  - a client sending a request key the handler never reads;
  - a client reading a response key the handler never returns.

Extraction is INTERPROCEDURAL over the shared call graph
(analysis/callgraph.py) -- PR 6 stopped at same-scope dataflow, which
is exactly where a helper function launders a key out of sight:

  - server side: every method named ``op_*(self, msg)`` on any class
    in the package.  Request keys = ``msg["k"]`` / ``msg.get("k")`` /
    ``"k" in msg`` in the handler, plus the same reads in any helper
    the graph can resolve that ``msg`` is passed to (transitively).
    Response keys = every string key of every dict literal plus
    ``name["k"] = ...`` constant subscript stores in the method (an
    over-approximation -- nested payload dicts widen the response set,
    which can only silence, never fabricate, a finding), plus the same
    keys in helpers whose RESULT the handler returns and helpers a
    returned dict is passed into (``fill(resp)``-style builders);
  - client side: calls whose callee is ``.call(`` / ``.send(`` /
    ``send_report(`` with a literal first argument, scanned across
    the package AND tools/; request keys are the literal keyword
    names.  ``X = client.call("op", ...)`` followed by ``X["k"]`` /
    ``X.get("k")`` / ``"k" in X`` records response reads; so does a
    direct subscript on the call, and -- through the call graph --
    ``helper(X)`` where the helper reads keys from that parameter.
    ``client.hello()`` maps to the ``hello`` op.

Transport-layer keys (framing/auth, owned by the handler loop and the
senders, not the ops): ``op``, ``clock``, ``hmac``, ``cnonce``
requests; ``ok``, ``error``, ``challenge``, ``coordinator_hmac``
responses.  Dynamic call sites (op name in a variable, ``**kw``
payloads) are skipped -- the loopback tests remain the net under
those.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from dprf_tpu.analysis import Finding
from dprf_tpu.analysis import callgraph as cg

NAME = "protocol"
DESCRIPTION = ("RPC request/response dict keys match between client "
               "call sites and op_* handlers, followed through helper "
               "functions via the call graph")

REQUEST_TRANSPORT = {"op", "clock", "hmac", "cnonce"}
RESPONSE_TRANSPORT = {"ok", "error", "challenge", "coordinator_hmac"}
#: call-attribute names treated as "send an op by literal name"
CLIENT_CALL_ATTRS = {"call", "send"}
CLIENT_CALL_NAMES = {"send_report", "send"}
#: zero-argument client methods that ARE an op under the hood
CLIENT_METHOD_OPS = {"hello": "hello"}

#: parse prefilters: a file with no handler/client call text cannot
#: contribute to the contract (the \b matches right after a dot)
_HANDLER_RE = re.compile(r"\bop_[A-Za-z0-9_]+\s*\(")
_CLIENT_RE = re.compile(r"\b(?:call|send|send_report|hello)\s*\(")

#: recursion guard for the helper-following walks (shared shape with
#: callgraph.MAX_CLOSURE_DEPTH: a deeper helper chain is an
#: architecture smell, and a pathological fixture must not hang)
_MAX_FOLLOW = 64


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# interprocedural key-following (over the shared call graph)

def _follow_param_reads(graph, fi, slot, out: dict,
                        visiting: set) -> None:
    """Keys read from the dict bound to ``slot`` in ``fi`` (a
    parameter name, or a ``*args``/``**kwargs`` element descriptor --
    callgraph slots), transitively through every resolvable helper
    the dict is passed to, including forwarding wrappers.
    ``out``: key -> (rel, line) of the read that pins it."""
    tag = (fi.key, slot)
    if tag in visiting or len(visiting) > _MAX_FOLLOW:
        return
    visiting.add(tag)
    s = graph.summary(fi)
    if isinstance(slot, str):
        for k, ln in s.param_reads.get(slot, {}).items():
            out.setdefault(k, (fi.rel, ln))
    for callee, argspec, kwspec, _line in s.calls:
        for s2 in cg.forwarded_slots(callee, argspec, kwspec, slot):
            _follow_param_reads(graph, callee, s2, out, visiting)


def _follow_param_writes(graph, fi, slot, out: dict,
                         visiting: set) -> None:
    """Keys a helper stores INTO the dict bound to ``slot``
    (``resp["k"] = ...`` response builders), transitively."""
    tag = (fi.key, slot)
    if tag in visiting or len(visiting) > _MAX_FOLLOW:
        return
    visiting.add(tag)
    s = graph.summary(fi)
    if isinstance(slot, str):
        for k, ln in s.param_writes.get(slot, {}).items():
            out.setdefault(k, (fi.rel, ln))
    for callee, argspec, kwspec, _line in s.calls:
        for s2 in cg.forwarded_slots(callee, argspec, kwspec, slot):
            _follow_param_writes(graph, callee, s2, out, visiting)


def _follow_returned_keys(graph, fi, out: dict, visiting: set) -> None:
    """Response keys ``fi`` can contribute: every dict-literal key and
    constant-subscript store in its body, plus -- transitively --
    helpers whose result it returns (``return make_resp(...)``, also
    via ``x = make_resp(...); return x``) and helpers a returned dict
    is passed into (``fill(resp); return resp``)."""
    if fi.key in visiting or len(visiting) > _MAX_FOLLOW:
        return
    visiting.add(fi.key)
    s = graph.summary(fi)
    for k, ln in s.dict_keys.items():
        out.setdefault(k, (fi.rel, ln))
    sc = None
    for node in s.return_exprs:
        if isinstance(node, ast.Call):
            if sc is None:
                sc = graph.scope(fi)
            callee = graph.resolve_call(node, sc)
            if callee is not None and callee.key != fi.key:
                _follow_returned_keys(graph, callee, out, visiting)
    for callee, argspec, kwspec, _line in s.calls:
        for name in s.returned_names:
            for s2 in cg.forwarded_slots(callee, argspec, kwspec,
                                         name):
                _follow_param_writes(graph, callee, s2, out, set())
    for name in s.returned_names:
        callee = s.name_calls.get(name)
        if callee is not None and callee.key != fi.key:
            _follow_returned_keys(graph, callee, out, visiting)


class _Handler:
    def __init__(self, op: str, rel: str, line: int):
        self.op = op
        self.rel = rel
        self.line = line
        self.reads: dict = {}      # key -> (rel, line)
        self.returns: dict = {}    # key -> (rel, line)


def _scan_handler(graph, fi) -> _Handler:
    h = _Handler(fi.name[3:], fi.rel, fi.node.lineno)
    params = cg.fn_params(fi.node)
    msg_param = params[1] if len(params) > 1 else None
    if msg_param is not None:
        _follow_param_reads(graph, fi, msg_param, h.reads, set())
    _follow_returned_keys(graph, fi, h.returns, set())
    return h


class _ClientSite:
    def __init__(self, op: str, rel: str, line: int):
        self.op = op
        self.rel = rel
        self.line = line
        self.sends: dict = {}      # key -> line
        self.reads: dict = {}      # response key -> (rel, line)


def _client_op_of_call(node: ast.Call) -> Optional[str]:
    """The literal op name of a client-ish call, or None."""
    f = node.func
    name = None
    if isinstance(f, ast.Attribute):
        if f.attr in CLIENT_METHOD_OPS and not node.args:
            return CLIENT_METHOD_OPS[f.attr]
        if f.attr in CLIENT_CALL_ATTRS:
            name = f.attr
    elif isinstance(f, ast.Name) and f.id in CLIENT_CALL_NAMES:
        name = f.id
    if name is None or not node.args:
        return None
    return _const_str(node.args[0])


def _scope_nodes(node) -> list:
    """The nodes of ONE lexical scope: everything under ``node``
    without descending into nested function/lambda bodies (each of
    those is its own scope -- idx.functions lists them all, so every
    body is scanned exactly once).  Class bodies are transparent,
    methods are not."""
    out = []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


class _ModScope:
    """Degenerate TypeScope for module-top-level client code: name
    resolution through the module's imports still works, attribute
    calls on typed expressions do not (no annotations to type from)."""

    __slots__ = ("module",)

    def __init__(self, module):
        self.module = module

    def type_of(self, node):
        return None


def _scan_clients(nodes: list, rel: str, graph, mod,
                  make_scope) -> list:
    """Client call sites in one scope's node list, with response reads
    resolved through simple ``X = <call>`` assignments -- same-scope
    subscript/get/in reads AND, through the call graph, helpers the
    response is passed to.  Scope isolation is load-bearing: one flat
    pass over a whole module would alias every function's ``resp``
    variable to whichever call site assigned it last,
    cross-attributing reads to the wrong op."""
    sites: list = []
    by_var: dict = {}      # var name -> _ClientSite (latest assign)
    calls: dict = {}       # id(call node) -> _ClientSite
    for node in nodes:
        if isinstance(node, ast.Call):
            op = _client_op_of_call(node)
            if op is None:
                continue
            site = _ClientSite(op, rel, node.lineno)
            for kw in node.keywords:
                if kw.arg is not None and kw.arg != "op":
                    site.sends.setdefault(kw.arg, node.lineno)
            sites.append(site)
            calls[id(node)] = site
    if not calls:
        return sites
    # response reads: X = <call>; then X["k"] / X.get("k") / "k" in X
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and id(node.value) in calls:
            by_var[node.targets[0].id] = calls[id(node.value)]

    def _site_of(expr) -> Optional[_ClientSite]:
        if isinstance(expr, ast.Name):
            return by_var.get(expr.id)
        if isinstance(expr, ast.Call):
            return calls.get(id(expr))
        return None

    scope = None
    for node in nodes:
        if isinstance(node, ast.Subscript):
            site = _site_of(node.value)
            key = _const_str(node.slice)
            if site is not None and key is not None:
                site.reads.setdefault(key, (rel, node.lineno))
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" and node.args:
                site = _site_of(node.func.value)
                key = _const_str(node.args[0])
                if site is not None and key is not None:
                    site.reads.setdefault(key, (rel, node.lineno))
                    continue
            # helper(X): response keys read inside a resolvable helper
            # count as this site's reads (the helper-laundering gap)
            for pos, arg in enumerate(node.args):
                site = _site_of(arg)
                if site is None:
                    continue
                if scope is None:
                    scope = make_scope()
                callee = graph.resolve_call(node, scope)
                if callee is None:
                    continue
                p = cg.slot_at(callee, pos)
                if p is not None:
                    _follow_param_reads(graph, callee, p, site.reads,
                                        set())
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)):
            site = _site_of(node.comparators[0]
                            if node.comparators else None)
            key = _const_str(node.left)
            if site is not None and key is not None:
                site.reads.setdefault(key, (rel, node.lineno))
    return sites


def run(ctx) -> list:
    findings: list = []
    graph = cg.get(ctx)
    handlers: dict = {}    # op -> _Handler
    for path in ctx.package_files():
        try:
            if not _HANDLER_RE.search(ctx.source(path)):
                continue
        except OSError:
            continue
        mod = graph.load_file(path)
        if mod is None:
            continue
        rel = ctx.rel(path)
        for ci in mod.classes.values():
            for mname, fi in ci.methods.items():
                if not mname.startswith("op_"):
                    continue
                h = _scan_handler(graph, fi)
                if h.op in handlers:
                    findings.append(Finding(
                        NAME, rel, fi.node.lineno,
                        f"op {h.op!r} handled twice (also "
                        f"{handlers[h.op].rel}:"
                        f"{handlers[h.op].line})"))
                handlers[h.op] = h

    sites: list = []
    for path in ctx.package_files() + ctx.tools_files():
        try:
            if not _CLIENT_RE.search(ctx.source(path)):
                continue
        except OSError:
            continue
        idx = ctx.index(path)
        if idx is None:
            continue
        mod = graph.load_file(path)
        if mod is None:
            continue
        rel = ctx.rel(path)
        # one scope per function (plus the module top level), nested
        # bodies excluded from their parents: the X-=-call dataflow
        # must not leak across scopes in either direction (a nested
        # def reusing the parent's response-variable name would
        # cross-attribute reads between ops)
        cls_of: dict = {}
        for cnode in idx.classes:
            for item in cnode.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    cls_of[id(item)] = cnode.name

        def _mk_scope(fn=None, m=mod):
            if fn is None:
                return _ModScope(m)
            return cg.TypeScope(graph, fn, m, cls_of.get(id(fn)))

        sites.extend(_scan_clients(
            _scope_nodes(ctx.tree(path)), rel, graph, mod,
            lambda m=mod: _ModScope(m)))
        for fn in idx.functions:
            sites.extend(_scan_clients(
                _scope_nodes(fn), rel, graph, mod,
                lambda fn=fn: _mk_scope(fn)))

    if not handlers:
        return findings

    by_op: dict = {}
    for site in sites:
        by_op.setdefault(site.op, []).append(site)

    # 1. undeclared ops
    for op, op_sites in sorted(by_op.items()):
        if op not in handlers:
            s = op_sites[0]
            findings.append(Finding(
                NAME, s.rel, s.line,
                f"client calls op {op!r} but no op_{op} handler "
                "exists"))

    for op, h in sorted(handlers.items()):
        op_sites = by_op.get(op, [])
        if not op_sites:
            continue       # ops endpoint (status & co): tests/scripts
        sent: set = set()
        for s in op_sites:
            sent.update(s.sends)
        # 2. handler reads a key no client sends
        for key, (rrel, line) in sorted(h.reads.items()):
            if key not in sent and key not in REQUEST_TRANSPORT:
                findings.append(Finding(
                    NAME, rrel, line,
                    f"op_{op} reads request key {key!r} that no "
                    "client call site sends -- dead or drifted "
                    "protocol surface"))
        # 3. client sends a key the handler ignores
        for s in op_sites:
            for key, line in sorted(s.sends.items()):
                if key not in h.reads and key not in REQUEST_TRANSPORT:
                    findings.append(Finding(
                        NAME, s.rel, line,
                        f"op {op!r} call sends key {key!r} the "
                        f"handler (op_{op}, {h.rel}:{h.line}) never "
                        "reads"))
        # 4. client reads a response key the handler never returns
        for s in op_sites:
            for key, (rrel, line) in sorted(s.reads.items()):
                if key not in h.returns \
                        and key not in RESPONSE_TRANSPORT:
                    findings.append(Finding(
                        NAME, rrel, line,
                        f"op {op!r} response read of key {key!r} "
                        f"that op_{op} ({h.rel}:{h.line}) never "
                        "returns"))
    return findings
