"""Metric/span declaration hygiene (absorbed from
tools/check_metrics.py).

The PR 3 bug this makes impossible: ``dprf_compile_seconds`` was
declared with ``("engine",)`` labels in two call sites and with
``("engine", "cache")`` in a third -- the registry's get-or-create
semantics turn a second declaration site into either silent drift or
a runtime ValueError, depending on which import runs first.  Rules:

  1. every ``dprf_*`` metric name passed as a literal to
     ``.counter(`` / ``.gauge(`` / ``.histogram(`` appears at EXACTLY
     ONE call site across the package;
  2. every span-name literal passed to a ``.record("...")`` call is a
     member of ``telemetry/trace.py``'s ``SPAN_NAMES`` tuple, which
     holds no duplicates;
  3. every metric an ALERT RULE references (ISSUE 10) -- the
     ``DEFAULT_RULES`` literal pack in ``telemetry/alerts.py`` and
     any ``DPRF_ALERT_RULES``-style fixture file under
     ``tests/fixtures/alert_rules*.json`` -- names a declared
     ``dprf_*`` metric.  A renamed metric would otherwise silently
     disarm its rule: the alert engine evaluates "condition false"
     against a metric that no longer exists, forever.
  4. every ``jax.profiler`` trace call (``.start_trace(`` /
     ``.stop_trace(`` / ``jax.profiler.trace(``) lives in
     ``telemetry/profiler.py`` (ISSUE 15): jax allows ONE active
     trace per process, so every starter must go through
     ProfileCapture's single-flight guard -- a raw call elsewhere
     is exactly the ``--profile``-vs-``DPRF_JAX_PROFILE`` collision
     the guard exists to prevent.  One-declaration-site discipline,
     same as metrics and spans.
"""

from __future__ import annotations

import ast
import os
import re

from dprf_tpu.analysis import Finding

NAME = "metrics"
DESCRIPTION = ("every dprf_* metric declared at one site; every span "
               "literal is in SPAN_NAMES; every alert rule "
               "references a declared metric; jax.profiler calls "
               "only in telemetry/profiler.py")

METRIC_METHODS = {"counter", "gauge", "histogram"}
TRACE_REL = os.path.join("telemetry", "trace.py")
ALERTS_REL = os.path.join("telemetry", "alerts.py")
PROFILER_REL = os.path.join("telemetry", "profiler.py")

#: profiler-trace attribute calls that must not exist outside the
#: single-flight owner (rule 4): start/stop are unambiguous; a bare
#: ``.trace(`` only counts when called on something named "profiler"
PROFILER_METHODS = {"start_trace", "stop_trace"}

#: parse prefilter: a file with no metric/record call text cannot
#: contribute a declaration or span use
_RELEVANT_RE = re.compile(
    r"\.(?:counter|gauge|histogram|record)\s*\(")
_PROFILER_RE = re.compile(r"\.(?:start_trace|stop_trace|trace)\s*\(")


def _literal(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _scan_file(idx):
    decls, span_uses = [], []
    for node in idx.calls:
        if not isinstance(node.func, ast.Attribute):
            continue
        first = _literal(node.args[0]) if node.args else None
        if (node.func.attr in METRIC_METHODS and first
                and first.startswith("dprf_")):
            decls.append((first, node.lineno))
        elif node.func.attr == "record" and first is not None:
            span_uses.append((first, node.lineno))
    return decls, span_uses


def _alert_rule_refs(idx):
    """(rule name, metric, lineno) triples from the ``DEFAULT_RULES``
    assignment -- a list of PURE dict literals by contract (the alert
    engine and this check share that shape), so the AST read is
    exact, or None when the assignment is missing."""
    if idx is None:
        return None
    for node in idx.assigns:
        if not any(isinstance(t, ast.Name) and t.id == "DEFAULT_RULES"
                   for t in node.targets):
            continue
        if not isinstance(node.value, (ast.List, ast.Tuple)):
            return None
        out = []
        for elt in node.value.elts:
            if not isinstance(elt, ast.Dict):
                continue
            d = {}
            for k, v in zip(elt.keys, elt.values):
                kk = _literal(k)
                if kk in ("name", "metric"):
                    d[kk] = _literal(v)
            out.append((d.get("name"), d.get("metric"), elt.lineno))
        return out
    return None


def _check_alert_rules(ctx, pkg_dir: str, declared: set) -> list:
    """Rule-pack validation (rule 3 of the module docstring): the
    default pack in telemetry/alerts.py plus every
    tests/fixtures/alert_rules*.json file an operator or test might
    feed DPRF_ALERT_RULES."""
    import json
    out = []
    alerts_py = os.path.join(pkg_dir, ALERTS_REL)
    if os.path.exists(alerts_py):
        rel = ctx.rel(alerts_py)
        refs = _alert_rule_refs(ctx.index(alerts_py))
        if refs is None:
            out.append(Finding(
                NAME, rel, 1,
                "DEFAULT_RULES literal rule pack not found in "
                "telemetry/alerts.py (it must stay a list of pure "
                "dict literals so this check can read it)"))
            refs = []
        for rule, metric, lineno in refs:
            if not metric:
                out.append(Finding(
                    NAME, rel, lineno,
                    f"alert rule {rule!r} has no literal 'metric' "
                    "key"))
            elif metric not in declared:
                out.append(Finding(
                    NAME, rel, lineno,
                    f"alert rule {rule!r} references metric "
                    f"{metric!r} that no package call site declares "
                    "-- stale or undeclared; the rule would be "
                    "silently disarmed"))
    fixtures = os.path.join(ctx.tests_dir, "fixtures")
    if os.path.isdir(fixtures):
        for fn in sorted(os.listdir(fixtures)):
            if not (fn.startswith("alert_rules")
                    and fn.endswith(".json")):
                continue
            p = os.path.join(fixtures, fn)
            try:
                with open(p, encoding="utf-8") as fh:
                    doc = json.load(fh)
            except (OSError, ValueError):
                out.append(Finding(
                    NAME, ctx.rel(p), 1,
                    "alert-rules fixture does not parse as JSON"))
                continue
            if not isinstance(doc, list):
                out.append(Finding(
                    NAME, ctx.rel(p), 1,
                    "alert-rules fixture must be a JSON list of "
                    "rule objects"))
                continue
            for i, r in enumerate(doc):
                rule = r.get("name") if isinstance(r, dict) else f"#{i}"
                metric = (r.get("metric")
                          if isinstance(r, dict) else None)
                if not isinstance(metric, str) or metric not in declared:
                    out.append(Finding(
                        NAME, ctx.rel(p), 1,
                        f"alert rule {rule!r} references metric "
                        f"{metric!r} that is not a declared dprf_* "
                        "metric"))
    return out


def _profiler_calls(idx):
    """(description, lineno) for every jax.profiler trace call in a
    file (rule 4): start/stop_trace attribute calls, plus ``.trace(``
    called on something named ``profiler``."""
    out = []
    for node in idx.calls:
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        if f.attr in PROFILER_METHODS:
            out.append((f.attr, node.lineno))
        elif f.attr == "trace":
            v = f.value
            name = (v.attr if isinstance(v, ast.Attribute)
                    else v.id if isinstance(v, ast.Name) else None)
            if name == "profiler":
                out.append(("profiler.trace", node.lineno))
    return out


def _check_profiler_discipline(ctx, pkg_dir: str) -> list:
    """Rule 4: every jax.profiler trace call lives in
    telemetry/profiler.py -- the single-flight capture owner."""
    out = []
    profiler_rel = ctx.rel(os.path.join(pkg_dir, PROFILER_REL))
    for path in (ctx.package_files() + ctx.root_files()
                 + ctx.tools_files()):
        try:
            if not _PROFILER_RE.search(ctx.source(path)):
                continue
        except OSError:
            continue
        rel = ctx.rel(path)
        if rel == profiler_rel:
            continue
        idx = ctx.index(path)
        if idx is None:
            continue
        for what, lineno in _profiler_calls(idx):
            out.append(Finding(
                NAME, rel, lineno,
                f"jax.profiler call ({what}) outside "
                "telemetry/profiler.py -- jax allows ONE active "
                "trace; route captures through ProfileCapture's "
                "single-flight guard (session/begin_window)"))
    return out


def _declared_span_names(idx):
    """The SPAN_NAMES tuple, or None when the assignment is missing."""
    if idx is None:
        return None
    for node in idx.assigns:
        if not any(isinstance(t, ast.Name) and t.id == "SPAN_NAMES"
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            names = [_literal(e) for e in node.value.elts]
            if all(n is not None for n in names):
                return names
    return None


def run(ctx) -> list:
    pkg_dir = ctx.package_dir
    out = []
    decl_sites: dict = {}    # metric name -> [(rel, line), ...]
    span_sites = []          # (name, rel, line)
    for path in ctx.package_files():
        try:
            if not _RELEVANT_RE.search(ctx.source(path)):
                continue
        except OSError:
            continue
        idx = ctx.index(path)
        if idx is None:
            continue
        decls, span_uses = _scan_file(idx)
        rel = ctx.rel(path)
        for metric, lineno in decls:
            decl_sites.setdefault(metric, []).append((rel, lineno))
        for span, lineno in span_uses:
            span_sites.append((span, rel, lineno))

    for metric, sites in sorted(decl_sites.items()):
        if len(sites) > 1:
            where = ", ".join(f"{r}:{ln}" for r, ln in sites)
            out.append(Finding(
                NAME, sites[0][0], sites[0][1],
                f"metric {metric!r} declared at {len(sites)} sites "
                f"({where}) -- declare once and share the helper "
                "(telemetry.declare_job_metrics pattern)"))

    trace_py = os.path.join(pkg_dir, TRACE_REL)
    span_names = (_declared_span_names(ctx.index(trace_py))
                  if os.path.exists(trace_py) else None)
    if span_names is None:
        if span_sites:
            out.append(Finding(
                NAME, ctx.rel(trace_py), 1,
                f"SPAN_NAMES tuple not found but {len(span_sites)} "
                ".record(...) call sites exist"))
    else:
        dupes = {n for n in span_names if span_names.count(n) > 1}
        if dupes:
            out.append(Finding(
                NAME, ctx.rel(trace_py), 1,
                f"duplicate SPAN_NAMES entries: {sorted(dupes)}"))
        allowed = set(span_names)
        for span, rel, lineno in span_sites:
            if span not in allowed:
                out.append(Finding(
                    NAME, rel, lineno,
                    f"span {span!r} not declared in "
                    "telemetry/trace.py SPAN_NAMES"))

    # alert rules (default pack + fixture files) must reference
    # declared metrics only
    out.extend(_check_alert_rules(ctx, pkg_dir, set(decl_sites)))
    # jax.profiler calls only in the single-flight owner (ISSUE 15)
    out.extend(_check_profiler_discipline(ctx, pkg_dir))
    return out
