"""Metric/span declaration hygiene (absorbed from
tools/check_metrics.py).

The PR 3 bug this makes impossible: ``dprf_compile_seconds`` was
declared with ``("engine",)`` labels in two call sites and with
``("engine", "cache")`` in a third -- the registry's get-or-create
semantics turn a second declaration site into either silent drift or
a runtime ValueError, depending on which import runs first.  Rules:

  1. every ``dprf_*`` metric name passed as a literal to
     ``.counter(`` / ``.gauge(`` / ``.histogram(`` appears at EXACTLY
     ONE call site across the package;
  2. every span-name literal passed to a ``.record("...")`` call is a
     member of ``telemetry/trace.py``'s ``SPAN_NAMES`` tuple, which
     holds no duplicates.
"""

from __future__ import annotations

import ast
import os
import re

from dprf_tpu.analysis import Finding

NAME = "metrics"
DESCRIPTION = ("every dprf_* metric declared at one site; every span "
               "literal is in SPAN_NAMES")

METRIC_METHODS = {"counter", "gauge", "histogram"}
TRACE_REL = os.path.join("telemetry", "trace.py")

#: parse prefilter: a file with no metric/record call text cannot
#: contribute a declaration or span use
_RELEVANT_RE = re.compile(
    r"\.(?:counter|gauge|histogram|record)\s*\(")


def _literal(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _scan_file(idx):
    decls, span_uses = [], []
    for node in idx.calls:
        if not isinstance(node.func, ast.Attribute):
            continue
        first = _literal(node.args[0]) if node.args else None
        if (node.func.attr in METRIC_METHODS and first
                and first.startswith("dprf_")):
            decls.append((first, node.lineno))
        elif node.func.attr == "record" and first is not None:
            span_uses.append((first, node.lineno))
    return decls, span_uses


def _declared_span_names(idx):
    """The SPAN_NAMES tuple, or None when the assignment is missing."""
    if idx is None:
        return None
    for node in idx.assigns:
        if not any(isinstance(t, ast.Name) and t.id == "SPAN_NAMES"
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            names = [_literal(e) for e in node.value.elts]
            if all(n is not None for n in names):
                return names
    return None


def run(ctx) -> list:
    pkg_dir = ctx.package_dir
    out = []
    decl_sites: dict = {}    # metric name -> [(rel, line), ...]
    span_sites = []          # (name, rel, line)
    for path in ctx.package_files():
        try:
            if not _RELEVANT_RE.search(ctx.source(path)):
                continue
        except OSError:
            continue
        idx = ctx.index(path)
        if idx is None:
            continue
        decls, span_uses = _scan_file(idx)
        rel = ctx.rel(path)
        for metric, lineno in decls:
            decl_sites.setdefault(metric, []).append((rel, lineno))
        for span, lineno in span_uses:
            span_sites.append((span, rel, lineno))

    for metric, sites in sorted(decl_sites.items()):
        if len(sites) > 1:
            where = ", ".join(f"{r}:{ln}" for r, ln in sites)
            out.append(Finding(
                NAME, sites[0][0], sites[0][1],
                f"metric {metric!r} declared at {len(sites)} sites "
                f"({where}) -- declare once and share the helper "
                "(telemetry.declare_job_metrics pattern)"))

    trace_py = os.path.join(pkg_dir, TRACE_REL)
    span_names = (_declared_span_names(ctx.index(trace_py))
                  if os.path.exists(trace_py) else None)
    if span_names is None:
        if span_sites:
            out.append(Finding(
                NAME, ctx.rel(trace_py), 1,
                f"SPAN_NAMES tuple not found but {len(span_sites)} "
                ".record(...) call sites exist"))
    else:
        dupes = {n for n in span_names if span_names.count(n) > 1}
        if dupes:
            out.append(Finding(
                NAME, ctx.rel(trace_py), 1,
                f"duplicate SPAN_NAMES entries: {sorted(dupes)}"))
        allowed = set(span_names)
        for span, rel, lineno in span_sites:
            if span not in allowed:
                out.append(Finding(
                    NAME, rel, lineno,
                    f"span {span!r} not declared in "
                    "telemetry/trace.py SPAN_NAMES"))
    return out
