"""Env-knob registry lint.

``dprf_tpu/utils/env.py`` is the ONE declaration site for every
``DPRF_*`` environment knob (name, default, type, docstring) and the
one sanctioned read path (typed getters).  This lint closes the loop:

  1. no raw ``os.environ`` / ``os.getenv`` read of a ``DPRF_*`` name
     (literal, or through a module-level string constant) anywhere
     outside the registry module -- package, tools/, tests/, and the
     repo-root driver scripts are all scanned;
  2. inside the package, env reads whose variable name the checker
     cannot resolve at all are flagged too ("unauditable read"):
     a knob smuggled through a computed name is still a knob;
  3. every getter call naming an UNDECLARED knob is flagged (the
     registry raises at runtime; this catches it before any test);
  4. every declared knob has at least one read site somewhere in the
     repo -- a knob nobody reads is stale documentation;
  5. the README's generated knob table is in sync with the registry
     (``dprf check --write-env-docs`` regenerates it).

Writes (``os.environ["DPRF_X"] = ...``) stay legal everywhere: tests
and conftest pin knobs; the lint governs READS.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from dprf_tpu.analysis import Finding

NAME = "env-knobs"
DESCRIPTION = ("DPRF_* env reads go through utils/env.py; registry "
               "and README knob table stay in sync")

GETTERS = {"get_raw", "get_str", "get_path", "get_int", "get_float",
           "get_bool", "knob"}
REGISTRY_REL = os.path.join("utils", "env.py")


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _os_bindings(import_nodes):
    """(os-module names, environ names, getenv names) bound in this
    file -- ``import os as _os`` / ``from os import environ as e``
    must not make a read invisible to the lint."""
    os_names = {"os"}
    environ_names = {"environ"}
    getenv_names = {"getenv"}
    for node in import_nodes:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "os" and a.asname:
                    os_names.add(a.asname)
        elif isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name == "environ":
                    environ_names.add(a.asname or a.name)
                elif a.name == "getenv":
                    getenv_names.add(a.asname or a.name)
    return os_names, environ_names, getenv_names


def _is_environ(node, os_names, environ_names) -> bool:
    """``<os-alias>.environ`` or a bare imported ``environ`` name."""
    if isinstance(node, ast.Attribute) and node.attr == "environ" \
            and isinstance(node.value, ast.Name) \
            and node.value.id in os_names:
        return True
    return isinstance(node, ast.Name) and node.id in environ_names


def _module_consts(tree) -> dict:
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            s = _const_str(node.value)
            if s is not None:
                out[node.targets[0].id] = s
    return out


def _declared_knobs(ctx) -> dict:
    """name -> declaration line, parsed from the registry module's
    ``_declare("DPRF_X", ...)`` calls (AST, not import: fixture trees
    must be checkable without being importable)."""
    path = os.path.join(ctx.package_dir, REGISTRY_REL)
    if not os.path.exists(path):
        return {}
    tree = ctx.tree(path)
    if tree is None:
        return {}
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "_declare" and node.args:
            name = _const_str(node.args[0])
            if name is not None:
                out[name] = node.lineno
    return out


def _load_registry(ctx):
    """The registry module executed from ctx's own tree (so a fixture
    repo checks against its own registry), or None."""
    path = os.path.join(ctx.package_dir, REGISTRY_REL)
    if not os.path.exists(path):
        return None
    import importlib.util
    import sys
    name = "_dprf_check_env_registry"
    try:
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        # dataclass field-type resolution looks the module up in
        # sys.modules (PEP 563 string annotations); exec'ing it
        # unregistered makes @dataclass itself crash
        sys.modules[name] = mod
        try:
            spec.loader.exec_module(mod)
        finally:
            sys.modules.pop(name, None)
        return mod
    except Exception:   # noqa: BLE001 -- a broken registry surfaces
        return None     # through check 3/4 findings instead


def run(ctx) -> list:
    findings: list = []
    declared = _declared_knobs(ctx)
    registry_path = os.path.join(ctx.package_dir, REGISTRY_REL)
    registry_rel = ctx.rel(registry_path)
    read_knobs: set = set()

    scan = (ctx.package_files() + ctx.tools_files() + ctx.test_files()
            + ctx.root_files())
    for path in scan:
        rel = ctx.rel(path)
        if rel == registry_rel:
            continue
        try:
            src = ctx.source(path)
        except OSError:
            continue
        # parse prefilter: every env read this lint can flag (or
        # getter read it must count) names one of these in source
        if ("environ" not in src and "getenv" not in src
                and "DPRF_" not in src):
            continue
        tree = ctx.tree(path)
        idx = ctx.index(path)
        if idx is None:
            continue
        consts = _module_consts(tree)
        os_names, environ_names, getenv_names = _os_bindings(
            idx.imports)
        in_package = path.startswith(ctx.package_dir + os.sep)

        def _name_of(arg) -> tuple:
            """(resolved name | None, resolvable?)"""
            s = _const_str(arg)
            if s is not None:
                return s, True
            if isinstance(arg, ast.Name) and arg.id in consts:
                return consts[arg.id], True
            return None, False

        def _flag_read(arg, lineno):
            resolved, ok = _name_of(arg)
            if ok and resolved is not None \
                    and resolved.startswith("DPRF_"):
                findings.append(Finding(
                    NAME, rel, lineno,
                    f"raw environment read of {resolved!r} -- go "
                    "through dprf_tpu.utils.env (the registry is the "
                    "single declaration site)"))
            elif not ok and in_package:
                findings.append(Finding(
                    NAME, rel, lineno,
                    "environment read with a name the checker cannot "
                    "resolve -- read knobs through "
                    "dprf_tpu.utils.env so they stay auditable"))

        for node in idx.calls:
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "get" \
                    and _is_environ(f.value, os_names, environ_names) \
                    and node.args:
                _flag_read(node.args[0], node.lineno)
            elif isinstance(f, ast.Attribute) \
                    and f.attr == "getenv" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in os_names and node.args:
                _flag_read(node.args[0], node.lineno)
            elif isinstance(f, ast.Name) and f.id in getenv_names \
                    and node.args:
                _flag_read(node.args[0], node.lineno)
            elif ((isinstance(f, ast.Attribute)
                   and f.attr in GETTERS)
                  or (isinstance(f, ast.Name)
                      and f.id in GETTERS)) and node.args:
                # literal knob name, or a module-level string constant
                # (the `ENABLE_ENV = "DPRF_TRACE"` idiom)
                knob, _ = _name_of(node.args[0])
                if knob is not None and knob.startswith("DPRF_"):
                    read_knobs.add(knob)
                    if declared and knob not in declared:
                        findings.append(Finding(
                            NAME, rel, node.lineno,
                            f"getter reads undeclared knob "
                            f"{knob!r} -- declare it in "
                            "utils/env.py"))
        for node in idx.subscripts:
            if _is_environ(node.value, os_names, environ_names) \
                    and isinstance(node.ctx, ast.Load):
                _flag_read(node.slice, node.lineno)

    if not declared:
        if os.path.exists(registry_path):
            findings.append(Finding(
                NAME, registry_rel, 1,
                "no _declare(...) knob declarations found in the "
                "registry module"))
        return findings

    for knob, lineno in sorted(declared.items()):
        if knob not in read_knobs:
            findings.append(Finding(
                NAME, registry_rel, lineno,
                f"knob {knob!r} is declared but never read through "
                "the registry anywhere in the repo -- delete it or "
                "wire it up"))

    # README sync (only when this tree has a README at all)
    if os.path.exists(ctx.readme):
        mod = _load_registry(ctx)
        if mod is not None and hasattr(mod, "readme_sync_error"):
            err = mod.readme_sync_error(ctx.readme)
            if err:
                findings.append(Finding(NAME, ctx.rel(ctx.readme), 1,
                                        err))
    return findings
