"""Interprocedural dataflow core: the whole-package call graph.

PR 6's analyzers stopped at same-scope dataflow (protocol) and
one-level method calls (locks).  The serve-plane and elastic-fleet
tentpoles will multiply threads, locks, sockets, and RPC helpers --
exactly the surfaces where one helper function launders a guarded
access, a blocking call, or a request key out of an analyzer's sight.
This module is the shared machinery that closes that gap:

  - a MODULE REGISTRY with demand loading: files parse when an
    import, annotation, or call actually reaches them, so the graph
    covers the whole package without paying a whole-package parse on
    every run (the <2 s in-process budget);
  - TYPE RESOLUTION, lifted from the locks analyzer: ``self`` inside
    a class; parameters/locals/attributes with class annotations;
    direct constructions; factory calls whose return annotation names
    a known class -- now shared by every interprocedural check;
  - per-function SUMMARIES, memoized on the shared graph: locks
    acquired (``with`` contexts over typed expressions), blocking
    calls, resolvable callees, dict keys read/written through each
    parameter, dict keys built, and return expressions;
  - cycle-safe TRANSITIVE CLOSURE over summaries (acquires + blocking
    reached), the machinery behind "blocking call reached via
    Dispatcher._requeue while holding CoordinatorState.lock".

The graph is generic: it records every ``with <typed>.<attr>``
acquisition and every dict-key read, and the analyzers (locks,
protocol, threads, retrace) filter against their own declaration
tables.  An expression the graph cannot type is not resolved -- the
declared tables cover the concurrent surfaces, and the fixtures in
tests/test_analysis_interproc.py pin the surfaces it must see.

Get the per-context singleton with ``callgraph.get(ctx)``; seed it
with the files an analyzer's own prefilters selected via
``graph.load_file`` -- imports pull in the rest on demand.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

#: method-attribute calls that block (or compile) -- the locks
#: analyzer forbids these while a declared lock is held, directly or
#: reached through the call graph
BLOCKING_ATTRS = {"sendall", "recv", "recv_into", "readline", "accept",
                  "connect", "makefile", "call", "aot_compile",
                  "ensure_warm", "warmup", "drain"}
#: bare-name calls that block
BLOCKING_NAMES = {"send_msg", "recv_msg", "sleep"}
#: module-qualified calls that block
BLOCKING_QUALIFIED = {("time", "sleep"), ("socket", "create_connection"),
                      ("subprocess", "run"), ("subprocess", "check_call"),
                      ("subprocess", "check_output"), ("jax", "jit"),
                      ("jax", "pmap")}

#: summary recursion budget: helper chains deeper than this are real
#: architecture smells, and an unbounded walk over a pathological
#: fixture must not hang the suite
MAX_CLOSURE_DEPTH = 64


def const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def expr_key(node) -> Optional[str]:
    """Normalize a Name/Attribute chain ('self', 'self.state', ...);
    None for anything a guard matcher should not try to compare."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = expr_key(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def ann_name(node) -> Optional[str]:
    """A class name out of an annotation: ``X``, ``"X"``, or
    ``Optional[X]``-style subscripts are reduced to X."""
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    s = const_str(node)
    if s:
        return s.strip().strip('"').strip("'")
    if isinstance(node, ast.Subscript):
        return ann_name(node.slice)
    return None


def walk_scope(node):
    """ast.walk that does NOT descend into nested function/class
    scopes (they are analyzed separately, with their own env)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def walk_expr(node):
    """Like walk_scope but yields ``node`` itself too -- for walking
    one expression.  A plain ``continue`` inside ``ast.walk`` does
    NOT do this: walk has already queued the nested scope's children,
    so a lambda's body would be scanned as the enclosing function's
    code."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def blocking_reason(node: ast.Call) -> Optional[str]:
    """Why this call blocks, or None."""
    f = node.func
    if isinstance(f, ast.Name) and f.id in BLOCKING_NAMES:
        return f.id
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name) \
                and (f.value.id, f.attr) in BLOCKING_QUALIFIED:
            return f"{f.value.id}.{f.attr}"
        if f.attr in BLOCKING_ATTRS:
            return f".{f.attr}()"
    return None


def fn_params(fn) -> list:
    """Positional parameter names, in call order (posonly + args)."""
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def param_at(fi: "FuncInfo", pos: int) -> Optional[str]:
    """The callee parameter a positional argument lands on (``self``
    skipped for methods), or None past the parameter list."""
    s = slot_at(fi, pos)
    return s if isinstance(s, str) else None


# -- argument slots ---------------------------------------------------------
#
# A SLOT names how a tracked value is bound inside a function:
#
#   "msg"               a plain parameter
#   ("*", "args", 2)    element 2 of the function's *args tuple
#   ("**", "kw", "msg") the "msg" entry of the function's **kw dict
#
# ``arg_slot`` describes one call-site argument; ``forwarded_slots``
# maps a caller-held slot through one call to the callee slots it
# lands on.  Together they close the PR 7 gap where a wrapper like
# ``def locked(self, *args, **kwargs): return self._do(*args,
# **kwargs)`` laundered a dict (and the facts read from it) out of
# the positional-names-only dataflow.

def arg_slot(node):
    """Call-site argument descriptor: a Name's id, ``("*", name)``
    for ``*name`` spreads, None for anything else."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Starred) and isinstance(node.value,
                                                    ast.Name):
        return ("*", node.value.id)
    return None


def slot_at(fi: "FuncInfo", pos: int):
    """The slot a positional argument lands on (``self`` skipped for
    methods): a parameter name, ``("*", vararg, offset)`` past the
    positional list when the callee takes ``*vararg``, else None."""
    params = fn_params(fi.node)
    if fi.cls is not None and params and params[0] == "self":
        params = params[1:]
    if 0 <= pos < len(params):
        return params[pos]
    va = fi.node.args.vararg
    if va is not None and pos >= len(params):
        return ("*", va.arg, pos - len(params))
    return None


def slot_for_keyword(fi: "FuncInfo", key: str):
    """The slot a ``key=value`` argument lands on: the parameter of
    that name, ``("**", kwarg, key)`` when it falls into a ``**kwarg``
    catch-all, else None (the call would TypeError at runtime)."""
    a = fi.node.args
    names = {p.arg for p in (list(a.posonlyargs) + list(a.args)
                             + list(a.kwonlyargs))}
    if key in names:
        return key
    if a.kwarg is not None:
        return ("**", a.kwarg.arg, key)
    return None


def forwarded_slots(callee: "FuncInfo", argspec: tuple, kwspec: tuple,
                    slot) -> list:
    """Callee slots a caller-held ``slot`` reaches through one call
    (``argspec``/``kwspec`` as recorded in ``Summary.calls``).
    Positional pass-through, ``key=name`` keywords, ``*args`` and
    ``**kwargs`` re-forwarding all resolve; a spread that cannot be
    positioned soundly resolves to nothing rather than to a guess."""
    out = []
    if isinstance(slot, str):
        for pos, an in enumerate(argspec):
            if an == slot:
                s2 = slot_at(callee, pos)
                if s2 is not None:
                    out.append(s2)
        for k, vn in kwspec:
            if vn == slot and k is not None:
                s2 = slot_for_keyword(callee, k)
                if s2 is not None:
                    out.append(s2)
    elif slot and slot[0] == "*":
        _, va, idx = slot
        for pos, an in enumerate(argspec):
            if an == ("*", va):
                # elements of *va land at call positions pos, pos+1,
                # ...; sound because everything before pos is a fixed
                # single argument.  Only the first spread of va is
                # position-sound (a second one would sit at an
                # unknowable offset past the first's length).
                s2 = slot_at(callee, pos + idx)
                if s2 is not None:
                    out.append(s2)
                break
    elif slot and slot[0] == "**":
        _, kw, key = slot
        if any(k is None and vn == kw for k, vn in kwspec):
            s2 = slot_for_keyword(callee, key)
            if s2 is not None:
                out.append(s2)
    return out


class FuncInfo:
    __slots__ = ("key", "name", "node", "rel", "module", "cls")

    def __init__(self, key, name, node, rel, module, cls):
        self.key = key          # ("C", clsname, name) | ("F", rel, name)
        self.name = name
        self.node = node
        self.rel = rel
        self.module = module    # ModuleInfo
        self.cls = cls          # ClassInfo | None

    @property
    def qualname(self) -> str:
        if self.cls is not None:
            return f"{self.cls.name}.{self.name}"
        return self.name


class ClassInfo:
    __slots__ = ("name", "rel", "line", "node", "module", "methods",
                 "bases", "method_marks", "_attr_types",
                 "init_assigned")

    def __init__(self, name, rel, line, node, module):
        self.name = name
        self.rel = rel
        self.line = line
        self.node = node
        self.module = module
        self.methods: dict = {}       # name -> FuncInfo
        self.bases: list = []         # base-class name strings
        #: method -> {attr: constant} for ``method._attr = const``
        #: class-body annotations (_holds_lock, _submit_based, ...)
        self.method_marks: dict = {}
        self._attr_types = None       # lazy: needs demand loading
        self.init_assigned: set = set()


class ModuleInfo:
    __slots__ = ("rel", "path", "tree", "classes", "functions",
                 "imports", "from_imports", "consts")

    def __init__(self, rel, path, tree):
        self.rel = rel
        self.path = path
        self.tree = tree
        self.classes: dict = {}       # name -> ClassInfo
        self.functions: dict = {}     # name -> FuncInfo (module level)
        self.imports: dict = {}       # alias -> dotted module
        self.from_imports: dict = {}  # name -> (dotted module, orig)
        self.consts: dict = {}        # module-level str constants


class Summary:
    """One function's facts, generic (no declaration-table filtering
    here -- each analyzer applies its own)."""

    __slots__ = ("acquires", "global_acquires", "blocking", "callees",
                 "calls", "name_calls", "param_reads", "param_writes",
                 "dict_keys", "return_exprs", "returned_names")

    def __init__(self):
        #: ``with <typed expr>.<attr>:`` contexts -> {(class, attr)}
        self.acquires: set = set()
        #: ``with <bare name>:`` contexts -> {(module rel, name)}
        self.global_acquires: set = set()
        self.blocking: list = []      # [(reason, line)]
        self.callees: dict = {}       # key -> (FuncInfo, first line)
        #: every resolvable call WITH its argument bindings:
        #: [(FuncInfo, argspec, kwspec, line)] where argspec is a
        #: tuple of ``arg_slot`` descriptors (names and ``*name``
        #: spreads) and kwspec is ((kwname|None, valuename), ...)
        #: (kwname None = a ``**name`` spread) -- the dataflow the
        #: protocol checker follows a dict through helper parameters
        #: and *args/**kwargs forwarding wrappers on
        self.calls: list = []
        #: local name -> FuncInfo for ``x = helper(...)`` assignments
        #: (last one wins) -- the ``x = make_resp(...); return x``
        #: response-builder dataflow
        self.name_calls: dict = {}
        #: param -> {key: line} for param["k"] / param.get("k") /
        #: "k" in param reads (the dict-dataflow the protocol checker
        #: follows through helpers)
        self.param_reads: dict = {}
        #: param -> {key: line} for param["k"] = ... stores (helpers
        #: that BUILD a response dict passed in by the handler)
        self.param_writes: dict = {}
        #: every dict-literal key + constant subscript store in the
        #: body (the protocol checker's response over-approximation)
        self.dict_keys: dict = {}
        self.return_exprs: list = []  # ast nodes returned
        self.returned_names: set = set()


class Closure:
    """Transitive facts reachable from one function."""

    __slots__ = ("acquires", "global_acquires", "blocking")

    def __init__(self):
        self.acquires: set = set()
        self.global_acquires: set = set()
        #: [(reason, via-qualname or None, line at the entry function)]
        self.blocking: list = []


def get(ctx) -> "CallGraph":
    """The per-AnalysisContext graph (built lazily, shared by every
    analyzer in the run so files parse and summarize once)."""
    g = getattr(ctx, "_callgraph", None)
    if g is None:
        g = ctx._callgraph = CallGraph(ctx)
    return g


class CallGraph:
    def __init__(self, ctx):
        self.ctx = ctx
        self.modules: dict = {}       # rel -> ModuleInfo | None
        self.classes: dict = {}       # name -> ClassInfo (first wins)
        self.returns: dict = {}       # func name -> class name
        self._funcs: dict = {}        # key -> FuncInfo
        self._summaries: dict = {}
        self._closures: dict = {}
        self._scopes: dict = {}       # key -> TypeScope (read-only)
        #: dotted prefix of the package ("dprf_tpu")
        self.pkg = os.path.basename(ctx.package_dir)

    # -- registry --------------------------------------------------------

    def load_file(self, path: str) -> Optional[ModuleInfo]:
        rel = self.ctx.rel(path)
        if rel in self.modules:
            return self.modules[rel]
        tree = self.ctx.tree(path)
        if tree is None:
            self.modules[rel] = None
            return None
        mod = ModuleInfo(rel, path, tree)
        self.modules[rel] = mod
        self._register(mod)
        return mod

    def load_dotted(self, dotted: str) -> Optional[ModuleInfo]:
        """``dprf_tpu.runtime.worker`` -> its ModuleInfo (parsed on
        demand); None for anything outside the package."""
        if not dotted.startswith(self.pkg):
            return None
        parts = dotted.split(".")
        base = os.path.join(os.path.dirname(self.ctx.package_dir),
                            *parts)
        for cand in (base + ".py", os.path.join(base, "__init__.py")):
            if os.path.isfile(cand):
                return self.load_file(cand)
        return None

    def _register(self, mod: ModuleInfo) -> None:
        # imports are collected FILE-wide, not just module-level: the
        # repo imports factories inside __init__ bodies, and those are
        # exactly the edges the retrace check resolves jit factories
        # through.  Reuse the typed index when another analyzer
        # already built one; don't force the full 7-bucket build for
        # files only the graph touches (demand-loaded imports).
        idx = self.ctx._indexes.get(mod.path)
        if idx is not None:
            import_nodes = idx.imports
        else:
            import_nodes = [n for n in ast.walk(mod.tree)
                            if type(n) in (ast.Import, ast.ImportFrom)]
        for node in import_nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    mod.from_imports[a.asname or a.name] = (node.module,
                                                            a.name)
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                s = const_str(node.value)
                if s is not None:
                    mod.consts[node.targets[0].id] = s
            elif isinstance(node, ast.ClassDef):
                self._register_class(mod, node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                fi = FuncInfo(("F", mod.rel, node.name), node.name,
                              node, mod.rel, mod, None)
                mod.functions[node.name] = fi
                self._funcs[fi.key] = fi
                r = ann_name(node.returns)
                if r:
                    self.returns.setdefault(node.name, r)

    def _register_class(self, mod: ModuleInfo, node: ast.ClassDef):
        ci = ClassInfo(node.name, mod.rel, node.lineno, node, mod)
        ci.bases = [b.id if isinstance(b, ast.Name) else b.attr
                    for b in node.bases
                    if isinstance(b, (ast.Name, ast.Attribute))]
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(("C", node.name, item.name), item.name,
                              item, mod.rel, mod, ci)
                ci.methods[item.name] = fi
                self._funcs.setdefault(fi.key, fi)
                r = ann_name(item.returns)
                if r and item.name != "__init__":
                    self.returns.setdefault(item.name, r)
            elif isinstance(item, ast.Assign) and len(item.targets) == 1:
                t = item.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name):
                    marks = ci.method_marks.setdefault(t.value.id, {})
                    if isinstance(item.value, ast.Constant):
                        marks[t.attr] = item.value.value
        mod.classes[node.name] = ci
        self.classes.setdefault(node.name, ci)
        # methods register under the class-name key space; a second
        # class of the same name elsewhere keeps its own ModuleInfo
        # entry but does not displace the first in the global table

    def func(self, key) -> Optional[FuncInfo]:
        return self._funcs.get(key)

    # -- type resolution --------------------------------------------------

    def class_named(self, name: Optional[str],
                    mod: Optional[ModuleInfo] = None) \
            -> Optional[ClassInfo]:
        """The ClassInfo for a name, demand-loading the module an
        import binds it to."""
        if not name:
            return None
        ci = self.classes.get(name)
        if ci is not None:
            return ci
        if mod is not None:
            tgt = mod.from_imports.get(name)
            if tgt is not None:
                m = self.load_dotted(tgt[0])
                if m is not None:
                    return self.classes.get(tgt[1]) or \
                        self.classes.get(name)
        return None

    def factory_class(self, fname: str,
                      mod: Optional[ModuleInfo] = None) -> Optional[str]:
        """Class name a factory call returns, by return annotation
        (demand-loading the factory's module when imported)."""
        c = self.returns.get(fname)
        if c is not None:
            return c
        if mod is not None:
            tgt = mod.from_imports.get(fname)
            if tgt is not None and self.load_dotted(tgt[0]) is not None:
                return self.returns.get(tgt[1]) or self.returns.get(fname)
        return None

    def attr_types(self, ci: ClassInfo) -> dict:
        """self-attr -> class name, from __init__ (annotated-parameter
        assignment, direct construction, annotated factory call,
        AnnAssign) -- lazy because annotation resolution may demand-
        load other modules."""
        if ci._attr_types is not None:
            return ci._attr_types
        out: dict = {}
        ci._attr_types = out          # set first: cycles terminate
        init = ci.methods.get("__init__")
        if init is None:
            return out
        fn = init.node
        ann = {}
        a = fn.args
        for p in (list(a.posonlyargs) + list(a.args)
                  + list(a.kwonlyargs)):
            n = ann_name(p.annotation)
            if self.class_named(n, ci.module) is not None:
                ann[p.arg] = n
        for st in walk_scope(fn):
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                t = st.targets[0]
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    ci.init_assigned.add(t.attr)
                    ty = None
                    if isinstance(st.value, ast.Name):
                        ty = ann.get(st.value.id)
                    elif isinstance(st.value, ast.Call):
                        ty = self.infer_call_type(st.value, ci.module)
                    if ty:
                        out[t.attr] = ty
            elif isinstance(st, ast.AnnAssign):
                t = st.target
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    ci.init_assigned.add(t.attr)
                    ty = ann_name(st.annotation)
                    if self.class_named(ty, ci.module) is not None:
                        out[t.attr] = ty
        return out

    def infer_call_type(self, call: ast.Call,
                        mod: Optional[ModuleInfo]) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            if self.class_named(f.id, mod) is not None:
                return f.id                     # direct construction
            return self.factory_class(f.id, mod)
        if isinstance(f, ast.Attribute):
            return self.factory_class(f.attr, mod)
        return None

    def method(self, cls_name: str, name: str) -> Optional[FuncInfo]:
        """Method lookup through the (name-resolved) base-class chain."""
        seen = set()
        stack = [cls_name]
        while stack:
            cn = stack.pop(0)
            if cn in seen:
                continue
            seen.add(cn)
            ci = self.classes.get(cn)
            if ci is None:
                continue
            fi = ci.methods.get(name)
            if fi is not None:
                return fi
            for b in ci.bases:
                self.class_named(b, ci.module)   # demand-load
                stack.append(b)
        return None

    def scope(self, fi: FuncInfo) -> "TypeScope":
        """Memoized: a TypeScope is read-only after _build, and the
        per-function env walk is the hottest path in a multi-analyzer
        run (each analyzer resolves calls in the same functions)."""
        sc = self._scopes.get(fi.key)
        if sc is None:
            sc = self._scopes[fi.key] = TypeScope(
                self, fi.node, fi.module,
                fi.cls.name if fi.cls is not None else None)
        return sc

    # -- summaries ---------------------------------------------------------

    def summary(self, fi: FuncInfo) -> Summary:
        s = self._summaries.get(fi.key)
        if s is None:
            s = self._summaries[fi.key] = self._summarize(fi)
        return s

    def _summarize(self, fi: FuncInfo) -> Summary:
        s = Summary()
        sc = self.scope(fi)
        params = set(fn_params(fi.node))
        params.update(p.arg for p in fi.node.args.kwonlyargs)
        for node in walk_scope(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    e = item.context_expr
                    if isinstance(e, ast.Attribute):
                        ty = sc.type_of(e.value)
                        if ty is not None:
                            s.acquires.add((ty, e.attr))
                    elif isinstance(e, ast.Name):
                        s.global_acquires.add((fi.rel, e.id))
            elif isinstance(node, ast.Call):
                why = blocking_reason(node)
                if why is not None:
                    s.blocking.append((why, node.lineno))
                callee = self.resolve_call(node, sc)
                if callee is not None and callee.key != fi.key:
                    s.callees.setdefault(callee.key,
                                         (callee, node.lineno))
                    s.calls.append((
                        callee,
                        tuple(arg_slot(a) for a in node.args),
                        tuple((kw.arg, kw.value.id)
                              for kw in node.keywords
                              if isinstance(kw.value, ast.Name)),
                        node.lineno))
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "get" \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in params and node.args:
                    k = const_str(node.args[0])
                    if k is not None:
                        s.param_reads.setdefault(
                            f.value.id, {}).setdefault(k, node.lineno)
            elif isinstance(node, ast.Subscript):
                k = const_str(node.slice)
                if k is None:
                    continue
                if isinstance(node.value, ast.Name) \
                        and node.value.id in params:
                    d = (s.param_writes
                         if isinstance(node.ctx, (ast.Store, ast.Del))
                         else s.param_reads)
                    d.setdefault(node.value.id, {}).setdefault(
                        k, node.lineno)
                if isinstance(node.ctx, ast.Store):
                    s.dict_keys.setdefault(k, node.lineno)
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                    and node.comparators \
                    and isinstance(node.comparators[0], ast.Name) \
                    and node.comparators[0].id in params:
                k = const_str(node.left)
                if k is not None:
                    s.param_reads.setdefault(
                        node.comparators[0].id, {}).setdefault(
                            k, node.lineno)
            elif isinstance(node, ast.Dict):
                for kn in node.keys:
                    k = const_str(kn)
                    if k is not None:
                        s.dict_keys.setdefault(k, node.lineno)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                callee = self.resolve_call(node.value, sc)
                if callee is not None and callee.key != fi.key:
                    s.name_calls[node.targets[0].id] = callee
            elif isinstance(node, ast.Return) and node.value is not None:
                s.return_exprs.append(node.value)
                if isinstance(node.value, ast.Name):
                    s.returned_names.add(node.value.id)
        return s

    def resolve_call(self, node: ast.Call,
                     sc: "TypeScope") -> Optional[FuncInfo]:
        """The FuncInfo a call statically reaches: a type-resolved
        method, a same-module function, an imported function, or a
        ``module.func()`` through an import alias."""
        f = node.func
        if isinstance(f, ast.Attribute):
            ty = sc.type_of(f.value)
            if ty is not None:
                return self.method(ty, f.attr)
            if isinstance(f.value, ast.Name):
                dotted = sc.module.imports.get(f.value.id)
                if dotted is not None:
                    m = self.load_dotted(dotted)
                    if m is not None:
                        return m.functions.get(f.attr)
            return None
        if isinstance(f, ast.Name):
            fi = sc.module.functions.get(f.id)
            if fi is not None:
                return fi
            tgt = sc.module.from_imports.get(f.id)
            if tgt is not None:
                m = self.load_dotted(tgt[0])
                if m is not None:
                    return m.functions.get(tgt[1])
        return None

    # -- transitive closure ------------------------------------------------

    def closure(self, fi: FuncInfo) -> Closure:
        out, _ = self._walk_closure(fi, set(), 0)
        return out

    def _walk_closure(self, fi: FuncInfo, visiting: set, depth: int):
        """(Closure, tainted?) -- tainted means a cycle back-edge (or
        the depth cap) truncated the recursion below, so the result
        may be incomplete for THIS node and must not be cached
        (caching a mid-cycle placeholder would permanently hide a
        cycle member's facts from later call sites).  The root's
        union is complete: every reachable node's direct facts fold
        in exactly once."""
        cached = self._closures.get(fi.key)
        if cached is not None:
            return cached, False
        if fi.key in visiting or depth > MAX_CLOSURE_DEPTH:
            return Closure(), True
        visiting.add(fi.key)
        s = self.summary(fi)
        out = Closure()
        out.acquires |= s.acquires
        out.global_acquires |= s.global_acquires
        out.blocking.extend((r, None, ln) for r, ln in s.blocking)
        tainted = False
        for key, (callee, line) in s.callees.items():
            sub, t = self._walk_closure(callee, visiting, depth + 1)
            tainted = tainted or t
            out.acquires |= sub.acquires
            out.global_acquires |= sub.global_acquires
            for reason, via, _ in sub.blocking:
                out.blocking.append(
                    (reason, via or callee.qualname, line))
        visiting.discard(fi.key)
        if not tainted or not visiting:
            self._closures[fi.key] = out
        return out, tainted


class TypeScope:
    """Static typing for one function body (the locks analyzer's
    resolution rules, lifted here so every interprocedural check
    shares them): annotations, direct constructions, annotated
    factories, and class attribute types."""

    __slots__ = ("g", "fn", "module", "env")

    def __init__(self, g: CallGraph, fn, module: ModuleInfo,
                 cls_name: Optional[str]):
        self.g = g
        self.fn = fn
        self.module = module
        self.env: dict = {}
        if cls_name is not None:
            self.env["self"] = cls_name
        self._build()

    def _learn(self, name: str, ty: Optional[str]) -> None:
        if ty is None:
            return
        cur = self.env.get(name)
        if cur is not None and cur != ty:
            self.env[name] = None    # conflicting: stop trusting it
        elif cur is None and name in self.env:
            pass                     # already poisoned
        else:
            self.env[name] = ty

    def _build(self) -> None:
        args = self.fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            n = ann_name(a.annotation)
            if self.g.class_named(n, self.module) is not None:
                self._learn(a.arg, n)
        for node in walk_scope(self.fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self._learn(node.targets[0].id,
                            self.type_of(node.value))
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                n = ann_name(node.annotation)
                if self.g.class_named(n, self.module) is not None:
                    self._learn(node.target.id, n)

    def type_of(self, node) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self.type_of(node.value)
            if base is not None:
                ci = self.g.classes.get(base)
                if ci is not None:
                    return self.g.attr_types(ci).get(node.attr)
            return None
        if isinstance(node, ast.Call):
            return self.g.infer_call_type(node, self.module)
        return None
