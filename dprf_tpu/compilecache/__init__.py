"""Compile-cost elimination layer (ISSUE 3).

The dominant *fixed* cost of every job is the jit warmup compile
(runtime/worker.py calls it out; the krb5aes smoke tier once spent ~9
minutes almost entirely in XLA compiles).  Every step shape we compile
is deterministic and repeated across workers, sessions, and bench runs
-- so this package wires JAX's persistent XLA compilation cache into
every execution path and makes its behavior observable:

  - ``enable()``          one idempotent entrypoint that points
                          ``jax_compilation_cache_dir`` at
                          ``$DPRF_COMPILE_CACHE_DIR`` (default
                          ``~/.cache/dprf/xla``, beside the tune cache)
                          with the persistence thresholds lowered so
                          our step compiles always persist.  Called
                          from the CLI (crack/serve/worker/bench/tune/
                          prewarm), dprf_tpu/bench.py, and the batch
                          autotuner.  Advisory: an unwritable dir or a
                          ``DPRF_COMPILE_CACHE=0`` kill switch degrades
                          to "no cache", never to a crashed job.
  - ``compile_observer``  times one step compile, classifies it as a
                          cache hit/miss, and publishes
                          ``dprf_compile_seconds{engine,cache}`` plus
                          ``dprf_compile_cache_hits_total`` /
                          ``_misses_total`` -- so "a stalled fleet that
                          is really compiling" is diagnosable from a
                          scrape or a telemetry snapshot
                          (tools/compile_report.py).
  - ``prewarm``           ahead-of-time cache population for a fleet
                          image (the ``dprf prewarm`` subcommand; see
                          compilecache/prewarm.py).

Classification: on jaxes with the ``jax_explain_cache_misses`` config
(``explain_capable``), the observer captures the compiler's own
per-compile "Persistent compilation cache hit/MISS" log lines -- the
EXACT classification (ISSUE 15).  The heuristic below stays the
fallback for windows the watch saw nothing in and for older jaxes: a
compile that wrote new entries into the cache dir is
a miss (exact -- JAX persists every compile at these thresholds); one
that wrote nothing and finished under the cold-compile floor
(``$DPRF_COMPILE_COLD_FLOOR_S``, default 5 s) is a hit.  A no-write
compile OVER the floor is still reported as a miss: that is what a
backend whose compiles cannot persist looks like, and calling it a hit
would hide exactly the cost this layer exists to eliminate.  Windows
that mix compile with real compute (an autotuner rung, a bench warmup
unit) classify by the entry delta alone -- ``classify_delta`` -- since
their wall time says nothing about the compile.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from dprf_tpu.utils import env as envreg

CACHE_DIR_ENV = "DPRF_COMPILE_CACHE_DIR"
#: kill switch: DPRF_COMPILE_CACHE=0 disables the persistent cache
DISABLE_ENV = "DPRF_COMPILE_CACHE"
COLD_FLOOR_ENV = "DPRF_COMPILE_COLD_FLOOR_S"
#: wall-time floor separating a deserialize-and-load cache hit from a
#: real XLA compile when the entry-count delta is zero.  The floor
#: only arbitrates that delta==0 case: a cold compile with the cache
#: enabled writes entries and is classified miss by the delta alone,
#: so the floor's job is telling a served hit (trace + executable
#: load, 0.2-2 s observed on a loaded CPU box) from a backend whose
#: compiles cannot persist at all (cold every time, typically tens of
#: seconds to minutes).  5 s splits those populations with headroom.
DEFAULT_COLD_FLOOR_S = 5.0

_lock = threading.Lock()
_state: dict = {"dir": None}
#: exact-classifier log-watch bookkeeping (ISSUE 15): refcounted
#: install of the jax._src.compiler capture handler, so nested
#: observers restore the logger's level/propagate exactly once
_watch_state: dict = {"count": 0, "saved": None}

#: `dprf check` locks analyzer: module-global cache state, written by
#: enable()/disable() and read from every compile site -- the serve
#: plane calls those from multiple threads.
GUARDED_BY = {
    "<module>": {"_lock": ("_state", "_watch_state")},
}


def default_cache_dir() -> str:
    """$DPRF_COMPILE_CACHE_DIR, or ~/.cache/dprf/xla (deliberately
    beside the tuning cache: one directory tree to bake into a fleet
    image carries both the tuned batches and their compiled steps)."""
    d = envreg.get_path(CACHE_DIR_ENV)
    if d:
        return d
    return os.path.join(os.path.expanduser("~"), ".cache", "dprf", "xla")


def cache_dir() -> Optional[str]:
    """The directory the cache is currently enabled on, or None."""
    with _lock:
        return _state["dir"]


def enabled() -> bool:
    with _lock:
        return _state["dir"] is not None


def enable(dir: Optional[str] = None, log=None) -> Optional[str]:
    """Turn on the persistent XLA compilation cache; returns the cache
    directory, or None when disabled/unusable.  Idempotent: re-calls
    with the same (or default) dir are no-ops; an explicit different
    dir re-points the cache (tests, ``prewarm --cache-dir``).

    The persistence thresholds are lowered to "persist everything":
    the default min-compile-time gate (1 s) would silently drop the
    very step compiles (some take ~1 s on CPU, minutes on TPU) this
    cache exists for, and a dropped entry reads as an eternal miss.
    """
    if not envreg.get_bool(DISABLE_ENV):
        return None
    d = os.path.abspath(dir or default_cache_dir())
    with _lock:
        if _state["dir"] == d:
            return d
        try:
            os.makedirs(d, exist_ok=True)
            probe = os.path.join(d, ".dprf-write-probe")
            with open(probe, "w") as fh:
                fh.write("ok")
            os.unlink(probe)
        except OSError as e:
            _warn(log, "compile cache dir unwritable; persistent "
                  "compilation cache DISABLED", dir=d, error=str(e))
            return None
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", d)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            # jax materializes its cache object AT MOST ONCE, at the
            # first compile -- a dir set (or changed) after that is
            # silently ignored unless the cache is reset.  Without
            # this, an enable() after any prior jit dispatch in the
            # process is a no-op that still *reports* enabled.
            _reset_backend_cache()
        except Exception as e:   # noqa: BLE001 -- an old jax without
            # these options must degrade, not kill the job
            _warn(log, "jax compilation-cache config rejected; "
                  "persistent compilation cache DISABLED", error=str(e))
            return None
        _state["dir"] = d
        if log is not None:
            log.info("persistent compile cache enabled", dir=d)
        return d


def _reset_backend_cache() -> None:
    """Drop jax's in-memory cache OBJECT so the next compile
    re-initializes it against the current config dir (on-disk entries
    are untouched)."""
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:   # noqa: BLE001 -- internal API; a jax that
        # moved it initializes lazily anyway on first-ever compile
        pass


def disable() -> None:
    """Undo enable() (tests).  Leaves on-disk entries alone."""
    with _lock:
        if _state["dir"] is None:
            return
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", None)
            _reset_backend_cache()
        except Exception:   # noqa: BLE001
            pass
        _state["dir"] = None


def _warn(log, msg: str, **kw) -> None:
    if log is not None:
        log.warn(msg, **kw)
    else:
        from dprf_tpu.utils.logging import DEFAULT
        DEFAULT.warn(msg, **kw)


def entry_count() -> Optional[int]:
    """Number of entries in the cache dir (None when disabled or
    unreadable).  JAX writes one flat file per cached executable, so a
    before/after count delta is an exact "did this compile persist
    anything new" signal for a single-process compile."""
    with _lock:
        d = _state["dir"]
    if d is None:
        return None
    try:
        return len(os.listdir(d))
    except OSError:
        return None


def cold_floor_s() -> float:
    return envreg.get_float(COLD_FLOOR_ENV, DEFAULT_COLD_FLOOR_S)


def classify_compile(seconds: float, entries_before: Optional[int] = None,
                     entries_after: Optional[int] = None) -> str:
    """"hit" | "miss" | "off" for one timed compile (see module
    docstring for the decision rule)."""
    if not enabled():
        return "off"
    if (entries_before is not None and entries_after is not None
            and entries_after > entries_before):
        return "miss"
    return "hit" if seconds < cold_floor_s() else "miss"


def classify_delta(entries_before: Optional[int],
                   entries_after: Optional[int]) -> str:
    """Entry-delta-only classification, for windows whose wall time
    mixes compile with real compute (autotuner rungs, bench warmup
    units): new entries -> miss, none -> hit.  The wall-time floor is
    deliberately NOT consulted -- a big rung's hashing would flip a
    genuine hit to 'miss' by sheer compute time."""
    if not enabled():
        return "off"
    if (entries_before is not None and entries_after is not None
            and entries_after > entries_before):
        return "miss"
    return "hit"


# ---------------------------------------------------------------------------
# exact hit/miss classification from the compiler's own log lines
# (ISSUE 15 satellite; closes the carried ROADMAP follow-up)

#: the logger jax's compile_or_get_cached path logs one line per
#: compile to: "Persistent compilation cache hit for '<module>'" /
#: "PERSISTENT COMPILATION CACHE MISS for '<module>'"
_JAX_COMPILER_LOGGER = "jax._src.compiler"
_HIT_MSG = "Persistent compilation cache hit"
_MISS_MSG = "PERSISTENT COMPILATION CACHE MISS"


def explain_capable() -> bool:
    """Newer-jax capability probe: the ``jax_explain_cache_misses``
    config option landed alongside the per-compile persistent-cache
    log lines this classifier captures (0.4.x era).  When absent, the
    entry-delta + wall-floor heuristic below stays the classifier."""
    try:
        import jax
        return hasattr(jax.config, "jax_explain_cache_misses")
    except Exception:   # noqa: BLE001 -- jax-less host
        return False


class _CacheLogWatch(logging.Handler):
    """Captures the compiler's per-compile hit/miss log lines for one
    observed window -- the EXACT classification (one line per XLA
    compile, emitted by the cache layer itself), replacing the
    entry-delta + wall-floor guess whenever it saw anything."""

    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.hits = 0
        self.misses = 0

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.msg if isinstance(record.msg, str) else \
            str(record.msg)
        if _HIT_MSG in msg:
            self.hits += 1
        elif _MISS_MSG in msg:
            self.misses += 1


def _watch_install(watch: _CacheLogWatch) -> None:
    """Attach a watch to the compiler logger.  The hit line logs at
    DEBUG unless ``jax_log_compiles`` is on, so the logger is dropped
    to DEBUG with propagation OFF for the window (the records land in
    our handler, not on the operator's console); the refcount restores
    both exactly once when the last nested observer exits."""
    logger = logging.getLogger(_JAX_COMPILER_LOGGER)
    with _lock:
        if _watch_state["count"] == 0:
            _watch_state["saved"] = (logger.level, logger.propagate)
            if logger.getEffectiveLevel() > logging.DEBUG:
                logger.setLevel(logging.DEBUG)
            logger.propagate = False
        _watch_state["count"] += 1
    logger.addHandler(watch)


def _watch_remove(watch: _CacheLogWatch) -> None:
    logger = logging.getLogger(_JAX_COMPILER_LOGGER)
    logger.removeHandler(watch)
    with _lock:
        _watch_state["count"] -= 1
        if _watch_state["count"] == 0 and _watch_state["saved"]:
            logger.setLevel(_watch_state["saved"][0])
            logger.propagate = _watch_state["saved"][1]
            _watch_state["saved"] = None


def compile_histogram(registry=None):
    """ONE declaration site for dprf_compile_seconds (worker warmup,
    bench, and prewarm all publish through here, so the label set can
    never drift).  The ``cache`` label is the hit/miss/off
    classification -- a scrape separates "fleet is cold-compiling"
    from "fleet is loading cached executables"."""
    from dprf_tpu.telemetry import get_registry
    return get_registry(registry).histogram(
        "dprf_compile_seconds", "step warmup/compile wall time",
        labelnames=("engine", "cache"))


def _cache_counters(registry=None) -> tuple:
    from dprf_tpu.telemetry import get_registry
    m = get_registry(registry)
    return (m.counter("dprf_compile_cache_hits_total",
                      "step compiles served from the persistent "
                      "compilation cache", labelnames=("engine",)),
            m.counter("dprf_compile_cache_misses_total",
                      "step compiles that ran XLA cold",
                      labelnames=("engine",)))


def observe_compile(engine: str, seconds: float, cache: str,
                    registry=None) -> None:
    """Publish one classified compile into the metric surface."""
    compile_histogram(registry).observe(seconds, engine=engine,
                                        cache=cache)
    hits, misses = _cache_counters(registry)
    if cache == "hit":
        hits.inc(engine=engine)
    elif cache == "miss":
        misses.inc(engine=engine)


class compile_observer:
    """Context manager around one step compile: times it, classifies
    hit/miss/off from the cache-dir entry delta + wall time, and
    publishes the metrics.  Build the compile's *arguments* before
    entering -- argument materialization can itself write tiny cache
    entries, which would misread a hit as a miss.

    Classification prefers the EXACT per-compile log lines the cache
    layer itself emits (``explain_capable`` jaxes; ISSUE 15): a
    window whose watch saw any line classifies from it alone -- any
    miss makes the window a miss, hits-only is a hit.  A window the
    watch saw nothing in (every executable already live in jax's
    in-memory cache, or an older jax) falls back to the entry-delta +
    wall-floor heuristic.

    Attributes after exit: ``seconds``, ``cache``.  Nothing is
    published when the body raises (a failed compile is not a compile
    cost, it is an error the caller handles)."""

    __slots__ = ("engine", "registry", "publish", "seconds", "cache",
                 "_t0", "_before", "_watch")

    def __init__(self, engine: str, registry=None, publish: bool = True):
        self.engine = engine
        self.registry = registry
        self.publish = publish
        self.seconds = 0.0
        self.cache = "off"
        self._watch: Optional[_CacheLogWatch] = None

    def __enter__(self) -> "compile_observer":
        if enabled() and explain_capable():
            self._watch = _CacheLogWatch()
            _watch_install(self._watch)
        self._before = entry_count()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.seconds = time.perf_counter() - self._t0
        watch, self._watch = self._watch, None
        if watch is not None:
            _watch_remove(watch)
        if exc_type is not None:
            return False
        if watch is not None and (watch.hits or watch.misses):
            self.cache = "miss" if watch.misses else "hit"
        else:
            self.cache = classify_compile(self.seconds, self._before,
                                          entry_count())
        if self.publish:
            observe_compile(self.engine, self.seconds, self.cache,
                            registry=self.registry)
        return False


__all__ = ["CACHE_DIR_ENV", "DISABLE_ENV", "COLD_FLOOR_ENV",
           "DEFAULT_COLD_FLOOR_S", "cache_dir", "classify_compile",
           "classify_delta", "cold_floor_s", "compile_histogram",
           "compile_observer", "default_cache_dir", "disable",
           "enable", "enabled", "entry_count", "explain_capable",
           "observe_compile"]
