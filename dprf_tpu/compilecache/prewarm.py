"""Ahead-of-time compile-cache population (`dprf prewarm`).

A worker joining a fleet should start hashing in seconds, not minutes:
every step shape a job will compile is deterministic, so a fleet image
can be baked with the persistent compilation cache already populated.
This module iterates (engine, attack, batch) specs -- seeded from the
tuning cache's entries and/or an explicit --engines/--attacks list --
builds each worker's step through the SAME factory path a job uses,
and compiles it ahead of time (``jax.jit(...).lower().compile()``)
without sweeping any keyspace.  A later job warmup of the same shape
then loads the cached executable instead of re-running XLA.

Mask shapes prewarm self-contained.  Wordlist shapes require the
job's REAL wordlist (and rule set): the compiled program embeds the
packed word table as constants, so content is part of the cache key.

Fan-out: ``jobs > 1`` shards the spec list over child processes (XLA
compiles hold the GIL-free C++ thread but each process compiles one
program at a time; independent specs parallelize across processes).
Each child is this same entrypoint with ``--spec-json``; results come
back as marker-prefixed JSON lines on stdout, so a partially-failed
child still reports every spec it finished.
"""

from __future__ import annotations

import dataclasses
import json
import sys
from typing import List, Optional, Sequence

#: stdout marker for child -> parent result lines
RESULT_MARKER = "PREWARM_JSON:"

#: fallback batch when a spec has no tuning-cache entry (matches the
#: CLI's pre-tuning default, cli.DEFAULT_BATCH)
DEFAULT_BATCH = 1 << 18


@dataclasses.dataclass
class PrewarmSpec:
    engine: str
    #: "mask" | "wordlist" | "combinator" | "hybrid-wm" | "hybrid-mw"
    attack: str = "mask"
    batch: int = DEFAULT_BATCH
    hit_cap: int = 64
    mask: str = "?a?a?a?a?a?a?a?a"
    rules: Optional[str] = None
    #: wordlist/hybrid attacks: the REAL wordlist file.  The compiled
    #: program embeds the packed word table as constants (verified:
    #: identical content hits, different content misses), so a
    #: synthetic stand-in would cache a program no job ever runs --
    #: "covered" in the report, cold on the fleet.
    wordlist: Optional[str] = None
    #: combinator attacks: the job's REAL "LEFT,RIGHT" word files
    #: (both tables are embedded, same contract as wordlist)
    combinator: Optional[str] = None
    #: >1 = the sharded (multi-chip mesh) step shape at this many
    #: devices; skipped gracefully when the host has fewer
    devices: int = 1

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "PrewarmSpec":
        return cls(**{k: v for k, v in d.items()
                      if k in {f.name for f in dataclasses.fields(cls)}})


class SkipSpec(Exception):
    """A spec this HOST cannot prewarm (e.g. a sharded shape on a
    single-device box) -- reported as skipped, never as an error."""


@dataclasses.dataclass
class PrewarmResult:
    engine: str
    attack: str
    batch: int
    compile_s: float = 0.0
    cache: str = "off"              # hit | miss | off | skip
    error: Optional[str] = None
    devices: int = 1
    skipped: Optional[str] = None   # why the host skipped the spec

    def as_dict(self) -> dict:
        d = {"engine": self.engine, "attack": self.attack,
             "batch": self.batch, "compile_s": round(self.compile_s, 3),
             "cache": self.cache, "devices": self.devices}
        if self.error:
            d["error"] = self.error
        if self.skipped:
            d["skipped"] = self.skipped
        return d


def tune_seeded_specs(device: str = "jax", hit_cap: int = 64,
                      mask: str = "?a?a?a?a?a?a?a?a",
                      rules: Optional[str] = None,
                      wordlist: Optional[str] = None,
                      devices: int = 1,
                      log=None) -> List[PrewarmSpec]:
    """Specs for every tuning-cache entry recorded for this device:
    `dprf tune` has already decided the batch each engine runs at, so
    those are exactly the shapes a fleet will compile.

    Wordlist entries need the job's ACTUAL wordlist (and rule set):
    the compiled program embeds the packed word table and the rule
    operations, so prewarming a wordlist shape with stand-ins would
    cache a program no real job runs -- reported as covered while the
    fleet still cold-compiles.  Without --wordlist those entries are
    skipped loudly, never faked."""
    from dprf_tpu.tune import default_cache, env_fingerprint
    cache = default_cache()
    specs: List[PrewarmSpec] = []
    for key, entry in sorted(cache.entries().items()):
        parts = dict(p.split("=", 1) for p in key.split("|") if "=" in p)
        if parts.get("device") != device:
            continue
        engine = parts.get("engine")
        attack = parts.get("attack", "mask")
        if not engine or attack not in ("mask", "wordlist"):
            continue
        if attack == "wordlist" and not wordlist:
            if log is not None:
                log.warn("skipping wordlist tune entry: prewarming "
                         "its program needs the job's real wordlist "
                         "(--wordlist, and --rules if the job uses "
                         "one)", key=key)
            continue
        # env-validated exactly like a job's lookup: a stale entry
        # (jax upgrade, engine edit, other chip) would prewarm a batch
        # no `--batch auto` job will resolve to -- reported covered
        # while the fleet still cold-compiles
        entry = cache.get(key, env_fingerprint(engine, device))
        if entry is None:
            if log is not None:
                log.warn("skipping stale tune entry (environment "
                         "fingerprint mismatch); re-run `dprf tune`",
                         key=key)
            continue
        try:
            batch = int(entry.get("batch", 0))
        except (TypeError, ValueError):
            continue
        if batch <= 0:
            continue
        try:
            cap = int(parts.get("hit_cap", hit_cap))
        except ValueError:
            cap = hit_cap
        specs.append(PrewarmSpec(
            engine=engine, attack=attack, batch=batch, hit_cap=cap,
            mask=mask,
            rules=rules if attack == "wordlist" else None,
            wordlist=wordlist if attack == "wordlist" else None,
            devices=max(1, int(devices))))
    return specs


def explicit_specs(engines: Sequence[str], attacks: Sequence[str],
                   hit_cap: int = 64, mask: str = "?a?a?a?a?a?a?a?a",
                   rules: Optional[str] = None,
                   wordlist: Optional[str] = None,
                   combinator: Optional[str] = None,
                   batch=None, devices: int = 1) -> List[PrewarmSpec]:
    """engines x attacks, batch resolved per engine from the tuning
    cache (``batch=None``/"auto") or pinned by an explicit int.  The
    tuned-batch lookup carries the same key extras a job's resolution
    uses (hit_cap, and rules_n for wordlist attacks with a rule set),
    so prewarm compiles the batch the job will actually run.
    ``devices > 1`` builds every spec's SHARDED (multi-chip mesh)
    shape instead of the single-device one."""
    from dprf_tpu.tune import lookup_tuned_batch
    rules_n = None
    if rules:
        from dprf_tpu.rules.parser import load_rules
        rules_n = len(load_rules(rules))
    specs = []
    for eng in engines:
        for attack in attacks:
            if batch in (None, "auto"):
                extras = {"hit_cap": hit_cap}
                if attack == "wordlist" and rules_n:
                    extras["rules_n"] = rules_n
                b = lookup_tuned_batch(eng, attack=attack, device="jax",
                                       extras=extras) or DEFAULT_BATCH
            else:
                b = int(batch)
            hybrid = attack in ("hybrid-wm", "hybrid-mw")
            specs.append(PrewarmSpec(
                engine=eng, attack=attack, batch=b, hit_cap=hit_cap,
                mask=mask,
                rules=rules if attack == "wordlist" else None,
                wordlist=(wordlist if attack == "wordlist" or hybrid
                          else None),
                combinator=(combinator if attack == "combinator"
                            else None),
                devices=max(1, int(devices))))
    return specs


def _combinator_gen(spec: PrewarmSpec, oracle):
    """Combinator/hybrid generator from the spec's REAL word files
    (both side tables are embedded in the compiled program, so
    stand-ins are refused exactly like wordlist shapes; the hybrid
    mask side is synthesized from spec.mask, as in a real job)."""
    from dprf_tpu.cli import _build_combinator_gen
    from dprf_tpu.utils.logging import DEFAULT as log
    if spec.attack == "combinator":
        if not spec.combinator:
            raise ValueError(
                "combinator prewarm needs the job's real left,right "
                "word files (--combinator LEFT,RIGHT): the compiled "
                "program embeds both word tables")
        arg = spec.combinator
    else:
        if not spec.wordlist:
            raise ValueError(
                f"{spec.attack} prewarm needs the job's real wordlist "
                "(--wordlist FILE): the compiled program embeds the "
                "word table, so a synthetic list would cache a "
                "program no job runs")
        arg = (f"{spec.wordlist},{spec.mask}"
               if spec.attack == "hybrid-wm"
               else f"{spec.mask},{spec.wordlist}")
    gen, _, _ = _build_combinator_gen(spec.attack, arg, {}, None,
                                      oracle, "jax", log)
    return gen


def _build_worker(spec: PrewarmSpec):
    """The job path's worker for this spec (engine factory selection
    included, so the prewarmed program is the one a real job runs)."""
    from dprf_tpu import get_engine
    oracle = get_engine(spec.engine, device="cpu")
    dev = get_engine(spec.engine, device="jax")
    # unmatchable single target (bench's trick: prewarm needs the step
    # shape, not cracks); engines whose targets need salts/params
    # raise here and are reported as skipped
    target = oracle.parse_target("ff" * oracle.digest_size)
    if spec.attack == "wordlist":
        if not spec.wordlist:
            raise ValueError(
                "wordlist-attack prewarm needs the job's real wordlist "
                "(--wordlist FILE): the compiled program embeds the "
                "packed word table, so a synthetic list would cache a "
                "program no job runs")
        from dprf_tpu.cli import _wordlist_max_len
        from dprf_tpu.generators.wordlist import WordlistRulesGenerator
        # same packing width as the job (coordinator-derived), so the
        # cached program is byte-identical to the one a worker warms
        gen = WordlistRulesGenerator.from_files(
            spec.wordlist, spec.rules,
            max_len=_wordlist_max_len(spec.engine, oracle, "jax"))
        maker_name = "make_wordlist_worker"
    elif spec.attack in ("combinator", "hybrid-wm", "hybrid-mw"):
        gen = _combinator_gen(spec, oracle)
        maker_name = "make_combinator_worker"
    else:
        from dprf_tpu.generators.mask import MaskGenerator
        gen = MaskGenerator(spec.mask)
        maker_name = "make_mask_worker"
    if spec.devices > 1:
        # sharded (multi-chip mesh) shape through the UNIFIED sharded
        # runtime (parallel/sharded.py) -- the same engine factory
        # path a `--devices N` job selects, so the cached programs
        # (per-batch step AND the capped superstep big units dispatch)
        # are exactly the ones a job warms
        import jax
        have = len(jax.devices())
        if have < spec.devices:
            raise SkipSpec(f"host has {have} device(s); the sharded "
                           f"shape needs {spec.devices}")
        from dprf_tpu.parallel.mesh import make_mesh
        smaker = getattr(
            dev, "make_sharded_" + maker_name[len("make_"):], None)
        if not callable(smaker):
            # a `--devices N` job for this engine warns and falls back
            # to one chip (cli._select_worker); mirror that as a skip,
            # not an error, so a fleet-wide sharded bake over mixed
            # engines doesn't read as failed
            raise SkipSpec(f"engine {spec.engine} has no sharded "
                           f"{spec.attack} worker (a job falls back "
                           "to one chip)")
        per_dev = (max(1, spec.batch // gen.n_rules)
                   if spec.attack == "wordlist" else spec.batch)
        return smaker(gen, [target], make_mesh(spec.devices), per_dev,
                      hit_capacity=spec.hit_cap, oracle=oracle)
    maker = getattr(dev, maker_name, None)
    if not callable(maker):
        raise ValueError(f"engine {spec.engine} has no {spec.attack} "
                         "device worker")
    return maker(gen, [target], batch=spec.batch,
                 hit_capacity=spec.hit_cap, oracle=oracle)


def prewarm_one(spec: PrewarmSpec, log=None) -> PrewarmResult:
    """Build + compile one spec's step; never raises (a fleet-image
    prewarm must report per-spec failures and keep going)."""
    try:
        worker = _build_worker(spec)
        if not getattr(worker, "_warmed", False):
            # AOT: populate the cache without dispatching
            worker.aot_compile()
        # (Pallas workers arrive warmed by their factory -- their
        # compile already went through the observer.)
        return PrewarmResult(
            spec.engine, spec.attack, spec.batch,
            compile_s=getattr(worker, "compile_seconds", 0.0),
            cache=getattr(worker, "compile_cache", "off"),
            devices=spec.devices)
    except SkipSpec as e:
        # not an error: this host simply cannot compile the shape
        # (e.g. a sharded spec on a single-device box); the fleet
        # image builder runs prewarm on a host that can
        if log is not None:
            log.info("prewarm spec skipped", engine=spec.engine,
                     attack=spec.attack, devices=spec.devices,
                     reason=str(e))
        return PrewarmResult(spec.engine, spec.attack, spec.batch,
                             cache="skip", devices=spec.devices,
                             skipped=str(e))
    except Exception as e:   # noqa: BLE001 -- parse/build/compile errors
        if log is not None:
            log.warn("prewarm spec failed", engine=spec.engine,
                     attack=spec.attack,
                     error=f"{type(e).__name__}: {e}")
        return PrewarmResult(spec.engine, spec.attack, spec.batch,
                             devices=spec.devices,
                             error=f"{type(e).__name__}: {e}")


def run_prewarm(specs: Sequence[PrewarmSpec], jobs: int = 1,
                log=None) -> List[PrewarmResult]:
    """Compile every spec; ``jobs > 1`` fans out over child processes
    (round-robin sharding keeps heavyweight engines spread out)."""
    specs = list(specs)
    if jobs <= 1 or len(specs) <= 1:
        return [prewarm_one(s, log=log) for s in specs]
    return _run_children(specs, jobs, log=log)


def _run_children(specs: List[PrewarmSpec], jobs: int,
                  log=None) -> List[PrewarmResult]:
    import subprocess

    from dprf_tpu import compilecache
    shards = [specs[i::jobs] for i in range(min(jobs, len(specs)))]
    procs = []
    for shard in shards:
        cmd = [sys.executable, "-m", "dprf_tpu", "prewarm", "--jobs",
               "1", "-q", "--spec-json",
               json.dumps([s.as_dict() for s in shard])]
        if compilecache.cache_dir():
            # children must write the SAME cache the parent enabled
            # (an explicit --cache-dir would otherwise be lost: env
            # resolution in the child picks the default)
            cmd += ["--cache-dir", compilecache.cache_dir()]
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE, text=True))
    results: List[PrewarmResult] = []
    for shard, proc in zip(shards, procs):
        out, err = proc.communicate()
        got = []
        for line in out.splitlines():
            if line.startswith(RESULT_MARKER):
                try:
                    d = json.loads(line[len(RESULT_MARKER):])
                    got.append(PrewarmResult(
                        d["engine"], d["attack"], d["batch"],
                        compile_s=d.get("compile_s", 0.0),
                        cache=d.get("cache", "off"),
                        error=d.get("error"),
                        devices=d.get("devices", 1),
                        skipped=d.get("skipped")))
                except (ValueError, KeyError):
                    continue
        reported = {(r.engine, r.attack, r.batch, r.devices)
                    for r in got}
        for s in shard:                    # child died mid-shard
            if (s.engine, s.attack, s.batch, s.devices) not in reported:
                got.append(PrewarmResult(
                    s.engine, s.attack, s.batch, devices=s.devices,
                    error=f"prewarm child rc={proc.returncode}"))
        if proc.returncode != 0 and log is not None:
            log.warn("prewarm child failed", rc=proc.returncode,
                     stderr=err[-500:])
        results.extend(got)
    return results


def render_table(results: Sequence[PrewarmResult]) -> str:
    """The human summary `dprf prewarm` prints to stderr via the log
    (the stdout JSON line stays machine-parseable)."""
    rows = [("engine", "attack", "devs", "batch", "compile_s",
             "cached?")]
    for r in results:
        status = (r.error if r.error
                  else f"skipped ({r.skipped})" if r.skipped
                  else {"hit": "yes", "miss": "no (now cached)"}.get(
                      r.cache, r.cache))
        rows.append((r.engine, r.attack, str(r.devices), str(r.batch),
                     f"{r.compile_s:.2f}", status))
    widths = [max(len(row[i]) for row in rows)
              for i in range(len(rows[0]))]
    return "\n".join("  ".join(c.ljust(w) for c, w in zip(row, widths))
                     for row in rows)
