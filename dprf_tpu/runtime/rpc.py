"""Host-level distributed backend: coordinator RPC + remote workers.

Inside one host/slice, parallelism is XLA collectives over ICI (the
sharded steps in dprf_tpu/parallel) -- there is no NCCL/MPI analogue to
manage.  ACROSS hosts, the control plane is deliberately tiny, exactly
the Dispatcher surface: lease a WorkUnit, report hits, complete.  This
module is that control plane: newline-delimited JSON over TCP.

    coordinator (dprf serve):  owns Dispatcher + found set + potfile/
        session persistence; hands out leases under a lock.
    worker (dprf worker):      connects, receives the job description,
        rebuilds engine/generator/targets locally, then loops
        lease -> fused device sweep -> complete(hits).

Fault model: a worker that dies simply stops leasing; its outstanding
unit's lease expires and the Dispatcher reissues it (idempotent -- units
are pure functions of the index range).  A worker that reports hits for
an already-reissued unit is harmless: hits are deduped by target.

Trust model: optional shared-secret authentication (--token).  When the
coordinator has a token, every connection must answer an HMAC-SHA256
challenge on hello before any other op is served (the challenge nonce
rotates after every failed attempt and a connection is dropped after a
few failures, so a connection cannot grind guesses against one nonce);
the worker may send its own nonce in hello, and the coordinator's reply
proves knowledge of the token over it -- mutual authentication.
Without a token the protocol is open -- bind to localhost or a trusted
network only (same stance as hashtopolis-style agents).  The transport
is cleartext either way: the token authenticates peers, it does not
encrypt the job.  The job description includes the raw hashlist lines;
wordlist files must exist on each worker host (they are referenced by
path, never shipped).
"""

from __future__ import annotations

import hmac as hmac_mod
import json
import re
import secrets
import socket
import socketserver
import threading
import time
from typing import Callable, Optional

from dprf_tpu.jobs.scheduler import CANCELLED as JOB_CANCELLED
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.worker import Hit
from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.telemetry import declare_job_metrics, get_registry
from dprf_tpu.telemetry import perf as perf_mod
from dprf_tpu.telemetry import profiler as profiler_mod
from dprf_tpu.telemetry import programs as programs_mod
from dprf_tpu.telemetry.alerts import AlertEngine
from dprf_tpu.telemetry.health import HealthRegistry, heartbeat_interval
from dprf_tpu.telemetry.trace import get_tracer, jax_profile_ctx

MAX_LINE = 64 << 20   # hashlists can be large; candidates never cross

#: leases one worker may hold at once (and the clamp on a lease
#: request's ``ahead``): bounds how much of the queue a buggy or
#: greedy client can vacuum into one host's ledger
MAX_LEASE_AHEAD = 16

#: spans one op_trace_push message may carry (a worker's whole local
#: ring, vs the per-unit MAX_INGEST_SPANS bound on complete/fail)
TRACE_PUSH_MAX = 2048

#: lock-discipline declarations (`dprf check` locks analyzer).  Every
#: worker connection is its own handler thread in a
#: ThreadingTCPServer, all mutating this state: the listed
#: CoordinatorState attributes must only be touched inside ``with
#: <state>.lock`` (or a method annotated ``_holds_lock``).  The
#: _CompletionSender flags are single-writer latched (assigned only by
#: its own thread's ``_run``, read cross-thread) -- GIL-atomic by
#: design, which ``<atomic>`` makes the checker enforce rather than
#: assume.
GUARDED_BY = {
    "CoordinatorState": {
        "lock": ("found", "dispatcher", "scheduler", "rejected",
                 "worker_rejects", "unit_reject_workers",
                 "quarantined", "_pull_epoch", "_profile_requests",
                 "_profile_summaries", "_profile_seq",
                 "_profile_last", "_profile_inflight",
                 "_profile_unread"),
    },
    "_CompletionSender": {"<atomic>": ("error", "stop_seen")},
}

#: kernel-profile summaries retained per worker (op_profile serves
#: the newest first; older captures live in the session journal)
PROFILE_SUMMARIES_PER_WORKER = 4

#: a pending capture request nobody picked up (worker named wrong,
#: dead, or never leasing) expires after this long -- the table stays
#: bounded and a stale entry can't suppress that worker's future
#: auto-captures forever
PROFILE_REQUEST_TTL_S = 600.0

#: a DELIVERED capture request whose summary never came back (worker
#: died mid-capture) expires after this long; until then the serve
#: drain loop keeps the RPC plane up so a capture racing the job's
#: end can still land its push
PROFILE_INFLIGHT_TTL_S = 180.0

#: an UNDELIVERED request holds the serve drain only this long: its
#: target either leases within seconds (delivery moves it to the
#: inflight ledger) or already exited -- the full request TTL would
#: pin a finished serve for minutes on a dead target
PROFILE_QUEUED_DRAIN_S = 30.0

#: a landed-but-unread summary holds the serve drain this long: the
#: requester polls op_profile every ~0.5 s, so without this grace the
#: drain could break between the worker's push and the poller's next
#: read and the CLI would hit a closed socket instead of its summary
PROFILE_READ_GRACE_S = 10.0

#: resource-ownership declarations (`dprf check` threads analyzer):
#: every socket/stream attribute acquired outside a ``with`` names
#: the method that releases it, and the analyzer verifies that
#: method really closes it on the shutdown path.
RELEASES = {
    "CoordinatorClient": {"_sock": "close", "_fh": "close"},
}

#: `dprf check` retrace analyzer: the remote pipelined sweep loop --
#: a host sync here serializes the device stream against RPC latency.
HOT_PATHS = ("worker_loop",)


class RpcError(RuntimeError):
    """Protocol-level failure talking to the coordinator (error
    response, auth failure).  Distinct from RuntimeError so the CLI can
    report it cleanly without swallowing unrelated internal errors."""


# ---------------------------------------------------------------------------
# framing

def send_msg(sock: socket.socket, obj: dict) -> None:
    sock.sendall(json.dumps(obj, separators=(",", ":")).encode() + b"\n")


def recv_msg(fh) -> Optional[dict]:
    line = fh.readline(MAX_LINE)
    if not line:
        return None
    if not line.endswith(b"\n"):
        # readline returned MAX_LINE bytes without a newline: reject
        # loudly instead of parsing a truncated message and desyncing
        # the framing on whatever bytes remain
        raise ValueError(f"message exceeds the {MAX_LINE}-byte frame limit")
    return json.loads(line)


# ---------------------------------------------------------------------------
# coordinator side

class CoordinatorState:
    """Shared, locked serve-plane state behind the RPC handlers.

    Multi-tenant (ISSUE 8): the state owns a jobs.JobScheduler -- a
    queue of Job records, each with its OWN Dispatcher, found set, hit
    buffer, verifier, and limits -- and the ctor's (job, dispatcher,
    n_targets, verifier) become the DEFAULT job (id = the dispatcher's
    ``job_id``, "j0").  ``self.job`` / ``self.dispatcher`` /
    ``self.found`` / ``self.verifier`` stay aliases of that default
    job, so every pre-multi-tenant caller and client reads exactly
    what it always did; further jobs arrive over ``op_job_submit``.
    """

    def __init__(self, job: dict, dispatcher: Dispatcher, n_targets: int,
                 on_hit: Optional[Callable] = None,
                 on_progress: Optional[Callable] = None,
                 verifier: Optional[Callable] = None,
                 token: Optional[str] = None, registry=None,
                 recorder=None, scheduler=None, job_builder=None,
                 on_job_hit: Optional[Callable] = None,
                 on_job_event: Optional[Callable] = None,
                 on_job_progress: Optional[Callable] = None,
                 owner: str = "local", priority: int = 1,
                 quota: Optional[int] = None,
                 owner_quotas: Optional[dict] = None):
        from dprf_tpu.jobs.scheduler import JobScheduler
        self.job = job                    # serializable job description
        self.dispatcher = dispatcher
        self.n_targets = n_targets
        self.on_hit = on_hit              # (target_index, cand_index, plain)
        self.on_progress = on_progress
        #: per-job (Job, target_index, cand_index, plain): the
        #: multi-tenant hit hook (session journaling, potfile) -- fires
        #: for EVERY job, where on_hit stays default-job-only
        self.on_job_hit = on_job_hit
        #: (kind, Job) for job lifecycle events ("submit", "cancel",
        #: "pause", "resume") -- how the serve front-end journals them
        self.on_job_event = on_job_event
        #: (job_id, completed_intervals, coverage_digest) after every
        #: landed complete: the per-job session-journal hook (tagged
        #: ``units`` records, digest riding each snapshot -- ISSUE 19)
        self.on_job_progress = on_job_progress
        #: spec -> (wire_job, dispatcher, targets, verifier) for
        #: op_job_submit; defaults to jobs.build.build_job_runtime
        self.job_builder = job_builder
        #: (target_index, plaintext) -> bool.  A worker with a buggy or
        #: malicious device path could report a wrong plaintext; accepting
        #: it would permanently mark the target found and poison the
        #: potfile/session journal.  One oracle hash per hit is negligible.
        self.verifier = verifier
        self.rejected = 0
        #: a worker whose hits keep failing verification has a broken
        #: (or malicious) device path; quarantining it stops the
        #: lease -> reject -> requeue livelock (same unit bouncing to
        #: the same worker forever).
        self.worker_rejects: dict[str, int] = {}
        self.unit_reject_workers: dict[tuple, set] = {}
        self.quarantined: set[str] = set()
        self.token = token                # None = unauthenticated protocol
        self.lock = threading.Lock()
        self.t0 = time.perf_counter()
        #: flight-recorder pull epoch (op_trace_pull arm=True bumps
        #: it): lease responses carry it, and a worker seeing a new
        #: epoch ships its LOCAL ring back via op_trace_push
        self._pull_epoch = 0
        self.scheduler = scheduler if scheduler is not None \
            else JobScheduler(registry=registry,
                              owner_quotas=owner_quotas)
        default = self.scheduler.add(
            job, dispatcher, n_targets, verifier=verifier,
            owner=owner, priority=priority, quota=quota,
            job_id=dispatcher.job_id)
        #: the default job's found set IS self.found (same dict): the
        #: single-job callers that read/seed state.found keep working
        self.found = default.found
        self.default_job_id = default.job_id
        #: the registry the RPC port's /metrics endpoint serves; the
        #: Dispatcher publishes unit/keyspace metrics into the same one
        self.registry = get_registry(registry)
        #: the flight recorder op_trace_tail serves; should be the
        #: SAME one the Dispatcher records into so the timeline is
        #: whole (both default to the process-wide recorder)
        self.tracer = get_tracer(recorder)
        #: fleet health plane (ISSUE 10): worker state machine +
        #: straggler detection fed by op_heartbeat and the
        #: lease/complete traffic; evaluated by health_tick on the
        #: DPRF_ALERT_EVAL_S loop (cli.cmd_serve's HealthMonitor)
        self.health = HealthRegistry(registry=registry)
        #: declarative alert rules over the same registry; pending ->
        #: firing -> resolved lifecycle served via op_alerts
        self.alerts = AlertEngine(registry=registry)
        #: compiled-program registry (ISSUE 13): the coordinator's own
        #: compile sites land here, and op_heartbeat merges the
        #: records workers ship -- op_programs serves the fleet view.
        #: Has its own lock (never touched under self.lock).
        self.programs = programs_mod.get_programs()
        #: (transition dict) hook: cmd_serve journals each fleet
        #: health transition as a {"type": "worker_health"} record;
        #: fired by health_tick UNDER the lock so the journal writes
        #: serialize with the hit/progress writers
        self.on_worker_health: Optional[Callable] = None
        #: kernel-profiling plane (ISSUE 15): pending capture
        #: requests per worker (delivered on the next lease/heartbeat
        #: response), the sanitized summaries workers pushed back,
        #: and the auto-capture cooldown ledger
        self._profile_requests: dict = {}
        self._profile_summaries: dict = {}
        self._profile_seq = 0
        self._profile_last: dict = {}
        #: delivered-but-unanswered capture requests ({id: delivered
        #: monotonic ts}): serve's drain loop waits on these so a
        #: capture racing job-end can land; TTL-expired by the prune
        self._profile_inflight: dict = {}
        #: per-worker monotonic ts of a summary push nobody has read
        #: yet: holds the serve drain for a short grace so the
        #: requester's next poll can collect it (cleared only for the
        #: workers a read actually shipped -- a filtered poll for
        #: worker A must not drop worker B's grace)
        self._profile_unread: dict = {}
        #: (worker, summary) hook: cmd_serve journals each pushed
        #: capture as a {"type": "profile"} record; fired UNDER the
        #: lock like the other journaling hooks
        self.on_profile: Optional[Callable] = None
        m = self.registry
        #: verify-phase attribution (telemetry/perf.py): the oracle
        #: re-hash cost of every hit batch, labeled per job
        self._h_phase = perf_mod.phase_histogram(m)
        jm = declare_job_metrics(m)
        self._m_hits = jm["hits"]
        self._m_rejects = jm["rejects"]
        self._m_cands = jm["cands"]
        self._g_targets = jm["targets"]
        self._g_found = jm["found"]
        self._m_rpc = m.counter(
            "dprf_rpc_requests_total", "RPC ops served",
            labelnames=("op",))
        self._g_quar = m.gauge(
            "dprf_workers_quarantined", "workers benched for repeated "
            "unverifiable hits")
        self._g_seen = m.gauge(
            "dprf_worker_last_seen_timestamp",
            "unix time of each worker's last lease/complete/"
            "heartbeat (ISSUE 10: heartbeats widened this beyond "
            "lease holders)",
            labelnames=("worker",))
        self._g_targets.set(n_targets)
        self._g_found.set(0)
        self._g_quar.set(0)

    #: distinct worker ids the liveness gauge will track; label
    #: children live for the registry's lifetime, so id CHURN (every
    #: restart is a new hostname:pid) must not grow coordinator memory
    #: without bound on a long-lived job
    MAX_WORKER_LABELS = 1024

    def _touch_worker(self, wid: str) -> None:
        """Liveness: scrape-visible last-contact time per worker.
        Past the label cap, overflow ids share one child -- the fleet
        stays observable even when individual ids stop being.  (The
        check-then-set pair is not atomic; concurrent handlers can
        overshoot the cap by a few children, which is fine -- the cap
        bounds growth, it is not an exact quota.)"""
        if (not self._g_seen.has_labels(worker=wid)
                and self._g_seen.child_count() >= self.MAX_WORKER_LABELS):
            wid = "_overflow"
        self._g_seen.set(time.time(), worker=wid)

    def health_tick(self) -> None:
        """One fleet-health evaluation pass (ISSUE 10), driven by the
        HealthMonitor loop every ``DPRF_ALERT_EVAL_S`` seconds: age
        the worker state machine + straggler detection, update the
        per-job SLO gauges, journal the drained transitions, then run
        the alert rules against the registry.  Lock discipline: the
        health registry and alert engine evaluate under their OWN
        locks (never nested inside ours); only the scheduler pass and
        the journaling callback take ``self.lock``."""
        transitions = self.health.evaluate()
        with self.lock:
            self.scheduler.update_slos()
            if self.on_worker_health:
                for tr in transitions:
                    self.on_worker_health(tr)
        events = self.alerts.evaluate()
        # alert-triggered kernel profiling (ISSUE 15): a straggler or
        # stalled-job alert FIRING requests one bounded capture window
        # on the implicated worker, cooldown-rate-limited
        self._maybe_autoprofile(events)

    def _maybe_autoprofile(self, events: list) -> None:
        """Queue a capture request for each newly-firing straggler /
        job_stalled alert (``DPRF_AUTOPROFILE``): the straggler rule
        names its worker in the labels; a stalled job implicates the
        fleet's slowest live worker.  One request per cooldown window
        (``DPRF_PROFILE_COOLDOWN_S``, global AND per worker) -- a
        flapping fleet must not spend its cycles profiling itself."""
        if not profiler_mod.autoprofile_enabled():
            return
        fired = [e for e in events
                 if e.get("state") == "firing"
                 and e.get("rule") in ("straggler", "job_stalled")]
        if not fired:
            return
        cooldown = profiler_mod.cooldown_s()
        now = time.monotonic()
        from dprf_tpu.utils.logging import DEFAULT as log
        # resolved OUTSIDE self.lock: slowest_worker takes the health
        # registry's own lock, and health_tick's contract is that the
        # two are acquired sequentially, never nested
        slowest = (self.health.slowest_worker()
                   if any("worker" not in (e.get("labels") or {})
                          for e in fired) else None)
        with self.lock:
            self._prune_profile_requests(now)
            for e in fired:
                worker = (e.get("labels") or {}).get("worker")
                if worker is None:
                    worker = slowest
                if worker is None or worker in self._profile_requests:
                    continue
                if len(self._profile_requests) >= self.MAX_WORKER_LABELS:
                    break       # table bound; entries expire by TTL
                last = max((self._profile_last.get("_global", 0.0),
                            self._profile_last.get(str(worker), 0.0)))
                if last and now - last < cooldown:
                    continue
                self._profile_seq += 1
                self._profile_requests[str(worker)] = {
                    "id": self._profile_seq,
                    "seconds": profiler_mod.default_window_s(),
                    "trigger": str(e.get("rule")),
                    "queued_at": now}
                self._profile_last["_global"] = now
                self._profile_last[str(worker)] = now
                log.info("auto-capture requested", worker=worker,
                         rule=e.get("rule"))

    def _prune_profile_requests(self, now: float) -> None:
        """Expire pending capture requests nobody picked up inside
        the TTL (dead / misnamed / never-leasing workers) and
        delivered requests whose summary never came back: keeps the
        client-fed tables bounded, unsticks auto-capture, and
        unblocks the serve drain loop."""
        stale = [w for w, r in self._profile_requests.items()
                 if now - r.get("queued_at", now)
                 > PROFILE_REQUEST_TTL_S]
        for w in stale:
            del self._profile_requests[w]
        dead = [rid for rid, ts in self._profile_inflight.items()
                if now - ts > PROFILE_INFLIGHT_TTL_S]
        for rid in dead:
            del self._profile_inflight[rid]
        unread = [w for w, ts in self._profile_unread.items()
                  if now - ts > PROFILE_READ_GRACE_S]
        for w in unread:
            del self._profile_unread[w]
    _prune_profile_requests._holds_lock = "lock"

    def _profile_request_for(self, wid: str) -> Optional[dict]:
        """Pop the pending capture request riding out on this
        worker's next lease/heartbeat response (None for most)."""
        if not self._profile_requests:
            return None
        req = self._profile_requests.pop(wid, None)
        if req is None:
            return None
        self._profile_inflight[req["id"]] = time.monotonic()
        req = dict(req)
        req.pop("queued_at", None)    # coordinator-clock bookkeeping
        return req
    _profile_request_for._holds_lock = "lock"

    def profile_pending(self) -> bool:
        """True while a capture request is delivered but unanswered
        (inside its TTL), or queued and young enough that delivery is
        still plausible: the serve drain loop keeps the RPC plane up
        for these, so a capture racing the job's last units can still
        land its summary."""
        with self.lock:
            now = time.monotonic()
            self._prune_profile_requests(now)
            if self._profile_inflight:
                return True
            if any(now - ts < PROFILE_READ_GRACE_S
                   for ts in self._profile_unread.values()):
                return True
            return any(now - r.get("queued_at", now)
                       < PROFILE_QUEUED_DRAIN_S
                       for r in self._profile_requests.values())

    def refresh_found_gauge(self) -> None:
        """Re-sync dprf_targets_found/_total after out-of-band
        mutations (potfile preload / session restore in
        cli.cmd_serve, job submit/restore)."""
        with self.lock:
            self._g_found.set(self.scheduler.found_total())
            self._g_targets.set(self.scheduler.targets_total())

    def seed_found(self, hits: list) -> None:
        """Seed the DEFAULT job from journaled hit records (resume):
        goes through the job's hit buffer so `op_hits_pull` clients
        see restored hits too, tolerant of malformed entries."""
        with self.lock:
            job = self.scheduler.get(self.default_job_id)
            for h in hits:
                try:
                    job.record_hit(int(h["target"]), int(h["index"]),
                                   bytes.fromhex(h["plaintext"]))
                except (KeyError, ValueError, TypeError):
                    continue

    #: rejected completions before a worker is quarantined.  Lower than
    #: the unit threshold so a single bad worker is benched while its
    #: unit can still requeue to an honest one.
    MAX_WORKER_REJECTS = 2
    #: DISTINCT workers whose reports on one unit were all rejected
    #: before the unit is force-completed (a logged potential coverage
    #: hole beats a job that can never terminate when every worker's
    #: device path is divergent)
    MAX_UNIT_REJECT_WORKERS = 3

    # -- RPC ops ---------------------------------------------------------

    def op_hello(self, msg: dict,
                 auth_owner: Optional[str] = None) -> dict:
        # the default job + its scheduler id: a multi-job worker seeds
        # its per-job worker cache with this one and fetches further
        # specs through op_job_status as their units arrive.  The
        # echoed owner is the identity the handler loop AUTHENTICATED
        # this connection as -- the client's claim (msg["owner"]
        # rides the auth handshake) is confirmed only when the hmac
        # over the owner-derived token proved it; on an open or
        # admin connection there is no tenant scoping, so the echo
        # is None no matter what the client claimed.
        return {"ok": True, "job": self.job,
                "job_id": self.default_job_id,
                "owner": auth_owner if msg.get("owner") else None}

    def op_lease(self, msg: dict) -> dict:
        """Hand out the next unit(s), fair-share-selected ACROSS jobs
        (jobs/scheduler.py).  The lease-ahead form (``ahead=N``)
        returns up to N units in ``"units"`` so a pipelined worker
        fills its submit-ahead queue in ONE round trip; ``"unit"``
        stays the first entry for pre-ahead clients.  Every entry
        names its job; per-worker holdings are capped at
        MAX_LEASE_AHEAD across all jobs.  ``pull`` carries the
        flight-recorder pull epoch (op_trace_pull)."""
        with self.lock:
            pull = self._pull_epoch
            if self._stopped():
                return {"unit": None, "stop": True, "pull": pull}
            raw_wid = msg.get("worker_id")
            wid = str(raw_wid) if raw_wid is not None else "?"
            if raw_wid is not None:
                # any lease poll is a sign of life for the health
                # plane (the idle-aware heartbeat contract: flowing
                # traffic makes explicit beats redundant); the
                # registry caps its own id cardinality
                self.health.observe(wid)
            if wid in self.quarantined:
                return {"unit": None, "stop": False,
                        "quarantined": True, "pull": pull}
            # pending kernel-profile request rides the lease response
            # (ISSUE 15); one dict probe for the common no-request
            # case, so the lease path pays nothing when idle
            prof_req = self._profile_request_for(wid)
            try:
                ahead = int(msg.get("ahead", 1))
            except (TypeError, ValueError):
                ahead = 1
            ahead = max(1, min(ahead, MAX_LEASE_AHEAD))
            # reap BEFORE clamping against this worker's holdings: a
            # restarted worker (same --id) still "holding" its crashed
            # predecessor's expired leases would otherwise clamp to 0
            # forever -- lease() below is the only reap site during an
            # active job, and a clamp of 0 never reaches it
            self.scheduler.reap_expired()
            # age-based job GC (DPRF_JOB_TTL_S): terminal jobs past
            # their TTL leave the table here, journaled so a restart
            # does not resurrect them; the default job is never reaped
            # (state.found aliases its dict)
            for gone in self.scheduler.maybe_gc(
                    keep=(self.default_job_id,)):
                if self.on_job_event:
                    self.on_job_event("gc", gone)
            ahead = min(ahead, max(
                0, MAX_LEASE_AHEAD - self.scheduler.outstanding_for(wid)))
            pairs = self.scheduler.lease_many(wid, ahead)
            if not pairs:
                # nothing leasable right now; workers retry unless NO
                # non-terminal job could ever lease again (a paused
                # job keeps the fleet polling for its resume)
                resp = {"unit": None,
                        "stop": self.scheduler.idle_stop(),
                        "pull": pull}
                if prof_req is not None:
                    resp["profile"] = prof_req
                return resp
            # liveness gauge only for ids that actually HOLD a lease:
            # worker_id is client-controlled, and a label child lives
            # forever, so polls with throwaway ids must not grow the
            # registry (holding a lease bounds the id set by the unit
            # ledger)
            self._touch_worker(wid)
            entries = []
            for job, unit in pairs:
                e = {"id": unit.unit_id, "start": unit.start,
                     "length": unit.length, "job": job.job_id}
                # trace context OUT, per unit: the worker parents its
                # rpc/warmup/sweep spans onto this lease, so the spans
                # it ships back with complete/fail stitch onto the
                # coordinator timeline
                ctx = job.dispatcher.trace_context(unit.unit_id)
                if ctx is not None:
                    e["trace"] = {"trace": ctx[0], "span": ctx[1]}
                entries.append(e)
            resp = {"unit": entries[0], "units": entries, "pull": pull}
            if prof_req is not None:
                resp["profile"] = prof_req
            if "trace" in entries[0]:
                # legacy single-unit clients read a top-level context
                resp["trace"] = entries[0]["trace"]
            return resp

    def op_complete(self, msg: dict) -> dict:
        unit_id = int(msg["unit_id"])
        hits = msg.get("hits", [])
        # per-unit wall time reported by the worker: feeds the adaptive
        # unit sizer's per-worker throughput EWMA (tune.unit_sizer).
        # Client-controlled, so sanitize: a junk value must read as "no
        # report", never as a poisoned estimate.
        elapsed = msg.get("elapsed")
        if not (isinstance(elapsed, (int, float)) and elapsed > 0):
            elapsed = None
        # Parse + verify OUTSIDE the lock: the oracle re-hash takes
        # seconds for bcrypt/PBKDF2, and holding the lock there would
        # stall every other worker's lease/complete (and hand any buggy
        # worker a coordinator-wide DoS).
        raw_job = msg.get("job")
        with self.lock:
            job = self.scheduler.get(
                str(raw_job) if raw_job is not None else None)
            if job is None:
                # unknown job id: nothing to route to -- treat like a
                # stale report (the id was valid when leased only if
                # the coordinator restarted without it)
                return {"ok": True, "stop": self._stopped(),
                        "dropped": True}
            cancelled = job.state == JOB_CANCELLED
            already = set(job.found)
            # the job's verifier/targets are immutable after admission:
            # safe to use outside the lock below
            verifier = job.verifier
            n_targets = job.n_targets
            # trace context of the attempt, read BEFORE complete/fail
            # pops the lease; remote spans + the hit_verify span below
            # parent onto it
            ctx = job.dispatcher.trace_context(unit_id)
        self.tracer.ingest(msg.get("spans"),
                           proc=str(msg.get("worker_id", "?")),
                           sent_at=msg.get("clock"))
        if cancelled:
            # cancel-mid-flight: the unit was leased before the
            # cancel; neither its coverage nor its hits may land.
            # _stopped mutates scheduler state, so back under the lock
            with self.lock:
                stopped = self._stopped()
            return {"ok": True, "stop": stopped, "dropped": True}
        t_verify = time.monotonic()
        verified = []
        rejected = 0
        for h in hits:
            ti = int(h["target"])
            if ti in already or not 0 <= ti < n_targets:
                continue
            plain = bytes.fromhex(h["plaintext"])
            if verifier is not None and not verifier(ti, plain):
                rejected += 1
                continue
            verified.append((ti, int(h["cand"]), plain))
        if hits:
            verify_s = time.monotonic() - t_verify
            self._h_phase.observe(verify_s, phase="verify",
                                  engine=job.spec.get("engine", "?"),
                                  job=job.job_id)
            self.tracer.record(
                "hit_verify", dur=verify_s,
                trace=ctx[0] if ctx else None,
                parent=ctx[1] if ctx else None, proc="coordinator",
                unit=unit_id, job=job.job_id, hits=len(hits),
                rejected=rejected)
        with self.lock:
            if job.state == JOB_CANCELLED:  # cancelled during verify
                return {"ok": True, "stop": self._stopped(),
                        "dropped": True}
            for ti, cand, plain in verified:
                if not self.scheduler.record_hit(job, ti, cand, plain):
                    continue
                self._m_hits.inc()
                if self.on_hit and job.job_id == self.default_job_id:
                    self.on_hit(ti, cand, plain)
                if self.on_job_hit:
                    self.on_job_hit(job, ti, cand, plain)
            self._g_found.set(self.scheduler.found_total())
            # attribute the unit's candidates BEFORE complete() drops
            # it from the lease ledger: remote workers hash in their
            # own processes, so the coordinator's scrapeable registry
            # must carry the fleet's sweep count itself
            raw_wid = msg.get("worker_id")
            wid = str(raw_wid) if raw_wid is not None else "?"
            # stale-guard context: with lease-ahead a crashed worker's
            # LATE complete can arrive after its unit was reissued to
            # another worker -- the live holder owns the completion
            # (verified hits above were still recorded; hits dedupe)
            guard = wid if raw_wid is not None else None
            unit = job.dispatcher.outstanding_unit(unit_id)
            if rejected:
                # The reporting worker's device path is suspect: requeue
                # the range instead of marking it done, or a wrong
                # plaintext would punch a permanent silent coverage hole
                # where the true crack may live.
                from dprf_tpu.utils.logging import DEFAULT as log
                self.rejected += rejected
                job.rejected += rejected
                self._m_rejects.inc(rejected)
                self.worker_rejects[wid] = \
                    self.worker_rejects.get(wid, 0) + 1
                if (self.worker_rejects[wid] >= self.MAX_WORKER_REJECTS
                        and wid not in self.quarantined):
                    self.quarantined.add(wid)
                    self._g_quar.set(len(self.quarantined))
                    log.warn("quarantined worker after repeated "
                             "unverifiable hits", worker=wid,
                             rejects=self.worker_rejects[wid])
                rejecters = self.unit_reject_workers.setdefault(
                    (job.job_id, unit_id), set())
                rejecters.add(wid)
                if len(rejecters) >= self.MAX_UNIT_REJECT_WORKERS:
                    # several DIFFERENT workers all produced unverifiable
                    # hits for this unit; requeueing again would livelock
                    # the job -- complete it, record the possible hole
                    log.warn("completing unit after rejected reports "
                             "from several workers; range may hold an "
                             "unrecovered crack", unit=unit_id,
                             job=job.job_id, workers=len(rejecters))
                    if unit is not None:
                        # coverage ledger marker (ISSUE 19): the range
                        # counts as covered below, but the audit trail
                        # must show it was force-completed over
                        # unverifiable reports -- the one place a
                        # "covered" range may still hide a crack
                        job.dispatcher.coverage.event(
                            "force_complete", unit.start, unit.end,
                            unit=unit_id, workers=len(rejecters))
                    self.scheduler.complete(job, unit_id,
                                            worker_id=guard)
                else:
                    self.scheduler.fail(job, unit_id, worker_id=guard)
            else:
                completed = self.scheduler.complete(
                    job, unit_id, elapsed=elapsed, worker_id=guard)
                if completed and self.on_job_progress:
                    self.on_job_progress(
                        job.job_id,
                        job.dispatcher.completed_intervals(),
                        job.dispatcher.coverage_digest())
                if completed and unit is not None:
                    # liveness only for completions of real leases (see
                    # op_lease on label cardinality); stale or rejected
                    # units are NOT counted -- the range is (re)swept by
                    # the live holder, whose complete counts it once
                    self._touch_worker(wid)
                    # feed the straggler detector: this worker's
                    # per-unit throughput EWMA (telemetry/health.py)
                    self.health.observe(
                        wid, rate_hs=(unit.length / elapsed
                                      if elapsed else None))
                    self._m_cands.inc(unit.length,
                                      engine=job.spec.get("engine", "?"),
                                      device="remote")
                    if elapsed:
                        # live roofline distance from the fleet's
                        # per-unit throughput (telemetry/perf.py)
                        perf_mod.publish_roofline(
                            job.spec.get("engine", "?"),
                            unit.length / elapsed,
                            registry=self.registry)
            if self.on_progress:
                done, total = self.scheduler.progress()
                self.on_progress(done, total,
                                 self.scheduler.found_total())
            return {"ok": rejected == 0, "stop": self._stopped()}

    def op_fail(self, msg: dict) -> dict:
        # the failing worker's spans (rpc, the aborted sweep) still
        # join the timeline -- exactly the attempts an operator wants
        # to see when a unit bounced between workers
        self.tracer.ingest(msg.get("spans"),
                           proc=str(msg.get("worker_id", "?")),
                           sent_at=msg.get("clock"))
        raw_wid = msg.get("worker_id")
        raw_job = msg.get("job")
        with self.lock:
            job = self.scheduler.get(
                str(raw_job) if raw_job is not None else None)
            if job is not None:
                self.scheduler.fail(
                    job, int(msg["unit_id"]),
                    worker_id=str(raw_wid) if raw_wid is not None
                    else None)
        return {"ok": True}

    # -- fleet health plane (ISSUE 10) -------------------------------------

    def op_heartbeat(self, msg: dict) -> dict:
        """Worker liveness + capability beacon.  Sent on the
        idle-aware ``DPRF_HEARTBEAT_S`` cadence (worker_loop): only
        when the main connection has been quiet for a beat --
        lease/complete traffic already counts as contact.  The
        payload (device kind, pipeline depth, queue depth, recent
        H/s, last error) is client-controlled and sanitized by the
        health registry; this op also touches the last-seen gauge,
        fixing its old lease-holders-only blind spot."""
        raw = msg.get("worker_id")
        if raw is None:
            return {"ok": False}
        wid = str(raw)
        payload = msg.get("payload")
        self.health.observe(wid, payload=payload)
        self._touch_worker(wid)
        # compiled-program records the worker analyzed since its last
        # beat (ISSUE 13): bounded, sanitized, fingerprint-deduped --
        # how the coordinator's op_programs table covers programs that
        # only ever compiled on worker hosts
        self.programs.ingest(msg.get("programs"), proc=wid)
        # THIS worker's free-HBM fraction feeds the adaptive unit
        # sizers (per-worker: the coordinator's own allocator says
        # nothing about a remote chip); junk payloads read as no
        # signal, never as a poisoned estimate
        frac = None
        if isinstance(payload, dict):
            limit, use = payload.get("hbm_limit"), \
                payload.get("hbm_in_use")
            if (isinstance(limit, (int, float)) and limit > 0
                    and isinstance(use, (int, float))
                    and not isinstance(limit, bool)
                    and not isinstance(use, bool)):
                frac = max(0.0, 1.0 - use / limit)
        if frac is not None:
            with self.lock:
                for j in self.scheduler.jobs():
                    if j.terminal():
                        continue
                    observe = getattr(
                        getattr(j.dispatcher, "sizer", None),
                        "observe_headroom", None)
                    if observe is not None:
                        observe(wid, frac)
        # a pending capture request also rides the heartbeat response
        # (ISSUE 15): an idle worker beats, never leases -- it must
        # still be profilable
        with self.lock:
            prof_req = self._profile_request_for(wid)
        resp = {"ok": True}
        if prof_req is not None:
            resp["profile"] = prof_req
        return resp

    # -- kernel-profiling plane (ISSUE 15) ---------------------------------

    def op_profile(self, msg: dict) -> dict:
        """``dprf profile --connect``: request one bounded capture
        window on a worker (``action: "request"``; the request rides
        that worker's next lease/heartbeat response, the raw trace
        stays on the worker host) and read back the sanitized
        summaries workers pushed (the default action)."""
        if msg.get("action") == "request":
            worker = msg.get("worker")
            seconds = msg.get("seconds")
            if not (isinstance(seconds, (int, float))
                    and not isinstance(seconds, bool) and seconds > 0):
                seconds = profiler_mod.default_window_s()
            if worker is None:
                # no target named: the slowest live worker is the one
                # an operator profiling a misbehaving fleet wants
                worker = self.health.slowest_worker()
                if worker is None:
                    states = self.health.states()
                    live = [w for w, s in states.items()
                            if s in ("healthy", "degraded")]
                    worker = live[0] if live else None
            if worker is None:
                return {"error": "no live worker to profile (name "
                        "one with worker=)"}
            with self.lock:
                self._prune_profile_requests(time.monotonic())
                existing = self._profile_requests.get(str(worker))
                if existing is not None:
                    # a request for this worker is already queued:
                    # share its id instead of orphaning it (the
                    # earlier requester's poll would never resolve)
                    return {"ok": True, "request_id": existing["id"],
                            "worker": str(worker), "pending": True}
                if (len(self._profile_requests)
                        >= self.MAX_WORKER_LABELS):
                    # worker names are client-controlled: bound the
                    # pending table like the summary/label tables
                    return {"error": "too many pending capture "
                            "requests; wait for deliveries or the "
                            "TTL"}
                self._profile_seq += 1
                rid = self._profile_seq
                self._profile_requests[str(worker)] = {
                    "id": rid, "seconds": float(seconds),
                    "trigger": "manual",
                    "queued_at": time.monotonic()}
            return {"ok": True, "request_id": rid,
                    "worker": str(worker)}
        want = msg.get("worker")
        with self.lock:
            # a poller waiting on ONE request names its worker: ship
            # that bucket alone, not the whole fleet's table (1024
            # workers x 4 summaries x 20 ops, every 0.5 s poll)
            summaries = {w: list(s) for w, s in
                         self._profile_summaries.items()
                         if want is None or w == str(want)}
            for w in summaries:           # read happened: drop grace
                self._profile_unread.pop(w, None)
            # queued_at is coordinator-local monotonic bookkeeping,
            # meaningless on any other host: never on the wire
            pending = {w: {k: v for k, v in r.items()
                           if k != "queued_at"}
                       for w, r in self._profile_requests.items()}
        return {"ok": True, "summaries": summaries,
                "pending": pending, "now": time.time()}

    def op_profile_push(self, msg: dict) -> dict:
        """A worker shipping its finished capture window's summary:
        sanitized + bounded exactly like spans and heartbeat
        payloads (client-controlled), stored newest-first per worker,
        and journaled as a ``{"type": "profile"}`` record via the
        cmd_serve hook."""
        raw = msg.get("worker_id")
        if raw is None:
            return {"ok": False}
        wid = str(raw)
        summary = profiler_mod.sanitize_summary(msg.get("summary"))
        if summary is None:
            return {"ok": False}
        self.health.observe(wid)
        with self.lock:
            rid = summary.get("request_id")
            if rid is not None:
                self._profile_inflight.pop(rid, None)
            self._profile_unread[wid] = time.monotonic()
            bucket = self._profile_summaries.setdefault(wid, [])
            bucket.insert(0, summary)
            del bucket[PROFILE_SUMMARIES_PER_WORKER:]
            if len(self._profile_summaries) > self.MAX_WORKER_LABELS:
                # ids are client-controlled; drop the oldest worker's
                # bucket rather than growing without bound
                oldest = min(
                    self._profile_summaries,
                    key=lambda w: self._profile_summaries[w][0].get(
                        "ts") or 0)
                if oldest != wid:
                    self._profile_summaries.pop(oldest, None)
            if self.on_profile:
                self.on_profile(wid, summary)
        from dprf_tpu.utils.logging import DEFAULT as log
        log.info("kernel profile received", worker=wid,
                 trigger=summary.get("trigger"),
                 device_s=summary.get("device_s"),
                 error=summary.get("error"))
        return {"ok": True}

    def op_programs(self, msg: dict) -> dict:
        """Compiled-program table for ``dprf programs --connect``:
        every analyzed executable this coordinator knows -- its own
        compile sites plus the records workers shipped in heartbeats
        -- with XLA-derived flops/bytes/peak-memory per program."""
        return {"ok": True, "programs": self.programs.snapshot(),
                "now": time.time()}

    def op_health(self, msg: dict) -> dict:
        """Fleet health snapshot for ``dprf health --connect``: every
        tracked worker's state-machine record, the per-job SLO rows
        (ETA / time-to-first-hit / stall flag), and the active
        alerts.  The health/alert reads run under their own locks,
        never nested inside ours."""
        workers = self.health.snapshot()
        active = self.alerts.active()
        with self.lock:
            slos = self.scheduler.slo_summaries()
        return {"ok": True, "workers": workers, "jobs": slos,
                "alerts": active, "now": time.time()}

    def op_alerts(self, msg: dict) -> dict:
        """Alert surface for ``dprf alerts --connect``: the active
        (pending/firing) set plus the recent transition history the
        engine keeps in memory (the full log is the session's
        ``.alerts.jsonl``)."""
        try:
            n = int(msg.get("n", 200))
        except (TypeError, ValueError):
            n = 200
        return {"ok": True, "alerts": self.alerts.active(),
                "history": self.alerts.history(n),
                "now": time.time()}

    def op_trace_tail(self, msg: dict) -> dict:
        """Flight-recorder read for ``dprf top``: the most recent
        spans plus the live lease table and job status -- everything a
        terminal view needs to show per-worker state, current unit,
        span in progress, and lease countdown."""
        try:
            n = int(msg.get("n", 200))
        except (TypeError, ValueError):
            n = 200
        n = max(1, min(n, 2000))
        trace = msg.get("trace")
        trace = trace if isinstance(trace, str) else None
        since = msg.get("since")
        resync = False
        if isinstance(since, str) and since:
            # incremental read (`dprf top --follow`): only spans newer
            # than the caller's cursor; resync=True means the cursor
            # fell off the ring and the payload is a full tail the
            # caller must REPLACE its buffer with
            spans, resync = self.tracer.tail_after(since, n, trace=trace)
        else:
            spans = self.tracer.tail(n, trace=trace)
        cursor = spans[-1].get("span") if spans else (
            since if isinstance(since, str) else None)
        # live utilization & roofline distance (ISSUE 9), computed
        # outside the state lock (the recorder has its own)
        busy = self.tracer.busy_fractions()
        roofline = perf_mod.roofline_snapshot(self.registry)
        # fleet health plane (ISSUE 10): per-worker state for the
        # HEALTH column + the firing alerts for the header line --
        # both read under their own locks
        health_states = self.health.states()
        firing = self.alerts.firing_names()
        # device memory view (ISSUE 13): per-worker HBM use for the
        # MEM column and the fleet total for the header -- from the
        # heartbeat payloads, so a CPU-only fleet simply shows none
        mem = self.health.mem_by_worker()
        hbm = self.health.hbm_totals()
        # last-capture-per-worker fallback from heartbeat payloads
        # (env-local captures that never pushed a summary)
        prof_hb = self.health.profile_by_worker()
        with self.lock:
            done, total = self.scheduler.progress()
            leases = []
            for j in self.scheduler.jobs():
                if not j.terminal():
                    leases.extend(j.dispatcher.outstanding_leases())
            status = {"done": done, "total": total,
                      "found": self.scheduler.found_total(),
                      "targets": self.scheduler.targets_total(),
                      "parked": self.scheduler.parked_total(),
                      "stop": self._stopped(),
                      "elapsed": time.perf_counter() - self.t0,
                      # the clock span timestamps live in: span ages
                      # must be computed against THIS, not the
                      # viewer's possibly-skewed wall clock
                      "now": time.time(),
                      # per-job rows for the dprf top admin view
                      "jobs": self.scheduler.summaries(),
                      # sliding-window device-busy per worker + the
                      # live per-engine roofline fraction (dprf top
                      # folds both into its header line)
                      "busy": busy,
                      "roofline": roofline,
                      # worker health states + firing alerts (the
                      # dprf top HEALTH column and header line)
                      "health": health_states,
                      "alerts": firing,
                      # per-worker HBM use + the fleet total (the
                      # dprf top MEM column and HBM header field)
                      "mem": mem,
                      "hbm": hbm,
                      # last kernel capture per worker (ISSUE 15):
                      # the dprf top PROF column reads age + trigger;
                      # pushed summaries win over the heartbeat
                      # payload's self-reported captures -- but an
                      # in-band ERROR push carries no ts, and must
                      # not blank a worker's known last-capture age
                      "profiles": {**prof_hb, **{
                          w: {"ts": b[0].get("ts"),
                              "trigger": b[0].get("trigger")}
                          for w, b in self._profile_summaries.items()
                          if b and b[0].get("ts") is not None}},
                      "quarantined": sorted(self.quarantined)}
        return {"ok": True, "spans": spans, "leases": leases,
                "status": status, "cursor": cursor, "resync": resync}

    def op_retry_parked(self, msg: dict) -> dict:
        """Admin op (`dprf retry-parked --connect`): requeue poisoned/
        parked units with a fresh retry budget on the LIVE jobs --
        without restarting them (a DONE-because-parked job returns to
        RUNNING).  Token-authenticated like every other RPC op when
        the coordinator has a token (it mutates the unit ledger,
        unlike the read-only /metrics scrape)."""
        with self.lock:
            n = self.scheduler.retry_parked()
        return {"ok": True, "retried": n}

    def op_metrics(self, msg: dict) -> dict:
        """Registry read over the RPC protocol (authenticated when the
        coordinator has a token); the HTTP GET path below serves the
        same registry for Prometheus scrapers."""
        if msg.get("format") == "json":
            return {"ok": True, "metrics": self.registry.snapshot()}
        return {"ok": True, "text": self.registry.render()}

    def op_status(self, msg: dict) -> dict:
        with self.lock:
            done, total = self.scheduler.progress()
            return {"done": done, "total": total,
                    "found": self.scheduler.found_total(),
                    "stop": self._stopped(),
                    # poisoned ranges (retry-cap parked), summed over
                    # EVERY job like done/total/found above: a tenant
                    # that "finished" with parked units did NOT sweep
                    # them, and the default-job-only count would hide
                    # that (per-job detail is in "jobs")
                    "parked": self.scheduler.parked_total(),
                    "parked_indices":
                        self.scheduler.parked_indices_total(),
                    "jobs": self.scheduler.summaries(),
                    "elapsed": time.perf_counter() - self.t0}

    # -- multi-tenant job admin (jobs/scheduler.py) -----------------------

    @staticmethod
    def _owner_denied(job, auth_owner: Optional[str]) -> Optional[dict]:
        """Owner enforcement (ISSUE 10 satellite): a connection
        authenticated with an owner-scoped token (``dprf token``) may
        only act on that owner's jobs; the admin token (and the open
        protocol) is exempt (auth_owner None)."""
        if auth_owner is not None and job.owner != auth_owner:
            return {"error": f"job {job.job_id} belongs to owner "
                    f"{job.owner!r}; this token is scoped to "
                    f"{auth_owner!r}"}
        return None

    def op_job_submit(self, msg: dict,
                      auth_owner: Optional[str] = None) -> dict:
        """Admit a new job to the scheduler.  The spec is rebuilt
        server-side (jobs/build.py): targets parsed, generator built,
        fingerprint recomputed -- a submission is DATA, never trusted
        structure.  The expensive build runs OUTSIDE the lock against
        a pre-reserved job id.  An owner-token connection's
        submission is FORCED to its authenticated owner -- the msg
        field cannot impersonate another tenant."""
        spec = msg.get("spec")
        builder = self.job_builder
        if builder is None:
            from dprf_tpu.jobs.build import build_job_runtime
            builder = build_job_runtime
        with self.lock:
            # a table wedged at the cap with TTL-expired terminal
            # jobs un-wedges HERE (force bypasses the GC's rate
            # limiter), before the capacity gate rejects the tenant
            for gone in self.scheduler.maybe_gc(
                    keep=(self.default_job_id,),
                    force=self.scheduler.full()):
                if self.on_job_event:
                    self.on_job_event("gc", gone)
            # capacity gate BEFORE the expensive build: a full table
            # must not cost target parsing, generator construction,
            # or per-job metric registration per rejected attempt
            if self.scheduler.full():
                return {"error": "job rejected: job table full "
                        f"({self.scheduler.MAX_JOBS} jobs)"}
            # per-owner aggregate quota (ISSUE 13 satellite): an owner
            # whose cap is already consumed is rejected at admission,
            # before the build -- the lease path enforces the same cap
            # for jobs admitted before the quota filled
            claimed = (auth_owner if auth_owner is not None
                       else str(msg.get("owner") or "?"))
            quota_err = self.scheduler.owner_quota_error(claimed)
            if quota_err is not None:
                return {"error": f"job rejected: {quota_err}"}
            jid = self.scheduler.reserve_id()
            lease_timeout = self.dispatcher.lease_timeout
        try:
            wire, dispatcher, targets, verifier = builder(
                spec, jid, registry=self.registry,
                recorder=self.tracer, lease_timeout=lease_timeout)
        except (ValueError, OSError, KeyError, TypeError) as e:
            return {"error": f"job rejected: {e}"}
        owner = (auth_owner if auth_owner is not None
                 else str(msg.get("owner") or "?"))
        try:
            priority = max(1, int(msg.get("priority") or 1))
        except (TypeError, ValueError):
            priority = 1
        quota = msg.get("quota")
        quota = int(quota) if isinstance(quota, (int, float)) else None
        rate = msg.get("rate")
        rate = float(rate) if isinstance(rate, (int, float)) else None
        with self.lock:
            try:
                job = self.scheduler.add(
                    wire, dispatcher, len(targets), verifier=verifier,
                    owner=owner, priority=priority, quota=quota,
                    rate=rate, job_id=jid)
            except ValueError as e:
                return {"error": str(e)}
            self._g_targets.set(self.scheduler.targets_total())
            summary = job.summary()
            # under the lock: the event hook journals (session file
            # writes must serialize with the on_hit/on_job_progress
            # writers, which also run under it)
            if self.on_job_event:
                self.on_job_event("submit", job)
        from dprf_tpu.utils.logging import DEFAULT as log
        log.info("job submitted", job=jid, owner=owner,
                 priority=priority, keyspace=wire["keyspace"],
                 fingerprint=wire["fingerprint"])
        return {"ok": True, "job": summary, "job_id": jid,
                "fingerprint": wire["fingerprint"],
                "keyspace": wire["keyspace"]}

    def op_job_list(self, msg: dict) -> dict:
        with self.lock:
            return {"ok": True, "jobs": self.scheduler.summaries()}

    def op_job_status(self, msg: dict) -> dict:
        """One job's summary plus its full wire spec -- the op a
        multi-job worker rebuilds an unfamiliar job from (the spec is
        the same shape op_hello ships for the default job)."""
        with self.lock:
            job = self.scheduler.get(self._job_arg(msg))
            if job is None:
                return {"error": f"unknown job {msg.get('job')!r}"}
            return {"ok": True, "job": job.summary(),
                    "spec": job.spec}

    def op_job_cancel(self, msg: dict,
                      auth_owner: Optional[str] = None) -> dict:
        with self.lock:
            jid = self._job_arg(msg) or ""
            job = self.scheduler.get(jid) if jid else None
            if job is None:
                return {"error": f"unknown job {msg.get('job')!r}"}
            denied = self._owner_denied(job, auth_owner)
            if denied is not None:
                return denied
            self.scheduler.cancel(jid)
            summary = job.summary()
            if self.on_job_event:
                self.on_job_event("cancel", job)
        return {"ok": True, "job": summary}

    def op_job_pause(self, msg: dict,
                     auth_owner: Optional[str] = None) -> dict:
        resume = bool(msg.get("resume"))
        with self.lock:
            jid = self._job_arg(msg) or ""
            job = self.scheduler.get(jid) if jid else None
            if job is None:
                return {"error": f"unknown job {msg.get('job')!r}"}
            denied = self._owner_denied(job, auth_owner)
            if denied is not None:
                return denied
            self.scheduler.pause(jid, resume=resume)
            summary = job.summary()
            if self.on_job_event:
                self.on_job_event("resume" if resume else "pause",
                                  job)
        return {"ok": True, "job": summary}

    def op_hits_pull(self, msg: dict,
                     auth_owner: Optional[str] = None) -> dict:
        """Cursor-based per-job hit delivery: the submitting client
        polls with its last cursor and receives only NEW hits -- the
        multi-tenant replacement for scraping the single global found
        set.  The cursor is the hit sequence number; hits never
        reorder, so a client can resume from any cursor.  An
        owner-token connection can only pull its OWN jobs' hits."""
        try:
            cursor = max(0, int(msg.get("cursor") or 0))
        except (TypeError, ValueError):
            cursor = 0
        with self.lock:
            job = self.scheduler.get(self._job_arg(msg))
            if job is None:
                return {"error": f"unknown job {msg.get('job')!r}"}
            denied = self._owner_denied(job, auth_owner)
            if denied is not None:
                return denied
            hits = [dict(h) for h in job.hits[cursor:]]
            return {"ok": True, "hits": hits,
                    "cursor": cursor + len(hits),
                    "state": job.state, "found": len(job.found),
                    "targets": job.n_targets}

    def _job_arg(self, msg: dict) -> Optional[str]:
        j = msg.get("job")
        return str(j) if j is not None else None
    _job_arg._holds_lock = "lock"   # callers hold self.lock

    #: ops the handler loop passes the connection's authenticated
    #: owner to (owner-scoped tenant tokens; see owner_token above)
    op_hello._wants_owner = True
    op_job_submit._wants_owner = True
    op_job_cancel._wants_owner = True
    op_job_pause._wants_owner = True
    op_hits_pull._wants_owner = True

    # -- incident-response trace collection -------------------------------

    def op_trace_pull(self, msg: dict) -> dict:
        """Flight-recorder dump for incident response (`dprf trace
        pull`): page through the coordinator's ring with a span-id
        cursor.  ``arm=True`` additionally bumps the PULL EPOCH, which
        rides every lease response -- each live worker seeing a new
        epoch ships its LOCAL ring back via op_trace_push, so the next
        pull holds the fleet-wide record, including spans that never
        rode a complete/fail message."""
        if msg.get("arm"):
            with self.lock:
                self._pull_epoch += 1
        try:
            n = int(msg.get("n", 1000))
        except (TypeError, ValueError):
            n = 1000
        n = max(1, min(n, 4096))
        since = msg.get("since")
        since = since if isinstance(since, str) else None
        # forward pager from the ring's OLDEST span: a pull is a full
        # dump, not a live tail -- the client walks until a short page
        spans, resync = self.tracer.head_after(since, n)
        cursor = spans[-1].get("span") if spans else since
        with self.lock:
            epoch = self._pull_epoch
        return {"ok": True, "spans": spans, "cursor": cursor,
                "resync": resync, "epoch": epoch}

    def op_trace_push(self, msg: dict) -> dict:
        """A worker shipping its local flight-recorder ring (the
        op_trace_pull arm handshake).  Sanitized exactly like the
        spans on complete/fail -- bounded count, declared names only,
        proc forced to the reporting worker id -- just with a ring-
        sized bound instead of the per-unit one."""
        ingested = self.tracer.ingest(
            msg.get("spans"), proc=str(msg.get("worker_id", "?")),
            sent_at=msg.get("clock"), limit=TRACE_PUSH_MAX)
        return {"ok": True, "ingested": ingested}

    def _stopped(self) -> bool:
        return self.scheduler.all_finished()
    _stopped._holds_lock = "lock"   # callers hold self.lock

    def finished(self) -> bool:
        with self.lock:
            return self._stopped()


def challenge_response(token: str, nonce_hex: str) -> str:
    """The proof a client sends for a hello challenge."""
    return hmac_mod.new(token.encode(), bytes.fromhex(nonce_hex),
                        "sha256").hexdigest()


# ---------------------------------------------------------------------------
# owner-scoped tenant tokens (ISSUE 10 satellite of a ROADMAP item)

#: owner tokens are self-describing: ``ot1.<owner>.<mac>`` where the
#: mac is derived from the coordinator's ADMIN secret -- so the
#: coordinator can verify any tenant's token without a token table,
#: and the auth layer knows WHO connected, not just that someone did
OWNER_TOKEN_PREFIX = "ot1."
_OWNER_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")


def owner_token(secret: str, owner: str) -> str:
    """Mint a tenant token from the coordinator's admin secret
    (``dprf token --owner``).  A connection authenticated with it is
    scoped to ``owner``: the owner-enforcing job ops
    (cancel/pause/resume/hits_pull) only act on that owner's jobs,
    and a submission's owner field is forced to it.  The admin secret
    itself stays exempt (owner None = admin)."""
    if not _OWNER_RE.match(owner or ""):
        raise ValueError(
            "owner must be 1-64 chars of [A-Za-z0-9_-] "
            f"(got {owner!r})")
    mac = hmac_mod.new(secret.encode(),
                       b"dprf-owner:" + owner.encode(),
                       "sha256").hexdigest()[:32]
    return f"{OWNER_TOKEN_PREFIX}{owner}.{mac}"


def token_owner(token: Optional[str]) -> Optional[str]:
    """The owner a token is scoped to; None for admin/plain tokens."""
    if not token or not token.startswith(OWNER_TOKEN_PREFIX):
        return None
    owner = token[len(OWNER_TOKEN_PREFIX):].split(".", 1)[0]
    return owner or None


class _Handler(socketserver.StreamRequestHandler):
    #: failed auth attempts before the connection is dropped
    MAX_AUTH_FAILURES = 3

    def _serve_http(self, request_line: bytes) -> None:
        """One-shot HTTP responder on the RPC port: ``GET /metrics``
        returns the coordinator registry in Prometheus text format.
        Read-only observability is served even when the RPC protocol
        is token-authenticated -- it exposes rates and counts, never
        the job description or hits -- so a scraper needs no secret."""
        state: CoordinatorState = self.server.state   # type: ignore
        try:
            while True:            # drain request headers politely
                line = self.rfile.readline(MAX_LINE)
                if not line or line in (b"\r\n", b"\n"):
                    break
            parts = request_line.split()
            head_only = parts and parts[0] == b"HEAD"
            path = parts[1].decode("latin-1") if len(parts) > 1 else ""
            if path.split("?")[0] == "/metrics":
                body = state.registry.render().encode()
                head = (b"HTTP/1.0 200 OK\r\n"
                        b"Content-Type: text/plain; version=0.0.4; "
                        b"charset=utf-8\r\n"
                        b"Content-Length: %d\r\n"
                        b"Connection: close\r\n\r\n" % len(body))
            else:
                body = b"try /metrics\n"
                head = (b"HTTP/1.0 404 Not Found\r\n"
                        b"Content-Type: text/plain\r\n"
                        b"Content-Length: %d\r\n"
                        b"Connection: close\r\n\r\n" % len(body))
            # HEAD: headers only (Content-Length still describes what
            # GET would return)
            self.connection.sendall(head if head_only else head + body)
        except OSError:
            pass

    def handle(self):
        state: CoordinatorState = self.server.state   # type: ignore
        nonce = secrets.token_hex(16)      # challenge, rotated per failure
        auth_failures = 0
        authed = state.token is None
        #: owner this connection authenticated AS (owner-scoped
        #: tenant tokens, ISSUE 10): None = admin token or open
        #: protocol -- exempt from the per-owner job-op checks
        conn_owner: Optional[str] = None
        #: the token string this connection's hmacs are keyed with
        #: (the owner-DERIVED token for tenant connections)
        conn_token = state.token
        while True:
            try:
                line = self.rfile.readline(MAX_LINE)
            except OSError:
                return
            if not line:
                return
            if line.startswith((b"GET ", b"HEAD ")):
                # Prometheus/curl scrape on the RPC port: answer HTTP
                # and close (HTTP clients don't speak the JSON framing)
                self._serve_http(line)
                return
            if not line.endswith(b"\n"):
                return     # over the frame limit: drop, as recv_msg does
            try:
                msg = json.loads(line)
            except ValueError:
                return
            if not isinstance(msg, dict):
                return
            if not authed:
                if msg.get("op") == "hello":
                    mac = msg.get("hmac")
                    # a hello naming an owner authenticates against
                    # the owner-DERIVED token (owner_token): the
                    # coordinator needs no token table, and a valid
                    # mac proves both the secret chain AND the owner
                    # identity in one step
                    owner = msg.get("owner")
                    owner = (owner if isinstance(owner, str)
                             and _OWNER_RE.match(owner) else None)
                    expect = (owner_token(state.token, owner)
                              if owner else state.token)
                    if (isinstance(mac, str) and hmac_mod.compare_digest(
                            mac, challenge_response(expect, nonce))):
                        authed = True      # fall through to op_hello
                        conn_owner = owner
                        conn_token = expect
                    else:
                        # a fresh nonce per attempt: a failed guess
                        # teaches nothing about the next challenge
                        auth_failures += 1
                        nonce = secrets.token_hex(16)
                        try:
                            send_msg(self.connection,
                                     {"ok": False, "challenge": nonce})
                        except OSError:
                            return
                        if auth_failures >= self.MAX_AUTH_FAILURES:
                            return          # drop the connection
                        continue
                else:
                    try:
                        send_msg(self.connection,
                                 {"error": "unauthenticated"})
                    except OSError:
                        return
                    continue
            op = getattr(state, f"op_{msg.get('op', '')}", None)
            # unknown ops share ONE label child: op strings are
            # client-controlled, and each distinct label value lives in
            # the registry forever -- an open-protocol client must not
            # be able to grow coordinator memory one junk op at a time
            state._m_rpc.inc(
                op=str(msg.get("op", "?")) if op is not None
                else "unknown")
            if op is None:
                resp = {"error": f"unknown op {msg.get('op')!r}"}
            else:
                try:
                    if getattr(op, "_wants_owner", False):
                        # owner-scoped job ops receive the identity
                        # this CONNECTION authenticated as -- never a
                        # spoofable message field
                        resp = op(msg, auth_owner=conn_owner)
                    else:
                        resp = op(msg)
                except Exception as e:       # defensive: never kill server
                    resp = {"error": f"{type(e).__name__}: {e}"}
            if (msg.get("op") == "hello" and state.token
                    and isinstance(msg.get("cnonce"), str)):
                # mutual auth: prove WE know the token over the
                # client's nonce, so a worker with --token refuses a
                # spoofed coordinator (and the job it would hand out).
                # Keyed with the CONNECTION's token: a tenant client
                # verifies with its owner-derived token
                try:
                    resp["coordinator_hmac"] = challenge_response(
                        conn_token, msg["cnonce"])
                except ValueError:
                    resp = {"error": "bad cnonce (want hex)"}
            try:
                send_msg(self.connection, resp)
            except OSError:
                return


class CoordinatorServer:
    """Threaded TCP server around a CoordinatorState."""

    def __init__(self, state: CoordinatorState, host: str = "127.0.0.1",
                 port: int = 0):
        # bind manually so allow_reuse_address is set BEFORE bind():
        # otherwise a restart on the same port trips over TIME_WAIT
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=False)
        self._srv.daemon_threads = True
        self._srv.allow_reuse_address = True
        try:
            self._srv.server_bind()
            self._srv.server_activate()
        except BaseException:
            self._srv.server_close()
            raise
        self._srv.state = state            # type: ignore
        self.state = state
        self.address = self._srv.server_address

    def serve_until_done(self, poll: float = 0.5,
                         drain: float = 600.0) -> None:
        """Run until the job finishes, then keep serving until every
        outstanding lease resolves (workers mid-unit must be able to
        report their final hits and see the stop flag -- a fixed grace
        window would race against unit processing time) AND every
        in-flight kernel-profile capture lands or expires (a capture
        racing the job's last units stops + analyzes on the worker
        for seconds after the final complete; vanishing now would
        lose its push).  `drain` caps the wait so a worker that died
        holding a lease can't pin the server forever; dead captures
        expire on their own PROFILE_INFLIGHT_TTL_S."""
        t = threading.Thread(target=self._srv.serve_forever,
                             kwargs={"poll_interval": 0.1}, daemon=True)
        t.start()
        try:
            while not self.state.finished():
                time.sleep(poll)
            deadline = time.monotonic() + drain
            while time.monotonic() < deadline:
                with self.state.lock:
                    # expired leases (dead workers) won't be reaped by
                    # lease() anymore -- nobody is leasing -- so reap
                    # here or a dead worker would pin the drain loop
                    self.state.scheduler.reap_expired()
                    outstanding = \
                        self.state.scheduler.total_outstanding()
                if outstanding == 0 \
                        and not self.state.profile_pending():
                    break
                time.sleep(poll)
            time.sleep(poll)   # let final responses flush
        finally:
            self._srv.shutdown()
            self._srv.server_close()

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self._srv.serve_forever,
                             kwargs={"poll_interval": 0.1}, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


# ---------------------------------------------------------------------------
# worker side

class CoordinatorClient:
    """Blocking JSON-RPC client used by remote workers."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 token: Optional[str] = None):
        self._addr = (host, port)
        self._timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._fh = self._sock.makefile("rb")
        self._token = token
        #: owner an ``ot1.`` tenant token is scoped to (None for the
        #: admin secret): sent with hello so the coordinator keys the
        #: challenge against the owner-derived token
        self._owner = token_owner(token)

    def clone(self) -> "CoordinatorClient":
        """A second authenticated connection to the same coordinator
        -- the async completion sender's channel, so report round
        trips ride beside the lease/sweep loop instead of inside it.
        Authentication is per-connection, so a token-auth'd clone
        answers its own hello challenge here."""
        peer = type(self)(self._addr[0], self._addr[1],
                          timeout=self._timeout, token=self._token)
        if self._token:
            try:
                peer.hello()
            except BaseException:
                peer.close()
                raise
        return peer

    def hello(self) -> dict:
        """Fetch the job, answering the coordinator's auth challenge if
        it has one.  When this client holds a token, the coordinator
        must in turn prove it knows the token over OUR nonce (mutual
        auth): a spoofed coordinator cannot hand this worker a job."""
        cnonce = secrets.token_hex(16)
        resp = self.call("hello", cnonce=cnonce, owner=self._owner)
        if resp.get("challenge"):
            if not self._token:
                raise RpcError(
                    "coordinator requires authentication; pass --token")
            resp = self.call("hello", cnonce=cnonce, owner=self._owner,
                             hmac=challenge_response(
                                 self._token, resp["challenge"]))
            if resp.get("challenge"):
                raise RpcError("authentication failed (wrong token?)")
        if self._token:
            proof = resp.get("coordinator_hmac")
            if not (isinstance(proof, str) and hmac_mod.compare_digest(
                    proof, challenge_response(self._token, cnonce))):
                raise RpcError("coordinator failed mutual authentication "
                               "(spoofed coordinator, or it has no/other "
                               "token)")
        return resp

    def call(self, op: str, **kw) -> dict:
        kw["op"] = op
        send_msg(self._sock, kw)
        resp = recv_msg(self._fh)
        if resp is None:
            raise ConnectionError("coordinator closed the connection")
        if "error" in resp:
            raise RpcError(f"coordinator error: {resp['error']}")
        return resp

    def close(self) -> None:
        # the makefile() stream holds its own reference to the socket
        # (and a buffer): closing only the socket leaks the stream
        # object and keeps the fd alive until GC
        try:
            self._fh.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _CompletionSender:
    """Ships ``complete``/``fail`` reports from a background thread on
    a dedicated connection, so the report round trip overlaps the next
    sweep instead of serializing with it.  Ordering is preserved (one
    FIFO queue, one thread); the first send failure is latched and
    re-raised by ``drain()`` -- the crash-surfacing contract of the
    serial loop.  Reports queued after a failure are dropped: their
    leases expire and reissue, and the latched error aborts the loop
    anyway."""

    def __init__(self, client: CoordinatorClient):
        import queue
        self._client = client
        self._q: "queue.Queue" = queue.Queue()
        self.error: Optional[BaseException] = None
        self.stop_seen = False
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="dprf-sender")
        self._t.start()

    def send(self, op: str, **kw) -> None:
        self._q.put((op, kw))

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            op, kw = item
            try:
                if self.error is None:
                    # clock stamped at SEND time: the coordinator
                    # rebases the shipped span timestamps against it
                    resp = self._client.call(op, clock=time.time(),
                                             **kw)
                    if resp.get("stop"):
                        self.stop_seen = True
            except Exception as e:   # noqa: BLE001 -- latched, then
                self.error = e       # re-raised by drain()
            finally:
                self._q.task_done()

    def drain(self) -> None:
        """Block until every queued report was sent (or dropped past a
        failure), then re-raise the first send failure."""
        self._q.join()
        if self.error is not None:
            raise self.error

    def close(self) -> None:
        self._q.put(None)
        self._t.join(timeout=30)
        self._client.close()


def worker_loop(client: CoordinatorClient, worker, worker_id: str,
                idle_sleep: float = 0.5, log=None, registry=None,
                recorder=None, depth: Optional[int] = None,
                worker_for: Optional[Callable] = None) -> int:
    """Pipelined lease -> submit-ahead -> resolve -> async-complete
    loop, until the coordinator says stop.  Returns units completed.

    worker: any object with .process(WorkUnit) -> list[Hit] (the same
    duck type the local Coordinator drives).  Submit-based workers
    (``process._submit_based``) enqueue unit N+1's device work BEFORE
    unit N resolves, so the next super-step is on the device stream
    while the host decodes hits and the RPC round trips fly; serial
    workers still gain the lease-ahead batch and the overlapped
    completion report.

    Multi-tenant (ISSUE 8): lease entries name their JOB; the optional
    ``worker_for(job_id)`` factory maps an unfamiliar job to its
    worker (cli.cmd_worker builds one that fetches the spec over
    op_job_status, fingerprint-checks it, and caches the rebuilt
    worker).  A factory returning None means the job cannot run on
    this host (missing wordlist file, divergent content fingerprint):
    its leases are failed back in-band and the loop keeps serving
    other jobs.  Without a factory every unit runs on the default
    ``worker`` -- the single-job fleet unchanged.  Complete/fail
    reports echo the job id so the coordinator routes them to the
    right ledger.

    ``depth=None`` (the default) runs the ADAPTIVE depth: EWMAs of
    the lease round trip and the inter-completion interval derive the
    live submit-ahead depth (~1 + rtt/unit_seconds) each iteration,
    capped by the ``DPRF_PIPELINE_DEPTH`` knob / ``--pipeline-depth``
    flag (worker.AdaptiveDepth).  An explicit integer pins the depth;
    1 is the serial fallback (one connection, synchronous completes).

    Crash surfacing matches the serial loop: a processing failure
    fails the aborted unit AND every queued lease, then re-raises;
    queued completion reports are drained before any return, and the
    first async send failure is re-raised.

    Tracing: the lease response's trace context parents this worker's
    ``rpc`` / ``warmup`` / ``sweep`` spans, which ship back inside the
    complete (or fail) message -- the coordinator's flight recorder
    then holds the unit's WHOLE lifecycle across every host that
    touched it.  When an operator ARMS a trace pull (op_trace_pull),
    the lease response's ``pull`` epoch bumps and this loop ships its
    whole LOCAL ring back once via op_trace_push.
    ``DPRF_JAX_PROFILE=<dir>`` additionally wraps the loop in a
    jax.profiler trace.
    """
    from dprf_tpu.runtime.worker import (AdaptiveDepth, UnitPipeline,
                                         pipeline_depth)

    m = get_registry(registry)
    tracer = get_tracer(recorder)
    # worker-side publication: candidates are counted where the hashing
    # happens (the local Coordinator does the same for in-process
    # jobs); declared through declare_job_metrics -- the ONE
    # declaration site (tools/check_metrics.py) -- so names and labels
    # can never drift from the coordinator's
    jm = declare_job_metrics(m)

    def _labels_of(w) -> tuple:
        return (getattr(getattr(w, "engine", None), "name", "unknown"),
                "cpu" if type(w).__name__ == "CpuWorker" else "jax")

    m_cands = jm["cands"]
    h_unit = jm["unit_seconds"]
    g_depth = m.gauge(
        "dprf_worker_pipeline_depth",
        "units this worker submits ahead of the oldest unresolved one "
        "(1 = serial loop; adapted to rtt/unit-seconds under the "
        "DPRF_PIPELINE_DEPTH cap unless pinned)")
    c_idle = m.counter(
        "dprf_worker_idle_seconds",
        "seconds this worker held no submitted unit between sweeps "
        "(pipeline drained: the device idles while RPCs fly)")
    # sampled per-phase attribution (telemetry/perf.py): every Nth
    # unit runs the serial synced probe; its phase spans ship back
    # with the complete report like any other worker span
    sampler = perf_mod.PerfSampler(registry=m, recorder=tracer)
    # kernel-profiling plane (ISSUE 15): on-demand bounded capture
    # windows requested over lease/heartbeat responses.  The loop
    # keeps sweeping while the trace records; poll_profile() is ONE
    # attribute read when no window is active -- the zero-overhead
    # contract for the steady-state path.
    prof = profiler_mod.get_profiler()
    swept = [0]      # cumulative resolved candidates (window counter)

    def push_profile(summary: dict) -> None:
        # best-effort on the MAIN connection, like trace_push: a
        # dead link surfaces on the next lease anyway
        try:
            client.call("profile_push", worker_id=worker_id,
                        summary=summary)
        except Exception:   # noqa: BLE001 -- diagnostics only
            pass

    def begin_profile(req) -> None:
        if not isinstance(req, dict):
            return
        seconds = req.get("seconds")
        ok = prof.begin_window(
            seconds if isinstance(seconds, (int, float))
            and not isinstance(seconds, bool) else None,
            trigger=str(req.get("trigger") or "manual"),
            engine=_labels_of(worker)[0],
            request_id=req.get("id"),
            counter_fn=lambda: swept[0], log=log)
        if not ok:
            # single-flight collision (--profile / DPRF_JAX_PROFILE
            # already tracing): report it in-band, not silently
            push_profile({"schema": profiler_mod.SUMMARY_SCHEMA,
                          "request_id": req.get("id"),
                          "trigger": str(req.get("trigger")
                                         or "manual"),
                          "engine": _labels_of(worker)[0],
                          "error": "capture busy "
                          f"(active: {prof.busy()})"})

    def poll_profile() -> None:
        s = prof.poll()
        if s is not None:
            push_profile(s)

    adaptive = None
    if depth is None:
        adaptive = AdaptiveDepth(pipeline_depth())
        depth = adaptive.depth
    sender = None
    if depth > 1 or (adaptive is not None and adaptive.cap > 1):
        try:
            sender = _CompletionSender(client.clone())
        except (OSError, RpcError) as e:
            if log:
                log.warn("completion-sender connection failed; "
                         "running the serial loop", error=str(e))
            depth = 1
            adaptive = None
    g_depth.set(depth)
    pipe = UnitPipeline(worker, depth)
    done_units = 0
    stop_seen = False
    idle_mark: Optional[float] = None
    t_last_resolve: Optional[float] = None
    warm_pending = getattr(worker, "ensure_warm", None) is not None
    cur = None        # entry being submitted/resolved, for the fail path
    lease_q: list = []    # leased-but-not-yet-submitted batch remainder
    pull_seen = 0     # last trace-pull epoch this worker answered

    # idle-aware heartbeats (ISSUE 10): an explicit op_heartbeat goes
    # out only when the MAIN connection has been quiet for a whole
    # DPRF_HEARTBEAT_S beat -- lease round trips already count as
    # contact on the coordinator's health plane, so a busy loop never
    # pays the extra RPC.  The payload is this worker's live
    # capability/health record (device kind, pipeline depth, queue
    # depth, recent H/s, last async-send error).
    hb_s = heartbeat_interval()
    t_contact = time.monotonic()
    rate_ewma: Optional[float] = None
    chips: list = []      # lazily probed on the first beat
    prog_seq = [0]        # newest program-registry seq already shipped

    def _chip_count() -> Optional[int]:
        if not chips:
            try:
                import jax
                chips.append(jax.local_device_count())
            except Exception:   # noqa: BLE001 -- jax-less host
                chips.append(None)
        return chips[0]

    def maybe_heartbeat() -> None:
        nonlocal t_contact
        if hb_s <= 0 or time.monotonic() - t_contact < hb_s:
            return
        t_contact = time.monotonic()
        eng_name, dev = _labels_of(worker)
        err = (str(sender.error)[:200]
               if sender is not None and sender.error is not None
               else None)
        payload = {"engine": eng_name, "device": dev,
                   "chips": _chip_count(),
                   "depth": pipe.depth,
                   "queue": len(pipe),
                   "rate_hs": rate_ewma,
                   "error": err}
        # last kernel capture on THIS host (ISSUE 15): age + trigger
        # ride the beat so `dprf top` can show them per worker even
        # for env-local captures that never pushed a summary
        last_prof = prof.last_summary()
        if last_prof is not None:
            payload["profile_ts"] = last_prof.get("ts")
            payload["profile_trigger"] = last_prof.get("trigger")
        # device introspection rides the beat (ISSUE 13): HBM totals
        # in the payload (fleet memory headroom on the coordinator's
        # health plane) and the program records analyzed since the
        # last beat.  The deferred analysis runs HERE -- the beat only
        # fires when the loop has been quiet, so the cache-served
        # recompile it may trigger never delays a dispatch.
        try:
            from dprf_tpu.telemetry import devstats
            programs_mod.analyze_pending()
            hbm = devstats.summary()
            if hbm is not None:
                payload["hbm_in_use"] = hbm["in_use"]
                payload["hbm_limit"] = hbm["limit"]
                payload["hbm_peak"] = hbm["peak"]
        except Exception:   # noqa: BLE001 -- introspection is
            pass            # best-effort, never loop state
        records, newest = programs_mod.get_programs().records_since(
            prog_seq[0])
        try:
            resp = client.call("heartbeat", worker_id=worker_id,
                               payload=payload, programs=records)
            prog_seq[0] = newest
        except Exception:   # noqa: BLE001 -- best-effort beacon; a
            return          # dead link surfaces on the next lease
        # an idle worker never leases: capture requests must be able
        # to ride the heartbeat response too
        begin_profile(resp.get("profile"))

    def _worker_of(job_id):
        if worker_for is None or job_id is None:
            return worker
        return worker_for(job_id)

    def send_report(op: str, **kw) -> Optional[dict]:
        if sender is not None:
            sender.send(op, **kw)
            return None
        return client.call(op, clock=time.time(), **kw)

    def send_fail(unit_id: int, ship: list, job=None) -> None:
        try:
            send_report("fail", unit_id=unit_id, worker_id=worker_id,
                        spans=ship, job=job)
        except Exception:   # noqa: BLE001 -- best-effort, as serial
            pass            # (the lease expires and reissues anyway)

    def push_ring() -> None:
        # an operator armed a fleet-wide trace pull: ship this
        # worker's local flight recorder (spans that never rode a
        # complete/fail) on the MAIN connection, best-effort
        try:
            client.call("trace_push", clock=time.time(),
                        worker_id=worker_id,
                        spans=tracer.tail(TRACE_PUSH_MAX))
        except Exception:   # noqa: BLE001 -- diagnostics only
            pass

    try:
        with jax_profile_ctx(log=log):
            while True:
                if sender is not None and sender.error is not None:
                    # the coordinator stopped answering completion
                    # reports: surface it like a serial complete would
                    raise sender.error
                if adaptive is not None:
                    # adaptive lease-ahead: re-derive the live depth
                    # from the rtt/unit EWMAs under the env-knob cap
                    new_depth = adaptive.update()
                    if new_depth != pipe.depth:
                        pipe.depth = new_depth
                        g_depth.set(new_depth)
                want = pipe.depth - len(pipe)
                entries = []
                if want > 0 and not stop_seen:
                    t_lease = time.monotonic()
                    try:
                        resp = client.call("lease", worker_id=worker_id,
                                           ahead=want)
                    except ConnectionError:
                        # The coordinator serves through its drain
                        # window and answers every lease poll with an
                        # explicit stop flag once the job is over, so a
                        # worker always learns completion in-band and
                        # returns below.  A bare connection drop here
                        # therefore means the coordinator crashed
                        # mid-job: surface it so scripted workers don't
                        # report success on unfinished work.
                        raise ConnectionError(
                            "coordinator connection dropped before any "
                            "stop signal (coordinator crash mid-job?)")
                    if resp.get("quarantined"):
                        raise RpcError(
                            "coordinator quarantined this worker: its "
                            "reported hits repeatedly failed oracle "
                            "verification (divergent device path?)")
                    lease_rtt = time.monotonic() - t_lease
                    t_contact = time.monotonic()  # lease = contact
                    if adaptive is not None:
                        adaptive.observe_rtt(lease_rtt)
                    pull = resp.get("pull")
                    if isinstance(pull, int) and pull > pull_seen:
                        pull_seen = pull
                        push_ring()
                    begin_profile(resp.get("profile"))
                    entries = resp.get("units")
                    if entries is None:
                        # pre-lease-ahead coordinator: single unit with
                        # a top-level trace context
                        entries = []
                        if resp.get("unit"):
                            unit_d = dict(resp["unit"])
                            if resp.get("trace"):
                                unit_d.setdefault("trace", resp["trace"])
                            entries = [unit_d]
                    if not entries:
                        if resp.get("stop"):
                            stop_seen = True
                        elif sender is not None:
                            # all our reports are in flight: land them,
                            # then trust the freshest stop answer (the
                            # final complete's response carries it)
                            # instead of sleeping into another poll
                            sender.drain()
                            if sender.stop_seen:
                                stop_seen = True
                        if len(pipe) == 0:
                            if stop_seen:
                                break
                            # nothing leasable and nothing queued:
                            # this is exactly when the coordinator
                            # would otherwise go blind on us
                            maybe_heartbeat()
                            poll_profile()
                            time.sleep(idle_sleep)
                            continue
                    first = True
                    lease_q = list(entries)
                    while lease_q:
                        unit_d = lease_q.pop(0)
                        job = unit_d.get("job")
                        unit = WorkUnit(unit_d["id"], unit_d["start"],
                                        unit_d["length"],
                                        job_id=str(job) if job
                                        is not None else "j0")
                        ctx = unit_d.get("trace") or {}
                        tid, lease_sid = ctx.get("trace"), ctx.get("span")
                        ship: list = []
                        if first:
                            # one rpc span per lease round trip,
                            # parented on the batch's first lease
                            first = False
                            ev = tracer.record(
                                "rpc", dur=lease_rtt, trace=tid,
                                parent=lease_sid, proc=worker_id,
                                op="lease", unit=unit.unit_id,
                                job=job, units=len(entries))
                            if ev:
                                ship.append(ev)
                        # resolve the unit's JOB to its worker (the
                        # factory path may rebuild a job from
                        # op_job_status).  None = this job cannot run
                        # on THIS host (missing wordlist, divergent
                        # fingerprint): release the lease in-band and
                        # keep serving every other job -- one bad
                        # submission must not take down the fleet
                        # (its units park after the retry budget).
                        # cur is set BEFORE the call so an unexpected
                        # factory crash still releases the lease.
                        cur = (unit, None, time.monotonic(),
                               (tid, lease_sid, ship, job, worker))
                        w = _worker_of(job)
                        if w is None:
                            send_fail(unit.unit_id, ship, job=job)
                            cur = None
                            continue
                        cur = (unit, None, cur[2],
                               (tid, lease_sid, ship, job, w))
                        # join an overlapped warmup (cli.cmd_worker
                        # starts one before the loop, so the compile
                        # overlapped the lease round trip); under the
                        # fail path so a compile failure releases the
                        # lease like any processing failure
                        ensure_warm = getattr(w, "ensure_warm", None)
                        if ensure_warm is not None:
                            ensure_warm()
                        if warm_pending and w is worker:
                            # the compile ran overlapped on a background
                            # thread; report its REAL cost
                            # (compile_seconds), not the near-zero join
                            # time, so a fleet stalled on cold compiles
                            # is legible in the trace
                            warm_pending = False
                            warm_s = getattr(worker, "compile_seconds",
                                             None)
                            if warm_s is not None:
                                ev = tracer.record(
                                    "warmup", dur=float(warm_s),
                                    trace=tid, parent=lease_sid,
                                    proc=worker_id,
                                    engine=_labels_of(worker)[0],
                                    cache=getattr(worker,
                                                  "compile_cache",
                                                  None),
                                    overlapped=True)
                                if ev:
                                    ship.append(ev)
                        if idle_mark is not None:
                            # the pipeline had drained: that gap was
                            # device-idle time (RPCs with no submitted
                            # work to hide them behind)
                            c_idle.inc(time.monotonic() - idle_mark)
                            idle_mark = None
                        probe = ((sampler, tid) if sampler.take()
                                 else None)
                        pipe.submit(unit,
                                    meta=(tid, lease_sid, ship, job, w),
                                    worker=w, probe=probe)
                        cur = None
                if len(pipe) == 0:
                    if stop_seen:
                        break
                    continue
                cur = pipe.pop()
                unit, pending, t_submit, \
                    (tid, lease_sid, ship, job, w) = cur
                hits = pending.resolve()
                cur = None
                swept[0] += unit.length
                now = time.monotonic()
                unit_s = now - t_submit
                # steady-state per-unit cost for the ADAPTIVE SIZER:
                # the interval between consecutive resolves.  unit_s
                # (submit->resolve) includes up to depth-1 units of
                # queue wait behind the device stream, which would read
                # as ~1/depth of the true throughput and shrink every
                # subsequent unit; the completion interval measures the
                # worker's real drain rate once the pipeline is primed.
                # After a drain (no leasable work) the interval would
                # instead carry starvation time, so it resets below and
                # the next unit falls back to its own unit_s.
                elapsed_report = (now - t_last_resolve
                                  if t_last_resolve is not None
                                  else unit_s)
                t_last_resolve = now
                if len(pipe) == 0:
                    idle_mark = now
                    t_last_resolve = None
                if adaptive is not None:
                    adaptive.observe_unit(elapsed_report)
                if elapsed_report > 0:
                    # recent-throughput EWMA for the heartbeat payload
                    inst = unit.length / elapsed_report
                    rate_ewma = (inst if rate_ewma is None
                                 else rate_ewma + 0.3 * (inst - rate_ewma))
                # a long sweep keeps the main connection quiet for its
                # whole duration: beat here if it starved the cadence
                maybe_heartbeat()
                # an elapsed capture window stops + analyzes + ships
                # here (one attribute read when no window is active)
                poll_profile()
                # the histogram gets the same per-unit cost: observing
                # unit_s here would inflate dprf_unit_seconds ~depth x
                # under pipelining with no throughput change
                h_unit.observe(elapsed_report)
                eng_name, device = _labels_of(w)
                m_cands.inc(unit.length, engine=eng_name, device=device)
                # ts backdates to t_submit, so consecutive sweep spans
                # OVERLAP when the loop pipelines (the invariant
                # tools/trace_overlap.py checks).  A probed unit's
                # sweep span carries the pre-allocated id its phase
                # children parent onto, and ships them along.
                psid = getattr(pending, "sweep_span", None)
                pspans = getattr(pending, "phase_spans", None)
                if pspans:
                    ship.extend(pspans)
                ev = tracer.record("sweep", dur=unit_s, trace=tid,
                                   parent=lease_sid, proc=worker_id,
                                   span=psid,
                                   unit=unit.unit_id, job=job,
                                   length=unit.length,
                                   hits=len(hits),
                                   probed=psid is not None)
                if ev:
                    ship.append(ev)
                payload = [{"target": h.target_index,
                            "cand": h.cand_index,
                            "plaintext": h.plaintext.hex()}
                           for h in hits]
                # elapsed rides the complete report: the coordinator's
                # adaptive unit sizer turns it into this worker's next
                # unit length; the job id routes it to the right
                # ledger; spans stitch the attempt onto the
                # coordinator's flight recorder
                resp = send_report("complete", unit_id=unit.unit_id,
                                   hits=payload, worker_id=worker_id,
                                   elapsed=elapsed_report, spans=ship,
                                   job=job)
                done_units += 1
                if log and hits:
                    log.info("hits reported", count=len(hits))
                if resp is not None and resp.get("stop"):
                    stop_seen = True
                if sender is not None and sender.stop_seen:
                    stop_seen = True
                if stop_seen and len(pipe) == 0:
                    break
        # clean exit: every queued report must land before we return
        # (the serial loop's in-band completion contract); the first
        # async send failure re-raises here
        if sender is not None:
            sender.drain()
        return done_units
    except BaseException as e:
        if cur is not None:
            # the aborted attempt still joins the timeline: ship what
            # we have with the fail report, then release the lease (and
            # every still-queued one) for another worker
            unit, _, t_unit, (tid, lease_sid, ship, job, _w) = cur
            ev = tracer.record("sweep",
                               dur=time.monotonic() - t_unit,
                               trace=tid, parent=lease_sid,
                               proc=worker_id, unit=unit.unit_id,
                               job=job, error=type(e).__name__)
            if ev:
                ship.append(ev)
            send_fail(unit.unit_id, ship, job=job)
        for q_unit, _, _, meta in pipe.drain():
            send_fail(q_unit.unit_id, meta[2], job=meta[3])
        for unit_d in lease_q:
            # leased but never submitted (the batch aborted first):
            # release these too, or they pin the ledger until expiry
            send_fail(unit_d["id"], [], job=unit_d.get("job"))
        if sender is not None:
            try:
                sender._q.join()   # land the fails; the original
            except Exception:      # error outranks any send failure
                pass
        raise
    finally:
        # a capture window still in flight on a CLEAN stop gets a
        # bounded grace to finish + push, and summaries that already
        # finished but were never drained (the background analysis
        # landed between the last poll and the stop) ship too: the
        # job's last unit landing mid-window would otherwise kill
        # the capture silently and the requester waits out its full
        # --wait.  Error exits skip the grace (the connection is
        # gone; a push can't land).  finish_now with nothing in
        # flight is one lock probe, so the idle exit pays nothing.
        if stop_seen:
            for _ in range(profiler_mod.HISTORY_MAX):
                s = prof.finish_now()
                if s is None:
                    break
                push_profile(s)
        # whatever remains must not outlive the loop (the profiler
        # slot would stay taken for the process lifetime)
        prof.abort_window()
        if sender is not None:
            sender.close()
