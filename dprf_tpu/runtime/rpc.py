"""Host-level distributed backend: coordinator RPC + remote workers.

Inside one host/slice, parallelism is XLA collectives over ICI (the
sharded steps in dprf_tpu/parallel) -- there is no NCCL/MPI analogue to
manage.  ACROSS hosts, the control plane is deliberately tiny, exactly
the Dispatcher surface: lease a WorkUnit, report hits, complete.  This
module is that control plane: newline-delimited JSON over TCP.

    coordinator (dprf serve):  owns Dispatcher + found set + potfile/
        session persistence; hands out leases under a lock.
    worker (dprf worker):      connects, receives the job description,
        rebuilds engine/generator/targets locally, then loops
        lease -> fused device sweep -> complete(hits).

Fault model: a worker that dies simply stops leasing; its outstanding
unit's lease expires and the Dispatcher reissues it (idempotent -- units
are pure functions of the index range).  A worker that reports hits for
an already-reissued unit is harmless: hits are deduped by target.

Trust model: optional shared-secret authentication (--token).  When the
coordinator has a token, every connection must answer an HMAC-SHA256
challenge on hello before any other op is served (the challenge nonce
rotates after every failed attempt and a connection is dropped after a
few failures, so a connection cannot grind guesses against one nonce);
the worker may send its own nonce in hello, and the coordinator's reply
proves knowledge of the token over it -- mutual authentication.
Without a token the protocol is open -- bind to localhost or a trusted
network only (same stance as hashtopolis-style agents).  The transport
is cleartext either way: the token authenticates peers, it does not
encrypt the job.  The job description includes the raw hashlist lines;
wordlist files must exist on each worker host (they are referenced by
path, never shipped).
"""

from __future__ import annotations

import hmac as hmac_mod
import json
import secrets
import socket
import socketserver
import threading
import time
from typing import Callable, Optional

from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.worker import Hit
from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.telemetry import declare_job_metrics, get_registry
from dprf_tpu.telemetry.trace import get_tracer, jax_profile_ctx

MAX_LINE = 64 << 20   # hashlists can be large; candidates never cross

#: leases one worker may hold at once (and the clamp on a lease
#: request's ``ahead``): bounds how much of the queue a buggy or
#: greedy client can vacuum into one host's ledger
MAX_LEASE_AHEAD = 16

#: lock-discipline declarations (`dprf check` locks analyzer).  Every
#: worker connection is its own handler thread in a
#: ThreadingTCPServer, all mutating this state: the listed
#: CoordinatorState attributes must only be touched inside ``with
#: <state>.lock`` (or a method annotated ``_holds_lock``).  The
#: _CompletionSender flags are single-writer latched (assigned only by
#: its own thread's ``_run``, read cross-thread) -- GIL-atomic by
#: design, which ``<atomic>`` makes the checker enforce rather than
#: assume.
GUARDED_BY = {
    "CoordinatorState": {
        "lock": ("found", "dispatcher", "rejected", "worker_rejects",
                 "unit_reject_workers", "quarantined"),
    },
    "_CompletionSender": {"<atomic>": ("error", "stop_seen")},
}

#: resource-ownership declarations (`dprf check` threads analyzer):
#: every socket/stream attribute acquired outside a ``with`` names
#: the method that releases it, and the analyzer verifies that
#: method really closes it on the shutdown path.
RELEASES = {
    "CoordinatorClient": {"_sock": "close", "_fh": "close"},
}

#: `dprf check` retrace analyzer: the remote pipelined sweep loop --
#: a host sync here serializes the device stream against RPC latency.
HOT_PATHS = ("worker_loop",)


class RpcError(RuntimeError):
    """Protocol-level failure talking to the coordinator (error
    response, auth failure).  Distinct from RuntimeError so the CLI can
    report it cleanly without swallowing unrelated internal errors."""


# ---------------------------------------------------------------------------
# framing

def send_msg(sock: socket.socket, obj: dict) -> None:
    sock.sendall(json.dumps(obj, separators=(",", ":")).encode() + b"\n")


def recv_msg(fh) -> Optional[dict]:
    line = fh.readline(MAX_LINE)
    if not line:
        return None
    if not line.endswith(b"\n"):
        # readline returned MAX_LINE bytes without a newline: reject
        # loudly instead of parsing a truncated message and desyncing
        # the framing on whatever bytes remain
        raise ValueError(f"message exceeds the {MAX_LINE}-byte frame limit")
    return json.loads(line)


# ---------------------------------------------------------------------------
# coordinator side

class CoordinatorState:
    """Shared, locked job state behind the RPC handlers."""

    def __init__(self, job: dict, dispatcher: Dispatcher, n_targets: int,
                 on_hit: Optional[Callable] = None,
                 on_progress: Optional[Callable] = None,
                 verifier: Optional[Callable] = None,
                 token: Optional[str] = None, registry=None,
                 recorder=None):
        self.job = job                    # serializable job description
        self.dispatcher = dispatcher
        self.n_targets = n_targets
        self.found: dict[int, bytes] = {}
        self.on_hit = on_hit              # (target_index, cand_index, plain)
        self.on_progress = on_progress
        #: (target_index, plaintext) -> bool.  A worker with a buggy or
        #: malicious device path could report a wrong plaintext; accepting
        #: it would permanently mark the target found and poison the
        #: potfile/session journal.  One oracle hash per hit is negligible.
        self.verifier = verifier
        self.rejected = 0
        #: a worker whose hits keep failing verification has a broken
        #: (or malicious) device path; quarantining it stops the
        #: lease -> reject -> requeue livelock (same unit bouncing to
        #: the same worker forever).
        self.worker_rejects: dict[str, int] = {}
        self.unit_reject_workers: dict[int, set] = {}
        self.quarantined: set[str] = set()
        self.token = token                # None = unauthenticated protocol
        self.lock = threading.Lock()
        self.t0 = time.perf_counter()
        #: the registry the RPC port's /metrics endpoint serves; the
        #: Dispatcher publishes unit/keyspace metrics into the same one
        self.registry = get_registry(registry)
        #: the flight recorder op_trace_tail serves; should be the
        #: SAME one the Dispatcher records into so the timeline is
        #: whole (both default to the process-wide recorder)
        self.tracer = get_tracer(recorder)
        m = self.registry
        jm = declare_job_metrics(m)
        self._m_hits = jm["hits"]
        self._m_rejects = jm["rejects"]
        self._m_cands = jm["cands"]
        self._g_targets = jm["targets"]
        self._g_found = jm["found"]
        self._m_rpc = m.counter(
            "dprf_rpc_requests_total", "RPC ops served",
            labelnames=("op",))
        self._g_quar = m.gauge(
            "dprf_workers_quarantined", "workers benched for repeated "
            "unverifiable hits")
        self._g_seen = m.gauge(
            "dprf_worker_last_seen_timestamp",
            "unix time of each worker's last lease/complete",
            labelnames=("worker",))
        self._g_targets.set(n_targets)
        self._g_found.set(0)
        self._g_quar.set(0)

    #: distinct worker ids the liveness gauge will track; label
    #: children live for the registry's lifetime, so id CHURN (every
    #: restart is a new hostname:pid) must not grow coordinator memory
    #: without bound on a long-lived job
    MAX_WORKER_LABELS = 1024

    def _touch_worker(self, wid: str) -> None:
        """Liveness: scrape-visible last-contact time per worker.
        Past the label cap, overflow ids share one child -- the fleet
        stays observable even when individual ids stop being.  (The
        check-then-set pair is not atomic; concurrent handlers can
        overshoot the cap by a few children, which is fine -- the cap
        bounds growth, it is not an exact quota.)"""
        if (not self._g_seen.has_labels(worker=wid)
                and self._g_seen.child_count() >= self.MAX_WORKER_LABELS):
            wid = "_overflow"
        self._g_seen.set(time.time(), worker=wid)

    def refresh_found_gauge(self) -> None:
        """Re-sync dprf_targets_found after out-of-band mutations of
        .found (potfile preload / session restore in cli.cmd_serve)."""
        with self.lock:
            self._g_found.set(len(self.found))

    #: rejected completions before a worker is quarantined.  Lower than
    #: the unit threshold so a single bad worker is benched while its
    #: unit can still requeue to an honest one.
    MAX_WORKER_REJECTS = 2
    #: DISTINCT workers whose reports on one unit were all rejected
    #: before the unit is force-completed (a logged potential coverage
    #: hole beats a job that can never terminate when every worker's
    #: device path is divergent)
    MAX_UNIT_REJECT_WORKERS = 3

    # -- RPC ops ---------------------------------------------------------

    def op_hello(self, msg: dict) -> dict:
        return {"ok": True, "job": self.job}

    def op_lease(self, msg: dict) -> dict:
        """Hand out the next unit(s).  The lease-ahead form
        (``ahead=N``) returns up to N units in ``"units"`` so a
        pipelined worker fills its submit-ahead queue in ONE round
        trip; ``"unit"`` stays the first entry for pre-ahead clients.
        Per-worker holdings are capped at MAX_LEASE_AHEAD."""
        with self.lock:
            if self._stopped():
                return {"unit": None, "stop": True}
            wid = str(msg.get("worker_id", "?"))
            if wid in self.quarantined:
                return {"unit": None, "stop": False, "quarantined": True}
            try:
                ahead = int(msg.get("ahead", 1))
            except (TypeError, ValueError):
                ahead = 1
            ahead = max(1, min(ahead, MAX_LEASE_AHEAD))
            # reap BEFORE clamping against this worker's holdings: a
            # restarted worker (same --id) still "holding" its crashed
            # predecessor's expired leases would otherwise clamp to 0
            # forever -- lease() below is the only reap site during an
            # active job, and a clamp of 0 never reaches it
            self.dispatcher.reap_expired()
            ahead = min(ahead, max(
                0, MAX_LEASE_AHEAD - self.dispatcher.outstanding_for(wid)))
            units = self.dispatcher.lease_many(wid, ahead)
            if not units:
                # nothing leasable right now; workers retry unless done
                return {"unit": None,
                        "stop": self.dispatcher.outstanding_count() == 0}
            # liveness gauge only for ids that actually HOLD a lease:
            # worker_id is client-controlled, and a label child lives
            # forever, so polls with throwaway ids must not grow the
            # registry (holding a lease bounds the id set by the unit
            # ledger)
            self._touch_worker(wid)
            entries = []
            for unit in units:
                e = {"id": unit.unit_id, "start": unit.start,
                     "length": unit.length}
                # trace context OUT, per unit: the worker parents its
                # rpc/warmup/sweep spans onto this lease, so the spans
                # it ships back with complete/fail stitch onto the
                # coordinator timeline
                ctx = self.dispatcher.trace_context(unit.unit_id)
                if ctx is not None:
                    e["trace"] = {"trace": ctx[0], "span": ctx[1]}
                entries.append(e)
            resp = {"unit": entries[0], "units": entries}
            if "trace" in entries[0]:
                # legacy single-unit clients read a top-level context
                resp["trace"] = entries[0]["trace"]
            return resp

    def op_complete(self, msg: dict) -> dict:
        unit_id = int(msg["unit_id"])
        hits = msg.get("hits", [])
        # per-unit wall time reported by the worker: feeds the adaptive
        # unit sizer's per-worker throughput EWMA (tune.unit_sizer).
        # Client-controlled, so sanitize: a junk value must read as "no
        # report", never as a poisoned estimate.
        elapsed = msg.get("elapsed")
        if not (isinstance(elapsed, (int, float)) and elapsed > 0):
            elapsed = None
        # Parse + verify OUTSIDE the lock: the oracle re-hash takes
        # seconds for bcrypt/PBKDF2, and holding the lock there would
        # stall every other worker's lease/complete (and hand any buggy
        # worker a coordinator-wide DoS).
        with self.lock:
            already = set(self.found)
            # trace context of the attempt, read BEFORE complete/fail
            # pops the lease; remote spans + the hit_verify span below
            # parent onto it
            ctx = self.dispatcher.trace_context(unit_id)
        self.tracer.ingest(msg.get("spans"),
                           proc=str(msg.get("worker_id", "?")),
                           sent_at=msg.get("clock"))
        t_verify = time.monotonic()
        verified = []
        rejected = 0
        for h in hits:
            ti = int(h["target"])
            if ti in already or not 0 <= ti < self.n_targets:
                continue
            plain = bytes.fromhex(h["plaintext"])
            if self.verifier is not None and not self.verifier(ti, plain):
                rejected += 1
                continue
            verified.append((ti, int(h["cand"]), plain))
        if hits:
            self.tracer.record(
                "hit_verify", dur=time.monotonic() - t_verify,
                trace=ctx[0] if ctx else None,
                parent=ctx[1] if ctx else None, proc="coordinator",
                unit=unit_id, hits=len(hits), rejected=rejected)
        with self.lock:
            for ti, cand, plain in verified:
                if ti in self.found:
                    continue
                self.found[ti] = plain
                self._m_hits.inc()
                if self.on_hit:
                    self.on_hit(ti, cand, plain)
            self._g_found.set(len(self.found))
            # attribute the unit's candidates BEFORE complete() drops
            # it from the lease ledger: remote workers hash in their
            # own processes, so the coordinator's scrapeable registry
            # must carry the fleet's sweep count itself
            raw_wid = msg.get("worker_id")
            wid = str(raw_wid) if raw_wid is not None else "?"
            # stale-guard context: with lease-ahead a crashed worker's
            # LATE complete can arrive after its unit was reissued to
            # another worker -- the live holder owns the completion
            # (verified hits above were still recorded; hits dedupe)
            guard = wid if raw_wid is not None else None
            unit = self.dispatcher.outstanding_unit(unit_id)
            if rejected:
                # The reporting worker's device path is suspect: requeue
                # the range instead of marking it done, or a wrong
                # plaintext would punch a permanent silent coverage hole
                # where the true crack may live.
                from dprf_tpu.utils.logging import DEFAULT as log
                self.rejected += rejected
                self._m_rejects.inc(rejected)
                self.worker_rejects[wid] = \
                    self.worker_rejects.get(wid, 0) + 1
                if (self.worker_rejects[wid] >= self.MAX_WORKER_REJECTS
                        and wid not in self.quarantined):
                    self.quarantined.add(wid)
                    self._g_quar.set(len(self.quarantined))
                    log.warn("quarantined worker after repeated "
                             "unverifiable hits", worker=wid,
                             rejects=self.worker_rejects[wid])
                rejecters = self.unit_reject_workers.setdefault(
                    unit_id, set())
                rejecters.add(wid)
                if len(rejecters) >= self.MAX_UNIT_REJECT_WORKERS:
                    # several DIFFERENT workers all produced unverifiable
                    # hits for this unit; requeueing again would livelock
                    # the job -- complete it, record the possible hole
                    log.warn("completing unit after rejected reports "
                             "from several workers; range may hold an "
                             "unrecovered crack", unit=unit_id,
                             workers=len(rejecters))
                    self.dispatcher.complete(unit_id, worker_id=guard)
                else:
                    self.dispatcher.fail(unit_id, worker_id=guard)
            else:
                completed = self.dispatcher.complete(
                    unit_id, elapsed=elapsed, worker_id=guard)
                if completed and unit is not None:
                    # liveness only for completions of real leases (see
                    # op_lease on label cardinality); stale or rejected
                    # units are NOT counted -- the range is (re)swept by
                    # the live holder, whose complete counts it once
                    self._touch_worker(wid)
                    self._m_cands.inc(unit.length,
                                      engine=self.job.get("engine", "?"),
                                      device="remote")
            if self.on_progress:
                done, total = self.dispatcher.progress()
                self.on_progress(done, total, len(self.found))
            return {"ok": rejected == 0, "stop": self._stopped()}

    def op_fail(self, msg: dict) -> dict:
        # the failing worker's spans (rpc, the aborted sweep) still
        # join the timeline -- exactly the attempts an operator wants
        # to see when a unit bounced between workers
        self.tracer.ingest(msg.get("spans"),
                           proc=str(msg.get("worker_id", "?")),
                           sent_at=msg.get("clock"))
        raw_wid = msg.get("worker_id")
        with self.lock:
            self.dispatcher.fail(
                int(msg["unit_id"]),
                worker_id=str(raw_wid) if raw_wid is not None else None)
        return {"ok": True}

    def op_trace_tail(self, msg: dict) -> dict:
        """Flight-recorder read for ``dprf top``: the most recent
        spans plus the live lease table and job status -- everything a
        terminal view needs to show per-worker state, current unit,
        span in progress, and lease countdown."""
        try:
            n = int(msg.get("n", 200))
        except (TypeError, ValueError):
            n = 200
        n = max(1, min(n, 2000))
        trace = msg.get("trace")
        trace = trace if isinstance(trace, str) else None
        since = msg.get("since")
        resync = False
        if isinstance(since, str) and since:
            # incremental read (`dprf top --follow`): only spans newer
            # than the caller's cursor; resync=True means the cursor
            # fell off the ring and the payload is a full tail the
            # caller must REPLACE its buffer with
            spans, resync = self.tracer.tail_after(since, n, trace=trace)
        else:
            spans = self.tracer.tail(n, trace=trace)
        cursor = spans[-1].get("span") if spans else (
            since if isinstance(since, str) else None)
        with self.lock:
            done, total = self.dispatcher.progress()
            leases = self.dispatcher.outstanding_leases()
            status = {"done": done, "total": total,
                      "found": len(self.found),
                      "targets": self.n_targets,
                      "parked": self.dispatcher.parked_count(),
                      "stop": self._stopped(),
                      "elapsed": time.perf_counter() - self.t0,
                      # the clock span timestamps live in: span ages
                      # must be computed against THIS, not the
                      # viewer's possibly-skewed wall clock
                      "now": time.time(),
                      "quarantined": sorted(self.quarantined)}
        return {"ok": True, "spans": spans, "leases": leases,
                "status": status, "cursor": cursor, "resync": resync}

    def op_retry_parked(self, msg: dict) -> dict:
        """Admin op (`dprf retry-parked --connect`): requeue poisoned/
        parked units with a fresh retry budget on the LIVE job --
        without restarting it.  Token-authenticated like every other
        RPC op when the coordinator has a token (it mutates the unit
        ledger, unlike the read-only /metrics scrape)."""
        with self.lock:
            n = self.dispatcher.retry_parked()
        return {"ok": True, "retried": n}

    def op_metrics(self, msg: dict) -> dict:
        """Registry read over the RPC protocol (authenticated when the
        coordinator has a token); the HTTP GET path below serves the
        same registry for Prometheus scrapers."""
        if msg.get("format") == "json":
            return {"ok": True, "metrics": self.registry.snapshot()}
        return {"ok": True, "text": self.registry.render()}

    def op_status(self, msg: dict) -> dict:
        with self.lock:
            done, total = self.dispatcher.progress()
            return {"done": done, "total": total,
                    "found": len(self.found), "stop": self._stopped(),
                    # poisoned ranges (retry-cap parked): a job that
                    # "finished" with parked units did NOT sweep them
                    "parked": self.dispatcher.parked_count(),
                    "parked_indices": self.dispatcher.parked_indices(),
                    "elapsed": time.perf_counter() - self.t0}

    def _stopped(self) -> bool:
        return (len(self.found) >= self.n_targets
                or self.dispatcher.done())
    _stopped._holds_lock = "lock"   # callers hold self.lock

    def finished(self) -> bool:
        with self.lock:
            return self._stopped()


def challenge_response(token: str, nonce_hex: str) -> str:
    """The proof a client sends for a hello challenge."""
    return hmac_mod.new(token.encode(), bytes.fromhex(nonce_hex),
                        "sha256").hexdigest()


class _Handler(socketserver.StreamRequestHandler):
    #: failed auth attempts before the connection is dropped
    MAX_AUTH_FAILURES = 3

    def _serve_http(self, request_line: bytes) -> None:
        """One-shot HTTP responder on the RPC port: ``GET /metrics``
        returns the coordinator registry in Prometheus text format.
        Read-only observability is served even when the RPC protocol
        is token-authenticated -- it exposes rates and counts, never
        the job description or hits -- so a scraper needs no secret."""
        state: CoordinatorState = self.server.state   # type: ignore
        try:
            while True:            # drain request headers politely
                line = self.rfile.readline(MAX_LINE)
                if not line or line in (b"\r\n", b"\n"):
                    break
            parts = request_line.split()
            head_only = parts and parts[0] == b"HEAD"
            path = parts[1].decode("latin-1") if len(parts) > 1 else ""
            if path.split("?")[0] == "/metrics":
                body = state.registry.render().encode()
                head = (b"HTTP/1.0 200 OK\r\n"
                        b"Content-Type: text/plain; version=0.0.4; "
                        b"charset=utf-8\r\n"
                        b"Content-Length: %d\r\n"
                        b"Connection: close\r\n\r\n" % len(body))
            else:
                body = b"try /metrics\n"
                head = (b"HTTP/1.0 404 Not Found\r\n"
                        b"Content-Type: text/plain\r\n"
                        b"Content-Length: %d\r\n"
                        b"Connection: close\r\n\r\n" % len(body))
            # HEAD: headers only (Content-Length still describes what
            # GET would return)
            self.connection.sendall(head if head_only else head + body)
        except OSError:
            pass

    def handle(self):
        state: CoordinatorState = self.server.state   # type: ignore
        nonce = secrets.token_hex(16)      # challenge, rotated per failure
        auth_failures = 0
        authed = state.token is None
        while True:
            try:
                line = self.rfile.readline(MAX_LINE)
            except OSError:
                return
            if not line:
                return
            if line.startswith((b"GET ", b"HEAD ")):
                # Prometheus/curl scrape on the RPC port: answer HTTP
                # and close (HTTP clients don't speak the JSON framing)
                self._serve_http(line)
                return
            if not line.endswith(b"\n"):
                return     # over the frame limit: drop, as recv_msg does
            try:
                msg = json.loads(line)
            except ValueError:
                return
            if not isinstance(msg, dict):
                return
            if not authed:
                if msg.get("op") == "hello":
                    mac = msg.get("hmac")
                    if (isinstance(mac, str) and hmac_mod.compare_digest(
                            mac, challenge_response(state.token, nonce))):
                        authed = True      # fall through to op_hello
                    else:
                        # a fresh nonce per attempt: a failed guess
                        # teaches nothing about the next challenge
                        auth_failures += 1
                        nonce = secrets.token_hex(16)
                        try:
                            send_msg(self.connection,
                                     {"ok": False, "challenge": nonce})
                        except OSError:
                            return
                        if auth_failures >= self.MAX_AUTH_FAILURES:
                            return          # drop the connection
                        continue
                else:
                    try:
                        send_msg(self.connection,
                                 {"error": "unauthenticated"})
                    except OSError:
                        return
                    continue
            op = getattr(state, f"op_{msg.get('op', '')}", None)
            # unknown ops share ONE label child: op strings are
            # client-controlled, and each distinct label value lives in
            # the registry forever -- an open-protocol client must not
            # be able to grow coordinator memory one junk op at a time
            state._m_rpc.inc(
                op=str(msg.get("op", "?")) if op is not None
                else "unknown")
            if op is None:
                resp = {"error": f"unknown op {msg.get('op')!r}"}
            else:
                try:
                    resp = op(msg)
                except Exception as e:       # defensive: never kill server
                    resp = {"error": f"{type(e).__name__}: {e}"}
            if (msg.get("op") == "hello" and state.token
                    and isinstance(msg.get("cnonce"), str)):
                # mutual auth: prove WE know the token over the
                # client's nonce, so a worker with --token refuses a
                # spoofed coordinator (and the job it would hand out)
                try:
                    resp["coordinator_hmac"] = challenge_response(
                        state.token, msg["cnonce"])
                except ValueError:
                    resp = {"error": "bad cnonce (want hex)"}
            try:
                send_msg(self.connection, resp)
            except OSError:
                return


class CoordinatorServer:
    """Threaded TCP server around a CoordinatorState."""

    def __init__(self, state: CoordinatorState, host: str = "127.0.0.1",
                 port: int = 0):
        # bind manually so allow_reuse_address is set BEFORE bind():
        # otherwise a restart on the same port trips over TIME_WAIT
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=False)
        self._srv.daemon_threads = True
        self._srv.allow_reuse_address = True
        try:
            self._srv.server_bind()
            self._srv.server_activate()
        except BaseException:
            self._srv.server_close()
            raise
        self._srv.state = state            # type: ignore
        self.state = state
        self.address = self._srv.server_address

    def serve_until_done(self, poll: float = 0.5,
                         drain: float = 600.0) -> None:
        """Run until the job finishes, then keep serving until every
        outstanding lease resolves (workers mid-unit must be able to
        report their final hits and see the stop flag -- a fixed grace
        window would race against unit processing time).  `drain` caps
        the wait so a worker that died holding a lease can't pin the
        server forever."""
        t = threading.Thread(target=self._srv.serve_forever,
                             kwargs={"poll_interval": 0.1}, daemon=True)
        t.start()
        try:
            while not self.state.finished():
                time.sleep(poll)
            deadline = time.monotonic() + drain
            while time.monotonic() < deadline:
                with self.state.lock:
                    # expired leases (dead workers) won't be reaped by
                    # lease() anymore -- nobody is leasing -- so reap
                    # here or a dead worker would pin the drain loop
                    self.state.dispatcher.reap_expired()
                    outstanding = self.state.dispatcher.outstanding_count()
                if outstanding == 0:
                    break
                time.sleep(poll)
            time.sleep(poll)   # let final responses flush
        finally:
            self._srv.shutdown()
            self._srv.server_close()

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self._srv.serve_forever,
                             kwargs={"poll_interval": 0.1}, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


# ---------------------------------------------------------------------------
# worker side

class CoordinatorClient:
    """Blocking JSON-RPC client used by remote workers."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 token: Optional[str] = None):
        self._addr = (host, port)
        self._timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._fh = self._sock.makefile("rb")
        self._token = token

    def clone(self) -> "CoordinatorClient":
        """A second authenticated connection to the same coordinator
        -- the async completion sender's channel, so report round
        trips ride beside the lease/sweep loop instead of inside it.
        Authentication is per-connection, so a token-auth'd clone
        answers its own hello challenge here."""
        peer = type(self)(self._addr[0], self._addr[1],
                          timeout=self._timeout, token=self._token)
        if self._token:
            try:
                peer.hello()
            except BaseException:
                peer.close()
                raise
        return peer

    def hello(self) -> dict:
        """Fetch the job, answering the coordinator's auth challenge if
        it has one.  When this client holds a token, the coordinator
        must in turn prove it knows the token over OUR nonce (mutual
        auth): a spoofed coordinator cannot hand this worker a job."""
        cnonce = secrets.token_hex(16)
        resp = self.call("hello", cnonce=cnonce)
        if resp.get("challenge"):
            if not self._token:
                raise RpcError(
                    "coordinator requires authentication; pass --token")
            resp = self.call("hello", cnonce=cnonce,
                             hmac=challenge_response(
                                 self._token, resp["challenge"]))
            if resp.get("challenge"):
                raise RpcError("authentication failed (wrong token?)")
        if self._token:
            proof = resp.get("coordinator_hmac")
            if not (isinstance(proof, str) and hmac_mod.compare_digest(
                    proof, challenge_response(self._token, cnonce))):
                raise RpcError("coordinator failed mutual authentication "
                               "(spoofed coordinator, or it has no/other "
                               "token)")
        return resp

    def call(self, op: str, **kw) -> dict:
        kw["op"] = op
        send_msg(self._sock, kw)
        resp = recv_msg(self._fh)
        if resp is None:
            raise ConnectionError("coordinator closed the connection")
        if "error" in resp:
            raise RpcError(f"coordinator error: {resp['error']}")
        return resp

    def close(self) -> None:
        # the makefile() stream holds its own reference to the socket
        # (and a buffer): closing only the socket leaks the stream
        # object and keeps the fd alive until GC
        try:
            self._fh.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _CompletionSender:
    """Ships ``complete``/``fail`` reports from a background thread on
    a dedicated connection, so the report round trip overlaps the next
    sweep instead of serializing with it.  Ordering is preserved (one
    FIFO queue, one thread); the first send failure is latched and
    re-raised by ``drain()`` -- the crash-surfacing contract of the
    serial loop.  Reports queued after a failure are dropped: their
    leases expire and reissue, and the latched error aborts the loop
    anyway."""

    def __init__(self, client: CoordinatorClient):
        import queue
        self._client = client
        self._q: "queue.Queue" = queue.Queue()
        self.error: Optional[BaseException] = None
        self.stop_seen = False
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="dprf-sender")
        self._t.start()

    def send(self, op: str, **kw) -> None:
        self._q.put((op, kw))

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            op, kw = item
            try:
                if self.error is None:
                    # clock stamped at SEND time: the coordinator
                    # rebases the shipped span timestamps against it
                    resp = self._client.call(op, clock=time.time(),
                                             **kw)
                    if resp.get("stop"):
                        self.stop_seen = True
            except Exception as e:   # noqa: BLE001 -- latched, then
                self.error = e       # re-raised by drain()
            finally:
                self._q.task_done()

    def drain(self) -> None:
        """Block until every queued report was sent (or dropped past a
        failure), then re-raise the first send failure."""
        self._q.join()
        if self.error is not None:
            raise self.error

    def close(self) -> None:
        self._q.put(None)
        self._t.join(timeout=30)
        self._client.close()


def worker_loop(client: CoordinatorClient, worker, worker_id: str,
                idle_sleep: float = 0.5, log=None, registry=None,
                recorder=None, depth: Optional[int] = None) -> int:
    """Pipelined lease -> submit-ahead -> resolve -> async-complete
    loop, until the coordinator says stop.  Returns units completed.

    worker: any object with .process(WorkUnit) -> list[Hit] (the same
    duck type the local Coordinator drives).  Submit-based workers
    (``process._submit_based``) enqueue unit N+1's device work BEFORE
    unit N resolves, so the next super-step is on the device stream
    while the host decodes hits and the RPC round trips fly; serial
    workers still gain the lease-ahead batch and the overlapped
    completion report.  ``depth`` defaults to the shared
    ``DPRF_PIPELINE_DEPTH`` knob; depth 1 is the serial fallback (one
    connection, synchronous completes -- the pre-pipelining loop).

    Crash surfacing matches the serial loop: a processing failure
    fails the aborted unit AND every queued lease, then re-raises;
    queued completion reports are drained before any return, and the
    first async send failure is re-raised.

    Tracing: the lease response's trace context parents this worker's
    ``rpc`` / ``warmup`` / ``sweep`` spans, which ship back inside the
    complete (or fail) message -- the coordinator's flight recorder
    then holds the unit's WHOLE lifecycle across every host that
    touched it.  ``DPRF_JAX_PROFILE=<dir>`` additionally wraps the
    loop in a jax.profiler trace.
    """
    from dprf_tpu.runtime.worker import UnitPipeline, pipeline_depth

    m = get_registry(registry)
    tracer = get_tracer(recorder)
    # worker-side publication: candidates are counted where the hashing
    # happens (the local Coordinator does the same for in-process
    # jobs); declared through declare_job_metrics -- the ONE
    # declaration site (tools/check_metrics.py) -- so names and labels
    # can never drift from the coordinator's
    jm = declare_job_metrics(m)
    eng_name = getattr(getattr(worker, "engine", None), "name", "unknown")
    device = "cpu" if type(worker).__name__ == "CpuWorker" else "jax"
    m_cands = jm["cands"]
    h_unit = jm["unit_seconds"]
    g_depth = m.gauge(
        "dprf_worker_pipeline_depth",
        "units this worker submits ahead of the oldest unresolved one "
        "(1 = serial loop)")
    c_idle = m.counter(
        "dprf_worker_idle_seconds",
        "seconds this worker held no submitted unit between sweeps "
        "(pipeline drained: the device idles while RPCs fly)")
    if depth is None:
        depth = pipeline_depth()
    sender = None
    if depth > 1:
        try:
            sender = _CompletionSender(client.clone())
        except (OSError, RpcError) as e:
            if log:
                log.warn("completion-sender connection failed; "
                         "running the serial loop", error=str(e))
            depth = 1
    g_depth.set(depth)
    pipe = UnitPipeline(worker, depth)
    done_units = 0
    stop_seen = False
    idle_mark: Optional[float] = None
    t_last_resolve: Optional[float] = None
    warm_pending = getattr(worker, "ensure_warm", None) is not None
    cur = None        # entry being submitted/resolved, for the fail path
    lease_q: list = []    # leased-but-not-yet-submitted batch remainder

    def send_report(op: str, **kw) -> Optional[dict]:
        if sender is not None:
            sender.send(op, **kw)
            return None
        return client.call(op, clock=time.time(), **kw)

    def send_fail(unit_id: int, ship: list) -> None:
        try:
            send_report("fail", unit_id=unit_id, worker_id=worker_id,
                        spans=ship)
        except Exception:   # noqa: BLE001 -- best-effort, as serial
            pass            # (the lease expires and reissues anyway)

    try:
        with jax_profile_ctx(log=log):
            while True:
                if sender is not None and sender.error is not None:
                    # the coordinator stopped answering completion
                    # reports: surface it like a serial complete would
                    raise sender.error
                want = pipe.depth - len(pipe)
                entries = []
                if want > 0 and not stop_seen:
                    t_lease = time.monotonic()
                    try:
                        resp = client.call("lease", worker_id=worker_id,
                                           ahead=want)
                    except ConnectionError:
                        # The coordinator serves through its drain
                        # window and answers every lease poll with an
                        # explicit stop flag once the job is over, so a
                        # worker always learns completion in-band and
                        # returns below.  A bare connection drop here
                        # therefore means the coordinator crashed
                        # mid-job: surface it so scripted workers don't
                        # report success on unfinished work.
                        raise ConnectionError(
                            "coordinator connection dropped before any "
                            "stop signal (coordinator crash mid-job?)")
                    if resp.get("quarantined"):
                        raise RpcError(
                            "coordinator quarantined this worker: its "
                            "reported hits repeatedly failed oracle "
                            "verification (divergent device path?)")
                    lease_rtt = time.monotonic() - t_lease
                    entries = resp.get("units")
                    if entries is None:
                        # pre-lease-ahead coordinator: single unit with
                        # a top-level trace context
                        entries = []
                        if resp.get("unit"):
                            unit_d = dict(resp["unit"])
                            if resp.get("trace"):
                                unit_d.setdefault("trace", resp["trace"])
                            entries = [unit_d]
                    if not entries:
                        if resp.get("stop"):
                            stop_seen = True
                        elif sender is not None:
                            # all our reports are in flight: land them,
                            # then trust the freshest stop answer (the
                            # final complete's response carries it)
                            # instead of sleeping into another poll
                            sender.drain()
                            if sender.stop_seen:
                                stop_seen = True
                        if len(pipe) == 0:
                            if stop_seen:
                                break
                            time.sleep(idle_sleep)
                            continue
                    first = True
                    lease_q = list(entries)
                    while lease_q:
                        unit_d = lease_q.pop(0)
                        unit = WorkUnit(unit_d["id"], unit_d["start"],
                                        unit_d["length"])
                        ctx = unit_d.get("trace") or {}
                        tid, lease_sid = ctx.get("trace"), ctx.get("span")
                        ship: list = []
                        if first:
                            # one rpc span per lease round trip,
                            # parented on the batch's first lease
                            first = False
                            ev = tracer.record(
                                "rpc", dur=lease_rtt, trace=tid,
                                parent=lease_sid, proc=worker_id,
                                op="lease", unit=unit.unit_id,
                                units=len(entries))
                            if ev:
                                ship.append(ev)
                        cur = (unit, None, time.monotonic(),
                               (tid, lease_sid, ship))
                        # join an overlapped warmup (cli.cmd_worker
                        # starts one before the loop, so the compile
                        # overlapped the lease round trip); under the
                        # fail path so a compile failure releases the
                        # lease like any processing failure
                        ensure_warm = getattr(worker, "ensure_warm",
                                              None)
                        if ensure_warm is not None:
                            ensure_warm()
                        if warm_pending:
                            # the compile ran overlapped on a background
                            # thread; report its REAL cost
                            # (compile_seconds), not the near-zero join
                            # time, so a fleet stalled on cold compiles
                            # is legible in the trace
                            warm_pending = False
                            warm_s = getattr(worker, "compile_seconds",
                                             None)
                            if warm_s is not None:
                                ev = tracer.record(
                                    "warmup", dur=float(warm_s),
                                    trace=tid, parent=lease_sid,
                                    proc=worker_id, engine=eng_name,
                                    cache=getattr(worker,
                                                  "compile_cache",
                                                  None),
                                    overlapped=True)
                                if ev:
                                    ship.append(ev)
                        if idle_mark is not None:
                            # the pipeline had drained: that gap was
                            # device-idle time (RPCs with no submitted
                            # work to hide them behind)
                            c_idle.inc(time.monotonic() - idle_mark)
                            idle_mark = None
                        pipe.submit(unit, meta=(tid, lease_sid, ship))
                        cur = None
                if len(pipe) == 0:
                    if stop_seen:
                        break
                    continue
                cur = pipe.pop()
                unit, pending, t_submit, (tid, lease_sid, ship) = cur
                hits = pending.resolve()
                cur = None
                now = time.monotonic()
                unit_s = now - t_submit
                # steady-state per-unit cost for the ADAPTIVE SIZER:
                # the interval between consecutive resolves.  unit_s
                # (submit->resolve) includes up to depth-1 units of
                # queue wait behind the device stream, which would read
                # as ~1/depth of the true throughput and shrink every
                # subsequent unit; the completion interval measures the
                # worker's real drain rate once the pipeline is primed.
                # After a drain (no leasable work) the interval would
                # instead carry starvation time, so it resets below and
                # the next unit falls back to its own unit_s.
                elapsed_report = (now - t_last_resolve
                                  if t_last_resolve is not None
                                  else unit_s)
                t_last_resolve = now
                if len(pipe) == 0:
                    idle_mark = now
                    t_last_resolve = None
                # the histogram gets the same per-unit cost: observing
                # unit_s here would inflate dprf_unit_seconds ~depth x
                # under pipelining with no throughput change
                h_unit.observe(elapsed_report)
                m_cands.inc(unit.length, engine=eng_name, device=device)
                # ts backdates to t_submit, so consecutive sweep spans
                # OVERLAP when the loop pipelines (the invariant
                # tools/trace_overlap.py checks)
                ev = tracer.record("sweep", dur=unit_s, trace=tid,
                                   parent=lease_sid, proc=worker_id,
                                   unit=unit.unit_id, length=unit.length,
                                   hits=len(hits))
                if ev:
                    ship.append(ev)
                payload = [{"target": h.target_index,
                            "cand": h.cand_index,
                            "plaintext": h.plaintext.hex()}
                           for h in hits]
                # elapsed rides the complete report: the coordinator's
                # adaptive unit sizer turns it into this worker's next
                # unit length; spans stitch the attempt onto the
                # coordinator's flight recorder
                resp = send_report("complete", unit_id=unit.unit_id,
                                   hits=payload, worker_id=worker_id,
                                   elapsed=elapsed_report, spans=ship)
                done_units += 1
                if log and hits:
                    log.info("hits reported", count=len(hits))
                if resp is not None and resp.get("stop"):
                    stop_seen = True
                if sender is not None and sender.stop_seen:
                    stop_seen = True
                if stop_seen and len(pipe) == 0:
                    break
        # clean exit: every queued report must land before we return
        # (the serial loop's in-band completion contract); the first
        # async send failure re-raises here
        if sender is not None:
            sender.drain()
        return done_units
    except BaseException as e:
        if cur is not None:
            # the aborted attempt still joins the timeline: ship what
            # we have with the fail report, then release the lease (and
            # every still-queued one) for another worker
            unit, _, t_unit, (tid, lease_sid, ship) = cur
            ev = tracer.record("sweep",
                               dur=time.monotonic() - t_unit,
                               trace=tid, parent=lease_sid,
                               proc=worker_id, unit=unit.unit_id,
                               error=type(e).__name__)
            if ev:
                ship.append(ev)
            send_fail(unit.unit_id, ship)
        for q_unit, _, _, meta in pipe.drain():
            send_fail(q_unit.unit_id, meta[2])
        for unit_d in lease_q:
            # leased but never submitted (the batch aborted first):
            # release these too, or they pin the ledger until expiry
            send_fail(unit_d["id"], [])
        if sender is not None:
            try:
                sender._q.join()   # land the fails; the original
            except Exception:      # error outranks any send failure
                pass
        raise
    finally:
        if sender is not None:
            sender.close()
