"""Host-level distributed backend: coordinator RPC + remote workers.

Inside one host/slice, parallelism is XLA collectives over ICI (the
sharded steps in dprf_tpu/parallel) -- there is no NCCL/MPI analogue to
manage.  ACROSS hosts, the control plane is deliberately tiny, exactly
the Dispatcher surface: lease a WorkUnit, report hits, complete.  This
module is that control plane: newline-delimited JSON over TCP.

    coordinator (dprf serve):  owns Dispatcher + found set + potfile/
        session persistence; hands out leases under a lock.
    worker (dprf worker):      connects, receives the job description,
        rebuilds engine/generator/targets locally, then loops
        lease -> fused device sweep -> complete(hits).

Fault model: a worker that dies simply stops leasing; its outstanding
unit's lease expires and the Dispatcher reissues it (idempotent -- units
are pure functions of the index range).  A worker that reports hits for
an already-reissued unit is harmless: hits are deduped by target.

Trust model: optional shared-secret authentication (--token).  When the
coordinator has a token, every connection must answer an HMAC-SHA256
challenge on hello before any other op is served (the challenge nonce
rotates after every failed attempt and a connection is dropped after a
few failures, so a connection cannot grind guesses against one nonce);
the worker may send its own nonce in hello, and the coordinator's reply
proves knowledge of the token over it -- mutual authentication.
Without a token the protocol is open -- bind to localhost or a trusted
network only (same stance as hashtopolis-style agents).  The transport
is cleartext either way: the token authenticates peers, it does not
encrypt the job.  The job description includes the raw hashlist lines;
wordlist files must exist on each worker host (they are referenced by
path, never shipped).
"""

from __future__ import annotations

import hmac as hmac_mod
import json
import secrets
import socket
import socketserver
import threading
import time
from typing import Callable, Optional

from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.worker import Hit
from dprf_tpu.runtime.workunit import WorkUnit

MAX_LINE = 64 << 20   # hashlists can be large; candidates never cross


class RpcError(RuntimeError):
    """Protocol-level failure talking to the coordinator (error
    response, auth failure).  Distinct from RuntimeError so the CLI can
    report it cleanly without swallowing unrelated internal errors."""


# ---------------------------------------------------------------------------
# framing

def send_msg(sock: socket.socket, obj: dict) -> None:
    sock.sendall(json.dumps(obj, separators=(",", ":")).encode() + b"\n")


def recv_msg(fh) -> Optional[dict]:
    line = fh.readline(MAX_LINE)
    if not line:
        return None
    if not line.endswith(b"\n"):
        # readline returned MAX_LINE bytes without a newline: reject
        # loudly instead of parsing a truncated message and desyncing
        # the framing on whatever bytes remain
        raise ValueError(f"message exceeds the {MAX_LINE}-byte frame limit")
    return json.loads(line)


# ---------------------------------------------------------------------------
# coordinator side

class CoordinatorState:
    """Shared, locked job state behind the RPC handlers."""

    def __init__(self, job: dict, dispatcher: Dispatcher, n_targets: int,
                 on_hit: Optional[Callable] = None,
                 on_progress: Optional[Callable] = None,
                 verifier: Optional[Callable] = None,
                 token: Optional[str] = None):
        self.job = job                    # serializable job description
        self.dispatcher = dispatcher
        self.n_targets = n_targets
        self.found: dict[int, bytes] = {}
        self.on_hit = on_hit              # (target_index, cand_index, plain)
        self.on_progress = on_progress
        #: (target_index, plaintext) -> bool.  A worker with a buggy or
        #: malicious device path could report a wrong plaintext; accepting
        #: it would permanently mark the target found and poison the
        #: potfile/session journal.  One oracle hash per hit is negligible.
        self.verifier = verifier
        self.rejected = 0
        #: a worker whose hits keep failing verification has a broken
        #: (or malicious) device path; quarantining it stops the
        #: lease -> reject -> requeue livelock (same unit bouncing to
        #: the same worker forever).
        self.worker_rejects: dict[str, int] = {}
        self.unit_reject_workers: dict[int, set] = {}
        self.quarantined: set[str] = set()
        self.token = token                # None = unauthenticated protocol
        self.lock = threading.Lock()
        self.t0 = time.perf_counter()

    #: rejected completions before a worker is quarantined.  Lower than
    #: the unit threshold so a single bad worker is benched while its
    #: unit can still requeue to an honest one.
    MAX_WORKER_REJECTS = 2
    #: DISTINCT workers whose reports on one unit were all rejected
    #: before the unit is force-completed (a logged potential coverage
    #: hole beats a job that can never terminate when every worker's
    #: device path is divergent)
    MAX_UNIT_REJECT_WORKERS = 3

    # -- RPC ops ---------------------------------------------------------

    def op_hello(self, msg: dict) -> dict:
        return {"ok": True, "job": self.job}

    def op_lease(self, msg: dict) -> dict:
        with self.lock:
            if self._stopped():
                return {"unit": None, "stop": True}
            wid = str(msg.get("worker_id", "?"))
            if wid in self.quarantined:
                return {"unit": None, "stop": False, "quarantined": True}
            unit = self.dispatcher.lease(wid)
            if unit is None:
                # nothing leasable right now; workers retry unless done
                return {"unit": None,
                        "stop": self.dispatcher.outstanding_count() == 0}
            return {"unit": {"id": unit.unit_id, "start": unit.start,
                             "length": unit.length}}

    def op_complete(self, msg: dict) -> dict:
        unit_id = int(msg["unit_id"])
        hits = msg.get("hits", [])
        # Parse + verify OUTSIDE the lock: the oracle re-hash takes
        # seconds for bcrypt/PBKDF2, and holding the lock there would
        # stall every other worker's lease/complete (and hand any buggy
        # worker a coordinator-wide DoS).
        with self.lock:
            already = set(self.found)
        verified = []
        rejected = 0
        for h in hits:
            ti = int(h["target"])
            if ti in already or not 0 <= ti < self.n_targets:
                continue
            plain = bytes.fromhex(h["plaintext"])
            if self.verifier is not None and not self.verifier(ti, plain):
                rejected += 1
                continue
            verified.append((ti, int(h["cand"]), plain))
        with self.lock:
            for ti, cand, plain in verified:
                if ti in self.found:
                    continue
                self.found[ti] = plain
                if self.on_hit:
                    self.on_hit(ti, cand, plain)
            if rejected:
                # The reporting worker's device path is suspect: requeue
                # the range instead of marking it done, or a wrong
                # plaintext would punch a permanent silent coverage hole
                # where the true crack may live.
                from dprf_tpu.utils.logging import DEFAULT as log
                self.rejected += rejected
                wid = str(msg.get("worker_id", "?"))
                self.worker_rejects[wid] = \
                    self.worker_rejects.get(wid, 0) + 1
                if (self.worker_rejects[wid] >= self.MAX_WORKER_REJECTS
                        and wid not in self.quarantined):
                    self.quarantined.add(wid)
                    log.warn("quarantined worker after repeated "
                             "unverifiable hits", worker=wid,
                             rejects=self.worker_rejects[wid])
                rejecters = self.unit_reject_workers.setdefault(
                    unit_id, set())
                rejecters.add(wid)
                if len(rejecters) >= self.MAX_UNIT_REJECT_WORKERS:
                    # several DIFFERENT workers all produced unverifiable
                    # hits for this unit; requeueing again would livelock
                    # the job -- complete it, record the possible hole
                    log.warn("completing unit after rejected reports "
                             "from several workers; range may hold an "
                             "unrecovered crack", unit=unit_id,
                             workers=len(rejecters))
                    self.dispatcher.complete(unit_id)
                else:
                    self.dispatcher.fail(unit_id)
            else:
                self.dispatcher.complete(unit_id)
            if self.on_progress:
                done, total = self.dispatcher.progress()
                self.on_progress(done, total, len(self.found))
            return {"ok": rejected == 0, "stop": self._stopped()}

    def op_fail(self, msg: dict) -> dict:
        with self.lock:
            self.dispatcher.fail(int(msg["unit_id"]))
        return {"ok": True}

    def op_status(self, msg: dict) -> dict:
        with self.lock:
            done, total = self.dispatcher.progress()
            return {"done": done, "total": total,
                    "found": len(self.found), "stop": self._stopped(),
                    "elapsed": time.perf_counter() - self.t0}

    def _stopped(self) -> bool:
        return (len(self.found) >= self.n_targets
                or self.dispatcher.done())

    def finished(self) -> bool:
        with self.lock:
            return self._stopped()


def challenge_response(token: str, nonce_hex: str) -> str:
    """The proof a client sends for a hello challenge."""
    return hmac_mod.new(token.encode(), bytes.fromhex(nonce_hex),
                        "sha256").hexdigest()


class _Handler(socketserver.StreamRequestHandler):
    #: failed auth attempts before the connection is dropped
    MAX_AUTH_FAILURES = 3

    def handle(self):
        state: CoordinatorState = self.server.state   # type: ignore
        nonce = secrets.token_hex(16)      # challenge, rotated per failure
        auth_failures = 0
        authed = state.token is None
        while True:
            try:
                msg = recv_msg(self.rfile)
            except (ValueError, OSError):
                return
            if msg is None:
                return
            if not authed:
                if msg.get("op") == "hello":
                    mac = msg.get("hmac")
                    if (isinstance(mac, str) and hmac_mod.compare_digest(
                            mac, challenge_response(state.token, nonce))):
                        authed = True      # fall through to op_hello
                    else:
                        # a fresh nonce per attempt: a failed guess
                        # teaches nothing about the next challenge
                        auth_failures += 1
                        nonce = secrets.token_hex(16)
                        try:
                            send_msg(self.connection,
                                     {"ok": False, "challenge": nonce})
                        except OSError:
                            return
                        if auth_failures >= self.MAX_AUTH_FAILURES:
                            return          # drop the connection
                        continue
                else:
                    try:
                        send_msg(self.connection,
                                 {"error": "unauthenticated"})
                    except OSError:
                        return
                    continue
            op = getattr(state, f"op_{msg.get('op', '')}", None)
            if op is None:
                resp = {"error": f"unknown op {msg.get('op')!r}"}
            else:
                try:
                    resp = op(msg)
                except Exception as e:       # defensive: never kill server
                    resp = {"error": f"{type(e).__name__}: {e}"}
            if (msg.get("op") == "hello" and state.token
                    and isinstance(msg.get("cnonce"), str)):
                # mutual auth: prove WE know the token over the
                # client's nonce, so a worker with --token refuses a
                # spoofed coordinator (and the job it would hand out)
                try:
                    resp["coordinator_hmac"] = challenge_response(
                        state.token, msg["cnonce"])
                except ValueError:
                    resp = {"error": "bad cnonce (want hex)"}
            try:
                send_msg(self.connection, resp)
            except OSError:
                return


class CoordinatorServer:
    """Threaded TCP server around a CoordinatorState."""

    def __init__(self, state: CoordinatorState, host: str = "127.0.0.1",
                 port: int = 0):
        # bind manually so allow_reuse_address is set BEFORE bind():
        # otherwise a restart on the same port trips over TIME_WAIT
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=False)
        self._srv.daemon_threads = True
        self._srv.allow_reuse_address = True
        try:
            self._srv.server_bind()
            self._srv.server_activate()
        except BaseException:
            self._srv.server_close()
            raise
        self._srv.state = state            # type: ignore
        self.state = state
        self.address = self._srv.server_address

    def serve_until_done(self, poll: float = 0.5,
                         drain: float = 600.0) -> None:
        """Run until the job finishes, then keep serving until every
        outstanding lease resolves (workers mid-unit must be able to
        report their final hits and see the stop flag -- a fixed grace
        window would race against unit processing time).  `drain` caps
        the wait so a worker that died holding a lease can't pin the
        server forever."""
        t = threading.Thread(target=self._srv.serve_forever,
                             kwargs={"poll_interval": 0.1}, daemon=True)
        t.start()
        try:
            while not self.state.finished():
                time.sleep(poll)
            deadline = time.monotonic() + drain
            while time.monotonic() < deadline:
                with self.state.lock:
                    # expired leases (dead workers) won't be reaped by
                    # lease() anymore -- nobody is leasing -- so reap
                    # here or a dead worker would pin the drain loop
                    self.state.dispatcher.reap_expired()
                    outstanding = self.state.dispatcher.outstanding_count()
                if outstanding == 0:
                    break
                time.sleep(poll)
            time.sleep(poll)   # let final responses flush
        finally:
            self._srv.shutdown()
            self._srv.server_close()

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self._srv.serve_forever,
                             kwargs={"poll_interval": 0.1}, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


# ---------------------------------------------------------------------------
# worker side

class CoordinatorClient:
    """Blocking JSON-RPC client used by remote workers."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 token: Optional[str] = None):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._fh = self._sock.makefile("rb")
        self._token = token

    def hello(self) -> dict:
        """Fetch the job, answering the coordinator's auth challenge if
        it has one.  When this client holds a token, the coordinator
        must in turn prove it knows the token over OUR nonce (mutual
        auth): a spoofed coordinator cannot hand this worker a job."""
        cnonce = secrets.token_hex(16)
        resp = self.call("hello", cnonce=cnonce)
        if resp.get("challenge"):
            if not self._token:
                raise RpcError(
                    "coordinator requires authentication; pass --token")
            resp = self.call("hello", cnonce=cnonce,
                             hmac=challenge_response(
                                 self._token, resp["challenge"]))
            if resp.get("challenge"):
                raise RpcError("authentication failed (wrong token?)")
        if self._token:
            proof = resp.get("coordinator_hmac")
            if not (isinstance(proof, str) and hmac_mod.compare_digest(
                    proof, challenge_response(self._token, cnonce))):
                raise RpcError("coordinator failed mutual authentication "
                               "(spoofed coordinator, or it has no/other "
                               "token)")
        return resp

    def call(self, op: str, **kw) -> dict:
        kw["op"] = op
        send_msg(self._sock, kw)
        resp = recv_msg(self._fh)
        if resp is None:
            raise ConnectionError("coordinator closed the connection")
        if "error" in resp:
            raise RpcError(f"coordinator error: {resp['error']}")
        return resp

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def worker_loop(client: CoordinatorClient, worker, worker_id: str,
                idle_sleep: float = 0.5, log=None) -> int:
    """Lease -> process -> complete until the coordinator says stop.

    worker: any object with .process(WorkUnit) -> list[Hit] (the same
    duck type the local Coordinator drives).  Returns units completed.
    """
    done_units = 0
    while True:
        try:
            resp = client.call("lease", worker_id=worker_id)
        except ConnectionError:
            # The coordinator serves through its drain window and
            # answers every lease poll with an explicit stop flag once
            # the job is over, so a worker always learns completion
            # in-band and returns below.  A bare connection drop here
            # therefore means the coordinator crashed mid-job: surface
            # it so scripted workers don't report success on unfinished
            # work (a clean rc used to hide exactly that).
            raise ConnectionError(
                "coordinator connection dropped before any stop signal "
                "(coordinator crash mid-job?)")
        if resp.get("quarantined"):
            raise RpcError(
                "coordinator quarantined this worker: its reported hits "
                "repeatedly failed oracle verification (divergent device "
                "path?)")
        unit_d = resp.get("unit")
        if unit_d is None:
            if resp.get("stop"):
                return done_units
            time.sleep(idle_sleep)
            continue
        unit = WorkUnit(unit_d["id"], unit_d["start"], unit_d["length"])
        try:
            hits = worker.process(unit)
        except Exception:
            # release the lease for another worker, then surface the bug
            try:
                client.call("fail", unit_id=unit.unit_id)
            except Exception:
                pass
            raise
        payload = [{"target": h.target_index, "cand": h.cand_index,
                    "plaintext": h.plaintext.hex()} for h in hits]
        resp = client.call("complete", unit_id=unit.unit_id, hits=payload,
                           worker_id=worker_id)
        done_units += 1
        if log and hits:
            log.info("hits reported", count=len(hits))
        if resp.get("stop"):
            return done_units
