"""Potfile: the cracked-results store (hash:plaintext append log).

Same contract as hashcat-class tools: a global file keyed by the target
hash text; plaintexts that aren't printable ASCII are stored as
$HEX[...] so the file stays line-oriented and lossless.
"""

from __future__ import annotations

import os
import re

_HEX_RE = re.compile(r"^\$HEX\[([0-9a-fA-F]*)\]$")


def encode_plain(plain: bytes) -> str:
    text = plain.decode("ascii", errors="replace")
    if plain and all(0x20 <= b < 0x7F for b in plain) and ":" not in text \
            and not _HEX_RE.match(text):
        return text
    return f"$HEX[{plain.hex()}]"


def decode_plain(text: str) -> bytes:
    m = _HEX_RE.match(text)
    if m:
        return bytes.fromhex(m.group(1))
    return text.encode("latin-1")


class Potfile:
    def __init__(self, path: str):
        self.path = path
        self._cracked: dict[str, bytes] = {}
        if os.path.exists(path):
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.rstrip("\n")
                    if not line or ":" not in line:
                        continue
                    key, _, plain = line.rpartition(":")
                    self._cracked[key] = decode_plain(plain)

    def __contains__(self, target_key: str) -> bool:
        return target_key in self._cracked

    def get(self, target_key: str):
        return self._cracked.get(target_key)

    def add(self, target_key: str, plain: bytes) -> None:
        if target_key in self._cracked:
            return
        self._cracked[target_key] = plain
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(f"{target_key}:{encode_plain(plain)}\n")
            fh.flush()
            os.fsync(fh.fileno())

    def __len__(self) -> int:
        return len(self._cracked)
