"""Session journal: checkpoint/resume for crack jobs.

Append-only JSONL (SURVEY.md section 5: "coordinator journals (unit
ledger, cracked set) to disk; resume = reload ledger, re-dispatch
incomplete units").  No device state is ever checkpointed -- units are
pure functions of their index range, so the journal is just:

  {"type": "header", "spec": {...}}          job identity (guards resume)
  {"type": "units", "intervals": [[s,e],..]} completed-coverage snapshot
  {"type": "hit", "target": t, "index": i, "plaintext": hex}
  {"type": "tune", "key": k, "record": {...}} tuning decision (batch
      autotune result) -- a resumed job reuses the recorded batch even
      when the machine's persistent tune cache is gone

Coverage is re-snapshotted (merged intervals) every `snapshot_every`
completions, so the file stays small and resume cost is O(intervals),
not O(units run).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional


@dataclasses.dataclass
class SessionState:
    spec: dict
    completed: list          # [(start, end), ...]
    hits: list               # [{"target": int, "index": int, "plaintext": str}]
    tuning: dict = dataclasses.field(default_factory=dict)  # key -> record


#: `dprf check` threads analyzer: the journal stream is owned by the
#: object and released by close() (called by the CLI's finally and the
#: coordinator shutdown path).
RELEASES = {
    "SessionJournal": {"_fh": "close"},
}


class SessionJournal:
    def __init__(self, path: str, snapshot_every: int = 64):
        self.path = path
        self.snapshot_every = snapshot_every
        self._since_snapshot = 0
        self._fh = None
        self._pending: list = []   # records queued before open()

    @property
    def telemetry_path(self) -> str:
        """Where this session's periodic telemetry snapshots live
        (telemetry.TelemetrySnapshotter) -- next to the journal, so a
        wedged run's post-mortem has both coverage AND fleet state."""
        from dprf_tpu.telemetry import telemetry_path
        return telemetry_path(self.path)

    @property
    def trace_path(self) -> str:
        """Where this session's lifecycle-span stream lives
        (telemetry/trace.py; exported with ``dprf trace export``) --
        third member of the journal family: coverage (.session),
        fleet state (.telemetry.jsonl), per-unit timeline
        (.trace.jsonl)."""
        from dprf_tpu.telemetry.trace import trace_path
        return trace_path(self.path)

    # -- writing ---------------------------------------------------------

    def open(self, spec: dict) -> None:
        fresh = not os.path.exists(self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        if fresh:
            self._emit({"type": "header", "spec": spec})
        for obj in self._pending:
            self._emit(obj)
        self._pending = []

    def _emit(self, obj: dict) -> None:
        self._fh.write(json.dumps(obj, separators=(",", ":")) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def record_units(self, intervals: list) -> None:
        self._since_snapshot += 1
        if self._since_snapshot >= self.snapshot_every:
            self._since_snapshot = 0
            self._emit({"type": "units",
                        "intervals": [[s, e] for s, e in intervals]})

    def snapshot(self, intervals: list) -> None:
        self._emit({"type": "units",
                    "intervals": [[s, e] for s, e in intervals]})

    def record_hit(self, target_index: int, cand_index: int,
                   plaintext: bytes) -> None:
        self._emit({"type": "hit", "target": target_index,
                    "index": cand_index, "plaintext": plaintext.hex()})

    def record_tuning(self, key: str, record: dict) -> None:
        """Journal a tuning decision (tune.make_key -> result record).
        The CLI resolves the batch BEFORE the journal is opened, so a
        pre-open record is buffered and flushed by open() -- right
        after the header, where resume reads it back."""
        obj = {"type": "tune", "key": key, "record": record}
        if self._fh is None:
            self._pending.append(obj)
        else:
            self._emit(obj)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    # -- reading ---------------------------------------------------------

    @staticmethod
    def load(path: str) -> Optional[SessionState]:
        if not os.path.exists(path):
            return None
        spec, completed, hits, tuning = {}, [], [], {}
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue   # torn tail write from a killed run
                t = obj.get("type")
                if t == "header":
                    spec = obj["spec"]
                elif t == "units":
                    completed = [(s, e) for s, e in obj["intervals"]]
                elif t == "hit":
                    hits.append(obj)
                elif t == "tune":
                    try:
                        tuning[str(obj["key"])] = dict(obj["record"])
                    except (KeyError, TypeError, ValueError):
                        continue    # malformed tune line: ignore
        return SessionState(spec=spec, completed=completed, hits=hits,
                            tuning=tuning)


def job_fingerprint(engine: str, attack: str, keyspace: int,
                    target_digests: list) -> str:
    """Stable identity of a job; resuming with a different job on the
    same session file is an error, not silent corruption.

    Digest ORDER matters: session hits are journaled by positional
    target index, so a reordered hashfile is a different job.
    """
    import hashlib
    h = hashlib.sha256()
    h.update(f"{engine}|{attack}|{keyspace}|".encode())
    for d in target_digests:
        h.update(d)
    return h.hexdigest()[:16]
