"""Session journal: checkpoint/resume for crack jobs.

Append-only JSONL (SURVEY.md section 5: "coordinator journals (unit
ledger, cracked set) to disk; resume = reload ledger, re-dispatch
incomplete units").  No device state is ever checkpointed -- units are
pure functions of their index range, so the journal is just:

  {"type": "header", "spec": {...}}          job identity (guards resume)
  {"type": "units", "intervals": [[s,e],..],
   "digest": "<hex>"}                        completed-coverage snapshot
      (digest: order-independent coverage digest of the intervals,
      ISSUE 19 -- resume and `dprf audit` must reproduce it)
  {"type": "hit", "target": t, "index": i, "plaintext": hex}
  {"type": "tune", "key": k, "record": {...}} tuning decision (batch
      autotune result) -- a resumed job reuses the recorded batch even
      when the machine's persistent tune cache is gone

Multi-tenant serve plane (ISSUE 8, tagging finalized in ISSUE 10): a
coordinator carries MANY jobs, so ``units`` and ``hit`` lines carry a
``"job": "<id>"`` tag -- new sessions tag EVERY line, including the
default job's (the header records ``default_job`` so load() folds its
lines back into the flat fields).  Untagged lines from pre-tagging
journals still read as the default job on restore; the dual write
path (untagged default + tagged tenants) is gone.  Scheduler-submitted
jobs add:

  {"type": "job", "id": j, "spec": {...}, "owner": o, "priority": p,
   "quota": q, "rate": r}                    a submitted job's identity
  {"type": "job_state", "id": j, "state": s} pause/cancel survives
                                             a coordinator restart
  {"type": "worker_health", "worker": w, "from": s, "to": s}
                                             fleet health transitions
                                             (ISSUE 10; diagnostics,
                                             never resume state)

Coverage is re-snapshotted (merged intervals) every `snapshot_every`
completions, so the file stays small and resume cost is O(intervals),
not O(units run).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional


@dataclasses.dataclass
class SessionState:
    spec: dict
    completed: list          # [(start, end), ...]
    hits: list               # [{"target": int, "index": int, "plaintext": str}]
    tuning: dict = dataclasses.field(default_factory=dict)  # key -> record
    #: scheduler-submitted jobs (multi-tenant serve plane), by id:
    #: {"spec", "owner", "priority", "quota", "rate", "state",
    #:  "completed", "hits"} -- the DEFAULT job stays in the flat
    #: fields above, exactly as single-job journals always read
    jobs: dict = dataclasses.field(default_factory=dict)
    #: worker_health transition records (ISSUE 10), in journal order:
    #: post-mortem material for `dprf report`, never resume state
    health_events: list = dataclasses.field(default_factory=list)
    #: kernel-profile capture summaries (ISSUE 15), in journal order:
    #: {"worker", "summary"} -- the `dprf report` kernel-profile
    #: section's input, never resume state
    profiles: list = dataclasses.field(default_factory=list)
    #: coverage digests (ISSUE 19), job id -> digest hex from the
    #: LAST units snapshot that carried one; the default job's lands
    #: under the header's default id.  Resume verifies the rebuilt
    #: ledger reproduces it (Dispatcher.from_completed expect_digest)
    #: and `dprf audit` checks it against the artifact replay.
    coverage: dict = dataclasses.field(default_factory=dict)
    #: the header's default job id -- the key the default job's
    #: coverage digest lands under
    default_job: str = "j0"


#: `dprf check` threads analyzer: the journal stream is owned by the
#: object and released by close() (called by the CLI's finally and the
#: coordinator shutdown path).
RELEASES = {
    "SessionJournal": {"_fh": "close"},
}


class SessionJournal:
    def __init__(self, path: str, snapshot_every: int = 64):
        self.path = path
        self.snapshot_every = snapshot_every
        self._since_snapshot: dict = {}   # job id (None=default) -> n
        self._fh = None
        self._pending: list = []   # records queued before open()

    @property
    def telemetry_path(self) -> str:
        """Where this session's periodic telemetry snapshots live
        (telemetry.TelemetrySnapshotter) -- next to the journal, so a
        wedged run's post-mortem has both coverage AND fleet state."""
        from dprf_tpu.telemetry import telemetry_path
        return telemetry_path(self.path)

    @property
    def trace_path(self) -> str:
        """Where this session's lifecycle-span stream lives
        (telemetry/trace.py; exported with ``dprf trace export``) --
        third member of the journal family: coverage (.session),
        fleet state (.telemetry.jsonl), per-unit timeline
        (.trace.jsonl)."""
        from dprf_tpu.telemetry.trace import trace_path
        return trace_path(self.path)

    @property
    def alerts_path(self) -> str:
        """Where this session's alert-event stream lives
        (telemetry/alerts.py) -- fourth member of the journal family:
        the pending/firing/resolved transitions `dprf report` folds
        into its health section."""
        from dprf_tpu.telemetry.alerts import alerts_path
        return alerts_path(self.path)

    # -- writing ---------------------------------------------------------

    def open(self, spec: dict, default_job: str = "j0") -> None:
        fresh = not os.path.exists(self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        if fresh:
            # default_job lets load() fold the (now always tagged)
            # default-job lines back into the flat resume fields
            self._emit({"type": "header", "spec": spec,
                        "default_job": default_job})
        for obj in self._pending:
            self._emit(obj)
        self._pending = []

    def _emit(self, obj: dict) -> None:
        self._fh.write(json.dumps(obj, separators=(",", ":")) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    @staticmethod
    def _tag(obj: dict, job: Optional[str]) -> dict:
        if job is not None:
            obj["job"] = job
        return obj

    def record_units(self, intervals: list,
                     job: Optional[str] = None,
                     digest: Optional[str] = None) -> None:
        # the snapshot counter is PER JOB: with one shared counter, a
        # job whose completions never land on the threshold crossing
        # would go unjournaled until shutdown -- a crash would lose
        # its whole coverage
        n = self._since_snapshot.get(job, 0) + 1
        if n >= self.snapshot_every:
            self._since_snapshot[job] = 0
            self.snapshot(intervals, job=job, digest=digest)
        else:
            self._since_snapshot[job] = n

    def snapshot(self, intervals: list,
                 job: Optional[str] = None,
                 digest: Optional[str] = None) -> None:
        obj = {"type": "units",
               "intervals": [[s, e] for s, e in intervals]}
        if digest:
            # coverage digest rides the snapshot it describes (ISSUE
            # 19): resume rebuilds the ledger from these intervals and
            # must reproduce the digest, or the journal is torn
            obj["digest"] = digest
        self._emit(self._tag(obj, job))

    def record_hit(self, target_index: int, cand_index: int,
                   plaintext: bytes, job: Optional[str] = None) -> None:
        self._emit(self._tag(
            {"type": "hit", "target": target_index,
             "index": cand_index, "plaintext": plaintext.hex()}, job))

    def record_job(self, job_id: str, spec: dict, owner: str = "?",
                   priority: int = 1, quota=None, rate=None) -> None:
        """Journal a scheduler-submitted job's identity so a
        coordinator restart can rebuild its ledger (jobs/build.py
        restore_jobs)."""
        self._emit({"type": "job", "id": job_id, "spec": spec,
                    "owner": owner, "priority": priority,
                    "quota": quota, "rate": rate})

    def record_job_state(self, job_id: str, state: str) -> None:
        """Journal a job-state transition (pause/cancel) -- an
        operator's cancel must survive the restart, or the job would
        silently resume sweeping."""
        self._emit({"type": "job_state", "id": job_id, "state": state})

    def record_worker_health(self, worker: str, frm: str, to: str,
                             ts=None, age_s=None) -> None:
        """Journal one fleet-health transition (ISSUE 10:
        healthy/degraded/missing/dead) -- the post-mortem record of
        when the fleet decayed, paired with the `.alerts.jsonl`
        stream.  Diagnostics only; load() never replays these into
        resume state."""
        obj = {"type": "worker_health", "worker": worker,
               "from": frm, "to": to}
        if ts is not None:
            obj["ts"] = ts
        if age_s is not None:
            obj["age_s"] = age_s
        self._emit(obj)

    def record_profile(self, worker: str, summary: dict) -> None:
        """Journal one kernel-profile capture summary (ISSUE 15: the
        sanitized result a worker pushed after an on-demand or
        alert-triggered window).  Diagnostics only -- `dprf report`
        renders these; load() never replays them into resume
        state."""
        self._emit({"type": "profile", "worker": worker,
                    "summary": summary})

    def record_job_gc(self, job_id: str) -> None:
        """Journal an age-based job reap (DPRF_JOB_TTL_S): a restart
        must not resurrect a job the GC already dropped -- load()
        removes the job's records when it sees this line."""
        self._emit({"type": "job_gc", "id": job_id})

    def record_tuning(self, key: str, record: dict) -> None:
        """Journal a tuning decision (tune.make_key -> result record).
        The CLI resolves the batch BEFORE the journal is opened, so a
        pre-open record is buffered and flushed by open() -- right
        after the header, where resume reads it back."""
        obj = {"type": "tune", "key": key, "record": record}
        if self._fh is None:
            self._pending.append(obj)
        else:
            self._emit(obj)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    # -- reading ---------------------------------------------------------

    @staticmethod
    def load(path: str) -> Optional[SessionState]:
        if not os.path.exists(path):
            return None
        spec, completed, hits, tuning = {}, [], [], {}
        jobs: dict = {}
        health_events: list = []
        profiles: list = []
        coverage: dict = {}
        # new sessions tag EVERY units/hit line (ISSUE 10); lines
        # tagged with the header's default job id fold back into the
        # flat fields, exactly where untagged (pre-tagging) lines of
        # old journals always landed
        default_jid = "j0"

        def job_rec(jid: str) -> dict:
            return jobs.setdefault(jid, {
                "spec": None, "owner": "?", "priority": 1,
                "quota": None, "rate": None, "state": None,
                "completed": [], "hits": [], "coverage_digest": None})

        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue   # torn tail write from a killed run
                t = obj.get("type")
                jid = obj.get("job")
                if t == "header":
                    spec = obj["spec"]
                    dj = obj.get("default_job")
                    if isinstance(dj, str) and dj:
                        default_jid = dj
                elif t == "units":
                    iv = [(s, e) for s, e in obj["intervals"]]
                    key = default_jid if jid is None else str(jid)
                    dg = obj.get("digest")
                    if not (isinstance(dg, str) and dg):
                        dg = None
                    if key == default_jid:
                        completed = iv
                    else:
                        r = job_rec(key)
                        r["completed"] = iv
                        r["coverage_digest"] = dg
                    if dg is not None:
                        # last snapshot wins, matching the intervals
                        coverage[key] = dg
                    else:
                        # a later digest-less snapshot supersedes the
                        # intervals the stale digest described
                        coverage.pop(key, None)
                elif t == "hit":
                    if jid is None or str(jid) == default_jid:
                        hits.append(obj)
                    else:
                        job_rec(str(jid))["hits"].append(obj)
                elif t == "worker_health":
                    health_events.append(obj)
                elif t == "profile":
                    if isinstance(obj.get("summary"), dict):
                        profiles.append(obj)
                elif t == "job":
                    try:
                        r = job_rec(str(obj["id"]))
                        r["spec"] = dict(obj["spec"])
                        r["owner"] = str(obj.get("owner", "?"))
                        r["priority"] = int(obj.get("priority") or 1)
                        r["quota"] = obj.get("quota")
                        r["rate"] = obj.get("rate")
                    except (KeyError, TypeError, ValueError):
                        continue    # malformed job line: ignore
                elif t == "job_state":
                    try:
                        job_rec(str(obj["id"]))["state"] = \
                            str(obj["state"])
                    except (KeyError, TypeError):
                        continue
                elif t == "job_gc":
                    # the scheduler reaped this job (age-based GC):
                    # drop everything journaled for it so restore
                    # does not resurrect it (ids are never reused)
                    jobs.pop(str(obj.get("id")), None)
                elif t == "tune":
                    try:
                        tuning[str(obj["key"])] = dict(obj["record"])
                    except (KeyError, TypeError, ValueError):
                        continue    # malformed tune line: ignore
        return SessionState(spec=spec, completed=completed, hits=hits,
                            tuning=tuning, jobs=jobs,
                            health_events=health_events,
                            profiles=profiles, coverage=coverage,
                            default_job=default_jid)


def job_fingerprint(engine: str, attack: str, keyspace: int,
                    target_digests: list) -> str:
    """Stable identity of a job; resuming with a different job on the
    same session file is an error, not silent corruption.

    Digest ORDER matters: session hits are journaled by positional
    target index, so a reordered hashfile is a different job.
    """
    import hashlib
    h = hashlib.sha256()
    h.update(f"{engine}|{attack}|{keyspace}|".encode())
    for d in target_digests:
        h.update(d)
    return h.hexdigest()[:16]
