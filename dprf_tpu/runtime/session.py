"""Session journal: checkpoint/resume for crack jobs.

Append-only JSONL (SURVEY.md section 5: "coordinator journals (unit
ledger, cracked set) to disk; resume = reload ledger, re-dispatch
incomplete units").  No device state is ever checkpointed -- units are
pure functions of their index range, so the journal is just:

  {"type": "header", "spec": {...}}          job identity (guards resume)
  {"type": "units", "intervals": [[s,e],..]} completed-coverage snapshot
  {"type": "hit", "target": t, "index": i, "plaintext": hex}
  {"type": "tune", "key": k, "record": {...}} tuning decision (batch
      autotune result) -- a resumed job reuses the recorded batch even
      when the machine's persistent tune cache is gone

Multi-tenant serve plane (ISSUE 8): a coordinator carries MANY jobs,
so the journal grew per-job records.  ``units`` and ``hit`` lines may
carry a ``"job": "<id>"`` tag; untagged lines belong to the DEFAULT
job (the one in the header) -- full backward compatibility with
single-job journals.  Scheduler-submitted jobs add:

  {"type": "job", "id": j, "spec": {...}, "owner": o, "priority": p,
   "quota": q, "rate": r}                    a submitted job's identity
  {"type": "job_state", "id": j, "state": s} pause/cancel survives
                                             a coordinator restart

Coverage is re-snapshotted (merged intervals) every `snapshot_every`
completions, so the file stays small and resume cost is O(intervals),
not O(units run).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional


@dataclasses.dataclass
class SessionState:
    spec: dict
    completed: list          # [(start, end), ...]
    hits: list               # [{"target": int, "index": int, "plaintext": str}]
    tuning: dict = dataclasses.field(default_factory=dict)  # key -> record
    #: scheduler-submitted jobs (multi-tenant serve plane), by id:
    #: {"spec", "owner", "priority", "quota", "rate", "state",
    #:  "completed", "hits"} -- the DEFAULT job stays in the flat
    #: fields above, exactly as single-job journals always read
    jobs: dict = dataclasses.field(default_factory=dict)


#: `dprf check` threads analyzer: the journal stream is owned by the
#: object and released by close() (called by the CLI's finally and the
#: coordinator shutdown path).
RELEASES = {
    "SessionJournal": {"_fh": "close"},
}


class SessionJournal:
    def __init__(self, path: str, snapshot_every: int = 64):
        self.path = path
        self.snapshot_every = snapshot_every
        self._since_snapshot: dict = {}   # job id (None=default) -> n
        self._fh = None
        self._pending: list = []   # records queued before open()

    @property
    def telemetry_path(self) -> str:
        """Where this session's periodic telemetry snapshots live
        (telemetry.TelemetrySnapshotter) -- next to the journal, so a
        wedged run's post-mortem has both coverage AND fleet state."""
        from dprf_tpu.telemetry import telemetry_path
        return telemetry_path(self.path)

    @property
    def trace_path(self) -> str:
        """Where this session's lifecycle-span stream lives
        (telemetry/trace.py; exported with ``dprf trace export``) --
        third member of the journal family: coverage (.session),
        fleet state (.telemetry.jsonl), per-unit timeline
        (.trace.jsonl)."""
        from dprf_tpu.telemetry.trace import trace_path
        return trace_path(self.path)

    # -- writing ---------------------------------------------------------

    def open(self, spec: dict) -> None:
        fresh = not os.path.exists(self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        if fresh:
            self._emit({"type": "header", "spec": spec})
        for obj in self._pending:
            self._emit(obj)
        self._pending = []

    def _emit(self, obj: dict) -> None:
        self._fh.write(json.dumps(obj, separators=(",", ":")) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    @staticmethod
    def _tag(obj: dict, job: Optional[str]) -> dict:
        if job is not None:
            obj["job"] = job
        return obj

    def record_units(self, intervals: list,
                     job: Optional[str] = None) -> None:
        # the snapshot counter is PER JOB: with one shared counter, a
        # job whose completions never land on the threshold crossing
        # would go unjournaled until shutdown -- a crash would lose
        # its whole coverage
        n = self._since_snapshot.get(job, 0) + 1
        if n >= self.snapshot_every:
            self._since_snapshot[job] = 0
            self.snapshot(intervals, job=job)
        else:
            self._since_snapshot[job] = n

    def snapshot(self, intervals: list,
                 job: Optional[str] = None) -> None:
        self._emit(self._tag(
            {"type": "units",
             "intervals": [[s, e] for s, e in intervals]}, job))

    def record_hit(self, target_index: int, cand_index: int,
                   plaintext: bytes, job: Optional[str] = None) -> None:
        self._emit(self._tag(
            {"type": "hit", "target": target_index,
             "index": cand_index, "plaintext": plaintext.hex()}, job))

    def record_job(self, job_id: str, spec: dict, owner: str = "?",
                   priority: int = 1, quota=None, rate=None) -> None:
        """Journal a scheduler-submitted job's identity so a
        coordinator restart can rebuild its ledger (jobs/build.py
        restore_jobs)."""
        self._emit({"type": "job", "id": job_id, "spec": spec,
                    "owner": owner, "priority": priority,
                    "quota": quota, "rate": rate})

    def record_job_state(self, job_id: str, state: str) -> None:
        """Journal a job-state transition (pause/cancel) -- an
        operator's cancel must survive the restart, or the job would
        silently resume sweeping."""
        self._emit({"type": "job_state", "id": job_id, "state": state})

    def record_job_gc(self, job_id: str) -> None:
        """Journal an age-based job reap (DPRF_JOB_TTL_S): a restart
        must not resurrect a job the GC already dropped -- load()
        removes the job's records when it sees this line."""
        self._emit({"type": "job_gc", "id": job_id})

    def record_tuning(self, key: str, record: dict) -> None:
        """Journal a tuning decision (tune.make_key -> result record).
        The CLI resolves the batch BEFORE the journal is opened, so a
        pre-open record is buffered and flushed by open() -- right
        after the header, where resume reads it back."""
        obj = {"type": "tune", "key": key, "record": record}
        if self._fh is None:
            self._pending.append(obj)
        else:
            self._emit(obj)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    # -- reading ---------------------------------------------------------

    @staticmethod
    def load(path: str) -> Optional[SessionState]:
        if not os.path.exists(path):
            return None
        spec, completed, hits, tuning = {}, [], [], {}
        jobs: dict = {}

        def job_rec(jid: str) -> dict:
            return jobs.setdefault(jid, {
                "spec": None, "owner": "?", "priority": 1,
                "quota": None, "rate": None, "state": None,
                "completed": [], "hits": []})

        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue   # torn tail write from a killed run
                t = obj.get("type")
                jid = obj.get("job")
                if t == "header":
                    spec = obj["spec"]
                elif t == "units":
                    iv = [(s, e) for s, e in obj["intervals"]]
                    if jid is None:
                        completed = iv
                    else:
                        job_rec(str(jid))["completed"] = iv
                elif t == "hit":
                    if jid is None:
                        hits.append(obj)
                    else:
                        job_rec(str(jid))["hits"].append(obj)
                elif t == "job":
                    try:
                        r = job_rec(str(obj["id"]))
                        r["spec"] = dict(obj["spec"])
                        r["owner"] = str(obj.get("owner", "?"))
                        r["priority"] = int(obj.get("priority") or 1)
                        r["quota"] = obj.get("quota")
                        r["rate"] = obj.get("rate")
                    except (KeyError, TypeError, ValueError):
                        continue    # malformed job line: ignore
                elif t == "job_state":
                    try:
                        job_rec(str(obj["id"]))["state"] = \
                            str(obj["state"])
                    except (KeyError, TypeError):
                        continue
                elif t == "job_gc":
                    # the scheduler reaped this job (age-based GC):
                    # drop everything journaled for it so restore
                    # does not resurrect it (ids are never reused)
                    jobs.pop(str(obj.get("id")), None)
                elif t == "tune":
                    try:
                        tuning[str(obj["key"])] = dict(obj["record"])
                    except (KeyError, TypeError, ValueError):
                        continue    # malformed tune line: ignore
        return SessionState(spec=spec, completed=completed, hits=hits,
                            tuning=tuning, jobs=jobs)


def job_fingerprint(engine: str, attack: str, keyspace: int,
                    target_digests: list) -> str:
    """Stable identity of a job; resuming with a different job on the
    same session file is an error, not silent corruption.

    Digest ORDER matters: session hits are journaled by positional
    target index, so a reordered hashfile is a different job.
    """
    import hashlib
    h = hashlib.sha256()
    h.update(f"{engine}|{attack}|{keyspace}|".encode())
    for d in target_digests:
        h.update(d)
    return h.hexdigest()[:16]
