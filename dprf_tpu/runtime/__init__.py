from dprf_tpu.runtime.workunit import WorkUnit  # noqa: F401
from dprf_tpu.runtime.dispatcher import Dispatcher  # noqa: F401
from dprf_tpu.runtime.coordinator import Coordinator, JobSpec  # noqa: F401
