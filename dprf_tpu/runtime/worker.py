"""Workers: fetch WorkUnit -> generate candidates -> hash -> report hits.

DeviceMaskWorker is the TPU path: one fused jitted step per job
(ops/pipeline.py), asynchronously dispatched per batch so the device
pipeline never drains; results are resolved after the whole unit is
queued.  Only hit buffers cross back to the host.

CpuWorker is the reference path (`--device=cpu`): oracle engines over
host-materialized candidates.  It is also the fallback that rescans a
batch exactly if a device hit buffer ever overflows.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.generators.base import CandidateGenerator
from dprf_tpu.runtime.workunit import WorkUnit


@dataclasses.dataclass(frozen=True)
class Hit:
    target_index: int      # position in the job's target list
    cand_index: int        # global keyspace index
    plaintext: bytes


class CpuWorker:
    """Oracle-engine worker; handles salted and unsalted engines."""

    def __init__(self, engine: HashEngine, gen: CandidateGenerator,
                 targets: Sequence[Target], chunk: int = 2048):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.chunk = chunk
        self._digest_map = {t.digest: i for i, t in enumerate(self.targets)}

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for start in range(unit.start, unit.end, self.chunk):
            n = min(self.chunk, unit.end - start)
            cands = self.gen.candidates(start, n)
            if self.engine.salted:
                for ti, t in enumerate(self.targets):
                    for j, d in enumerate(self.engine.hash_batch(
                            cands, params=t.params)):
                        if d == t.digest:
                            hits.append(Hit(ti, start + j, cands[j]))
            else:
                for j, d in enumerate(self.engine.hash_batch(cands)):
                    ti = self._digest_map.get(d)
                    if ti is not None:
                        hits.append(Hit(ti, start + j, cands[j]))
        return hits


class MaskWorkerBase:
    """Shared machinery for fused-pipeline mask workers.

    Subclasses set ``self.step`` (the jitted crack step) and
    ``self.stride`` (keyspace indices consumed per step call) in
    __init__ after calling ``_setup_targets``, and implement
    ``_batch_hits`` to decode one step result.
    """

    def _setup_targets(self, engine, gen, targets: Sequence[Target],
                       hit_capacity: int, oracle: Optional[HashEngine]):
        from dprf_tpu.ops import compare as cmp_ops
        from dprf_tpu.ops.pipeline import target_words

        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        digests = [t.digest for t in self.targets]
        self.multi = len(digests) > 1
        if self.multi:
            table = cmp_ops.make_target_table(
                digests, little_endian=engine.little_endian)
            self._order = table.order
            return table
        self._order = np.zeros(1, dtype=np.int64)
        return target_words(digests[0], engine.little_endian)

    def process(self, unit: WorkUnit) -> list[Hit]:
        import jax.numpy as jnp
        queued = []
        for bstart in range(unit.start, unit.end, self.stride):
            n_valid = min(self.stride, unit.end - bstart)
            base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
            queued.append((bstart, self.step(base, jnp.int32(n_valid))))
        hits: list[Hit] = []
        for bstart, result in queued:
            hits.extend(self._batch_hits(bstart, result, unit))
        return hits

    def _decode_lanes(self, bstart: int, lanes_np, tpos_np) -> list[Hit]:
        """Hit-buffer arrays -> Hit records (lane -1 = unused slot)."""
        hits = []
        for lane, tp in zip(lanes_np, tpos_np):
            if lane < 0:
                continue
            gidx = bstart + int(lane)
            ti = int(self._order[int(tp)]) if self.multi else 0
            hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits

    def _rescan(self, bstart: int, unit: WorkUnit) -> list[Hit]:
        """Exact host rescan of one overflowed batch (pathological case:
        more hits in a batch than the device hit buffer holds)."""
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        end = min(bstart + self.stride, unit.end)
        sub = WorkUnit(-1, bstart, end - bstart)
        return CpuWorker(self.oracle, self.gen, self.targets).process(sub)


class DeviceMaskWorker(MaskWorkerBase):
    """Fused-pipeline worker for mask attacks on fast (unsalted) hashes."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.pipeline import make_mask_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity, oracle)
        self.batch = self.stride = batch
        self.step = make_mask_crack_step(
            engine, gen, tgt, batch, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))

    def _batch_hits(self, bstart: int, result, unit: WorkUnit) -> list[Hit]:
        count, lanes, tpos = result
        count = int(count)
        if count == 0:
            return []
        if count > self.hit_capacity:
            return self._rescan(bstart, unit)
        return self._decode_lanes(bstart, np.asarray(lanes), np.asarray(tpos))
