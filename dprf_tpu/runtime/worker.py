"""Workers: fetch WorkUnit -> generate candidates -> hash -> report hits.

DeviceMaskWorker is the TPU path: one fused jitted step per job
(ops/pipeline.py), asynchronously dispatched per batch so the device
pipeline never drains; results are resolved after the whole unit is
queued.  Only hit buffers cross back to the host.

CpuWorker is the reference path (`--device=cpu`): oracle engines over
host-materialized candidates.  It is also the fallback that rescans a
batch exactly if a device hit buffer ever overflows.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.generators.base import CandidateGenerator
from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.telemetry import coverage


@dataclasses.dataclass(frozen=True)
class Hit:
    target_index: int      # position in the job's target list
    cand_index: int        # global keyspace index
    plaintext: bytes


class PendingUnit:
    """A WorkUnit whose device work is fully enqueued but not yet
    resolved.  The unit-level flag (device-accumulated hit indicator)
    is already on its way back to the host; ``resolve()`` blocks on it
    and only fetches the queued hit buffers when it is nonzero.

    Callers that hold a PendingUnit while submitting the NEXT unit
    overlap the flag's link round trip with that unit's compute -- the
    difference between paying ~RTT per unit and paying ~max(compute,
    RTT) (see Coordinator.run / bench.run_config)."""

    __slots__ = ("worker", "unit", "queued", "flag")

    def __init__(self, worker, unit, queued, flag):
        self.worker = worker
        self.unit = unit
        self.queued = queued
        self.flag = flag

    def resolve(self) -> list["Hit"]:
        if self.flag is None or int(self.flag) == 0:
            return []
        hits: list[Hit] = []
        for kind, start, result in self.queued:
            hits.extend(self.worker._decode_queued(kind, start, result,
                                                   self.unit))
        return hits


def submit_or_process(worker, unit) -> "PendingUnit":
    """Uniform pipelining entry.  A worker is submitted asynchronously
    ONLY when its ``process`` is one of the submit-based
    implementations (marked ``_submit_based``): a subclass that
    overrides ``process`` with its own sweep logic (per-salt-block
    steps, per-target steps, sharded super-batches, chunked bcrypt,
    CpuWorker...) must run through that override, not through an
    inherited ``submit`` that would bypass it."""
    if getattr(type(worker).process, "_submit_based", False):
        return worker.submit(unit)
    return _ResolvedUnit(worker.process(unit))


class _ResolvedUnit:
    __slots__ = ("hits",)

    def __init__(self, hits):
        self.hits = hits

    def resolve(self):
        return self.hits


#: `dprf check` retrace analyzer: the per-batch device dispatch loop.
#: Everything submit() enqueues rides the device stream; a host sync
#: or a retrace inside it stalls every unit of every job.
HOT_PATHS = ("MaskWorkerBase.submit",)

#: `dprf check` retrace analyzer: the SAMPLED perf probe is ALLOWED
#: to sync inside hot loops -- forced block_until_ready boundaries
#: are how per-phase attribution stays honest, and sampling
#: (DPRF_PERF_SAMPLE) keeps them off the steady-state path.  An
#: explicit declaration, not a suppression comment: stale entries
#: are findings.
PERF_PROBE = ("dprf_tpu.telemetry.perf.probe_pending",)

#: env override for the submit-ahead depth both pipelined loops run at
PIPELINE_DEPTH_ENV = "DPRF_PIPELINE_DEPTH"


def pipeline_depth(default: int = 2) -> int:
    """The depth CAP shared by Coordinator.run and rpc.worker_loop --
    the ONE resolution site for the knob.  ``DPRF_PIPELINE_DEPTH``
    overrides (1 = serial fallback: no overlap, no async completion);
    clamped to [1, 64].  The local loop runs AT this depth; the remote
    loop ADAPTS its live depth to the measured RTT / unit-seconds
    ratio below it (AdaptiveDepth) -- the knob bounds how many leases
    one worker may queue, it no longer pins the working depth."""
    from dprf_tpu.utils import env as envreg
    return max(1, min(envreg.get_int(PIPELINE_DEPTH_ENV, int(default)),
                      64))


class AdaptiveDepth:
    """RTT-adaptive submit-ahead depth for the remote worker loop.

    The right depth is a physics answer, not a config answer: to keep
    the device stream full, a worker must hold enough units that the
    lease/complete round trips hide behind compute -- about
    ``1 + rtt/unit_seconds`` units.  A static depth (the old
    ``DPRF_PIPELINE_DEPTH`` semantics) over-leases on fat links
    (units sit idle in one worker's queue while another starves) and
    under-leases on thin ones.  This tracker keeps EWMAs of both
    quantities (same smoothing idea as tune.AdaptiveUnitSizer) and
    derives the live depth each loop iteration; the env knob / CLI
    flag remains as the CAP.

    Until both signals exist the depth stays at ``start`` (2: enough
    to overlap one round trip -- the pre-adaptive default)."""

    __slots__ = ("cap", "depth", "alpha", "_rtt", "_unit")

    def __init__(self, cap: int, start: int = 2, alpha: float = 0.3):
        self.cap = max(1, int(cap))
        self.depth = max(1, min(int(start), self.cap))
        self.alpha = alpha
        self._rtt: Optional[float] = None
        self._unit: Optional[float] = None

    def _ewma(self, cur: Optional[float], sample: float) -> float:
        if cur is None:
            return sample
        return cur + self.alpha * (sample - cur)

    def observe_rtt(self, seconds: float) -> None:
        if seconds > 0:
            self._rtt = self._ewma(self._rtt, seconds)

    def observe_unit(self, seconds: float) -> None:
        if seconds > 0:
            self._unit = self._ewma(self._unit, seconds)

    def update(self) -> int:
        """Recompute and return the live depth (monotonic per call,
        moves at most one step at a time: a single glitched sample
        must not swing a fleet's lease holdings)."""
        if self._rtt is not None and self._unit is not None:
            want = 1 + int(-(-self._rtt // max(self._unit, 1e-9)))
            want = max(1, min(want, self.cap))
            if want > self.depth:
                self.depth += 1
            elif want < self.depth:
                self.depth -= 1
        return self.depth


class UnitPipeline:
    """Bounded submit-ahead FIFO of (unit, PendingUnit): device work
    for every queued unit is already dispatched when it enters, so
    resolving the head overlaps its readback latency with the tail's
    compute.  The ONE pipelining implementation shared by the local
    Coordinator.run and the remote rpc.worker_loop -- over the RPC
    boundary the same overlap additionally hides the lease/complete
    round trips behind the device stream."""

    __slots__ = ("worker", "depth", "_q")

    def __init__(self, worker, depth: int):
        self.worker = worker
        self.depth = max(1, int(depth))
        self._q: list = []

    def __len__(self) -> int:
        return len(self._q)

    @property
    def full(self) -> bool:
        return len(self._q) >= self.depth

    def submit(self, unit, meta=None, worker=None, probe=None) -> None:
        """Dispatch the unit's device work now (enqueue-only for
        submit-based workers; a serial worker's process runs here) and
        queue it for a later resolve.  ``worker`` overrides the
        pipeline's default for THIS unit -- a multi-job worker loop
        routes each unit to its job's worker while sharing one
        submit-ahead queue.

        ``probe`` = (PerfSampler, trace id) routes THIS unit through
        the sampled per-phase sweep (telemetry/perf.py): serial and
        synced, so the phase breakdown is honest; the resolved entry
        carries its phase spans and the pre-allocated sweep span id.
        The submit timestamp is taken BEFORE the dispatch so a
        serial/probed unit's submit-to-resolve time covers its real
        work, not just queue wait."""
        import time
        t0 = time.monotonic()
        w = worker or self.worker
        if probe is not None:
            from dprf_tpu.telemetry.perf import (drain_backlog,
                                                 probe_pending)
            # the probe's first sync must measure ITS unit, not the
            # queued units' device backlog: wait for the stream to
            # drain first (the probe serializes anyway -- this only
            # moves the wait out of the attributed phases)
            drain_backlog(self._q)
            pending = probe_pending(w, unit, probe[0], trace=probe[1])
        else:
            pending = submit_or_process(w, unit)
        self._q.append((unit, pending, t0, meta))

    def pop(self):
        """Oldest (unit, pending, t_submit, meta); caller resolves."""
        return self._q.pop(0)

    def drain(self) -> list:
        """Abandon every queued entry (failure path): entries oldest
        first; in-flight device work is never resolved."""
        entries = self._q[:]
        self._q.clear()
        return entries


def word_cover_range(unit: WorkUnit, n_rules: int) -> tuple:
    """Covering word range [w_start, w_end) of a keyspace-index unit
    (index = word * n_rules + rule; ceil on the end)."""
    return unit.start // n_rules, -(-unit.end // n_rules)


def wordlist_lane_to_gidx(lane: int, ws: int, word_batch: int,
                          n_rules: int) -> int:
    """Rule-major flat step lane (r*B + b) -> global keyspace index for
    a step whose word window starts at ws.  Single source of truth for
    the decode every wordlist worker uses."""
    r, b = divmod(lane, word_batch)
    return (ws + b) * n_rules + r


class CpuWorker:
    """Oracle-engine worker; handles salted and unsalted engines."""

    def __init__(self, engine: HashEngine, gen: CandidateGenerator,
                 targets: Sequence[Target], chunk: int = 2048):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.chunk = chunk
        self._digest_map = {t.digest: i for i, t in enumerate(self.targets)}

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for start in range(unit.start, unit.end, self.chunk):
            n = min(self.chunk, unit.end - start)
            # Rule-based generators may reject candidates (None): those
            # keyspace indices are holes — never hashed.
            pairs = [(start + j, c)
                     for j, c in enumerate(self.gen.candidates(start, n))
                     if c is not None]
            if not pairs:
                continue
            cands = [c for _, c in pairs]
            if self.engine.salted:
                for ti, t in enumerate(self.targets):
                    for (gidx, cand), d in zip(pairs, self.engine.hash_batch(
                            cands, params=t.params)):
                        if d == t.digest:
                            hits.append(Hit(ti, gidx, cand))
            else:
                for (gidx, cand), d in zip(pairs,
                                           self.engine.hash_batch(cands)):
                    ti = self._digest_map.get(d)
                    if ti is not None:
                        hits.append(Hit(ti, gidx, cand))
        return hits

    #: host loop, no device stream to overlap -- pipelining a CpuWorker
    #: just runs process() at submit time (tools/check_worker_contract)
    process._serial_only = True


class _MultiPending:
    """Pending handle over several sub-unit pendings (one per
    contiguous index run of a rank-ordered unit); resolve() drains
    them oldest-first, so device readbacks overlap later runs'
    compute exactly like the unit pipeline does across units."""

    __slots__ = ("_pendings",)

    def __init__(self, pendings):
        self._pendings = pendings

    def resolve(self) -> list["Hit"]:
        hits: list[Hit] = []
        for p in self._pendings:
            hits.extend(p.resolve())
        return hits


class OrderedWorker:
    """Rank-space adapter over any worker: the dispatcher's unit spans
    are RANKS (generators/order.py); this wrapper decodes each leased
    span into its contiguous index runs and submits every run through
    the wrapped worker's unchanged index-space path -- the device
    pipeline (fused steps, sharded supersteps, Pallas kernels) never
    sees a rank.  Runs are submitted in rank order, so the most
    probable candidates are swept (and their hits surface) first even
    within one unit.  Sub-units reuse the parent's unit id and job id:
    coverage accounting stays per leased unit, and every Hit carries
    its index-space cand_index exactly as before."""

    def __init__(self, worker, order):
        self._worker = worker
        #: the job's rank<->index bijection; the coordinator's rescan
        #: path (Coordinator._finish_unit) re-wraps its CPU oracle
        #: worker with this same object
        self.order = order

    def submit(self, unit: WorkUnit) -> "_MultiPending":
        subs = []
        for s, e in self.order.index_spans(unit.start, unit.end):
            subs.append(submit_or_process(
                self._worker, WorkUnit(unit.unit_id, s, e - s,
                                       job_id=unit.job_id)))
        return _MultiPending(subs)

    def process(self, unit: WorkUnit) -> list["Hit"]:
        return self.submit(unit).resolve()

    process._submit_based = True

    def __getattr__(self, name):
        # everything else (gen, targets, warmup_async, engine,
        # compile_seconds...) is the wrapped worker's business
        return getattr(self._worker, name)


class MaskWorkerBase:
    """Shared machinery for fused-pipeline mask workers.

    Subclasses set ``self.step`` (the jitted crack step) and
    ``self.stride`` (keyspace indices consumed per step call) in
    __init__ after calling ``_setup_targets``, and implement
    ``_batch_hits`` to decode one step result.
    """

    #: attack shape this worker family's program registry records
    #: carry (telemetry/programs.py); wordlist/combinator subclasses
    #: override
    ATTACK = "mask"

    def _setup_targets(self, engine, gen, targets: Sequence[Target],
                       hit_capacity: int, oracle: Optional[HashEngine],
                       probe_ok: bool = False):
        from dprf_tpu.ops import compare as cmp_ops
        from dprf_tpu.ops.pipeline import target_words

        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        digests = [t.digest for t in self.targets]
        self.multi = len(digests) > 1
        if self.multi and probe_ok:
            ptable = self._setup_probe(digests)
            if ptable is not None:
                return ptable
        if self.multi:
            table = cmp_ops.make_target_table(
                digests, little_endian=engine.little_endian)
            self._order = table.order
            return table
        self._order = np.zeros(1, dtype=np.int64)
        return target_words(digests[0], engine.little_endian)

    def _setup_probe(self, digests: list):
        """Bulk target lists (>= DPRF_TARGETS_PROBE_MIN digests) get
        the O(1)-per-candidate probe table (dprf_tpu/targets/) instead
        of the replicated compare table; a build failure falls back to
        the replicated path loudly.  Only workers whose step builder
        understands a ProbeTable pass probe_ok=True."""
        from dprf_tpu.targets import probe as probe_mod
        from dprf_tpu.utils.logging import DEFAULT as log
        if not probe_mod.probe_eligible(self.targets, self.engine):
            return None
        try:
            ptable = probe_mod.build_probe_table(
                digests, little_endian=self.engine.little_endian,
                log=log)
        except Exception as e:    # noqa: BLE001 -- degrade, not die
            log.warn("probe-table build failed; falling back to the "
                     "replicated compare table",
                     targets=len(digests), error=str(e))
            return None
        if ptable.mode == probe_mod.MODE_HOST_VERIFY \
                and self.oracle is None:
            # every survivor needs a host hash in this layout; without
            # an oracle the worker could never confirm a single hit
            log.warn("host-verify probe table needs an oracle engine; "
                     "falling back to the replicated compare table",
                     targets=len(digests))
            return None
        self._digest_map = {t.digest: i
                            for i, t in enumerate(self.targets)}
        self._order = ptable.order
        # distinct program-registry label: the probe step's roofline
        # is a different program from the replicated-compare step's
        self.ATTACK = self.ATTACK + "+probe"
        return ptable

    def warmup_args(self) -> tuple:
        """The step arguments a zero-work warmup dispatch uses -- same
        shapes/dtypes as production dispatches, so the compiled (and
        persistently cached) program is the one real units run."""
        import jax.numpy as jnp
        return (jnp.asarray(self.gen.digits(0), dtype=jnp.int32),
                jnp.int32(0))

    def warmup(self) -> None:
        """Force the step's compile now (jit is lazy).  The engine
        factory calls this for Pallas workers so a Mosaic/XLA compile
        failure surfaces at worker construction -- where it can fall
        back to another path -- instead of mid-job."""
        args = self.warmup_args()   # built OUTSIDE the observer: arg
        # materialization can write tiny cache entries of its own
        self._timed_warmup(args)

    def _timed_warmup(self, args: tuple) -> None:
        """One observed warmup dispatch: times the compile, classifies
        it against the persistent compilation cache (hit/miss/off),
        and publishes dprf_compile_seconds{engine,cache} (the dominant
        fixed cost of a job; a scrape that shows minutes here explains
        a 'stalled' fleet that is really compiling)."""
        import time

        from dprf_tpu.compilecache import compile_observer
        from dprf_tpu.utils.sync import hard_sync
        t0 = time.perf_counter()
        # hard_sync (not block_until_ready) so a RUNTIME kernel fault
        # also surfaces here, not just a compile failure -- over the
        # axon tunnel block_until_ready returns at enqueue and the
        # fault would land on the first real batch instead
        with compile_observer(getattr(self.engine, "name",
                                      "unknown")) as obs:
            hard_sync(self.step(*args))
        #: warmup/compile wall time; tune/autotuner.sweep folds it into
        #: a rung's fixed cost (covers workers warmed before the
        #: sweep's own clock started)
        self.compile_seconds = time.perf_counter() - t0
        #: "hit" | "miss" | "off": whether the persistent compilation
        #: cache served this step (bench and prewarm report it)
        self.compile_cache = obs.cache
        self._warmed = True
        # register the compiled program for XLA-derived introspection
        # (telemetry/programs.py).  Registration only -- the analysis
        # (a cache-served recompile + cost/memory read) is deferred to
        # an off-hot-path consumer (warmup_async's background thread,
        # the heartbeat loop, tune, bench, `dprf programs`).
        self._register_program(args)

    def _register_program(self, args: tuple, compiled=None,
                          lowered=None) -> None:
        from dprf_tpu.telemetry import programs as programs_mod
        programs_mod.register_program(
            getattr(self.engine, "name", "unknown"), self.ATTACK,
            int(getattr(self, "stride", 0) or 0), step=self.step,
            args=args, compiled=compiled, lowered=lowered)

    def aot_compile(self) -> None:
        """Compile the step WITHOUT dispatching (``dprf prewarm``):
        lower + compile populates the persistent compilation cache
        with exactly the executable a same-shape warmup dispatch
        loads.  Steps that cannot AOT-lower fall back to a plain
        warmup dispatch (still zero keyspace work: n_valid = 0).

        Tracing/lowering happens OUTSIDE the observer: it is pure
        Python the cache can never serve, and folding it in would
        understate the cache's effect on the XLA compile itself
        (``xla_compile_seconds``, the >=5x acceptance quantity)."""
        import time
        args = self.warmup_args()
        lower = getattr(self.step, "lower", None)
        if lower is None:
            return self.warmup()
        from dprf_tpu.compilecache import compile_observer
        t0 = time.perf_counter()
        lowered = lower(*args)
        trace_s = time.perf_counter() - t0
        with compile_observer(getattr(self.engine, "name",
                                      "unknown")) as obs:
            compiled = lowered.compile()
        #: the XLA compile alone -- what the persistent cache
        #: eliminates (trace/lower cost is irreducible host Python)
        self.xla_compile_seconds = obs.seconds
        self.compile_seconds = trace_s + obs.seconds
        self.compile_cache = obs.cache
        # the Compiled object is in hand here: analysis is a ~ms read,
        # so prewarm's program table fills with no extra compile; the
        # Lowered rides along for the real module fingerprint
        self._register_program(args, compiled=compiled,
                               lowered=lowered)

    def warmup_async(self):
        """Overlapped warmup: start warmup() on a background thread so
        the step compile runs while the caller finishes job setup
        (potfile preload, session restore, first leases).  Join with
        ``ensure_warm()`` before the first step dispatch -- cold-start
        wall time becomes max(compile, setup) instead of their sum.
        DPRF_ASYNC_WARMUP=0 degrades to a synchronous warmup."""
        import threading

        from dprf_tpu.utils import env as envreg
        if getattr(self, "_warmed", False) or \
                getattr(self, "_warm_thread", None) is not None:
            return self
        if not envreg.get_bool("DPRF_ASYNC_WARMUP"):
            self.warmup()
            return self
        self._warm_error = None

        def _run():
            try:
                self.warmup()
            except BaseException as e:   # noqa: BLE001 -- re-raised
                # by ensure_warm on the caller's thread
                self._warm_error = e
                return
            # deferred program analysis on the SAME background thread:
            # the recompile it triggers is persistent-cache-served (the
            # warmup above just populated the cache) and overlaps job
            # setup exactly like the warmup did.  Best-effort: the
            # analyzed roofline is observability, never job state.
            try:
                from dprf_tpu.telemetry import programs as programs_mod
                programs_mod.analyze_pending()
            except Exception:   # noqa: BLE001
                pass

        t = threading.Thread(target=_run, name="dprf-warmup",
                             daemon=True)
        self._warm_thread = t
        t.start()
        return self

    def ensure_warm(self) -> None:
        """Join an in-flight warmup_async(); re-raises its failure on
        the calling thread (the same place a synchronous warmup would
        have raised).  No-op when warmup never ran or already ran."""
        t = getattr(self, "_warm_thread", None)
        if t is None:
            return
        t.join()
        self._warm_thread = None
        err = getattr(self, "_warm_error", None)
        if err is not None:
            self._warm_error = None
            raise err

    def _batch_flag(self, result):
        """Scalar that is nonzero iff this batch needs host attention
        (hits or overflow).  Element 0 of every step result is its hit
        count; subclasses with extra buffers override."""
        return result[0]

    #: largest number of batches fused into one super-step dispatch
    #: and the smallest chunk worth a dedicated compile.  Power-of-two
    #: inner sizes bound the compile cache at log2(SUPER_CAP) entries.
    SUPER_CAP = 256
    SUPER_MIN = 8

    #: fusion mechanism for multi-batch units.  "scan" wraps the step
    #: in ops/superstep.make_super_step (lax.scan with stacked
    #: outputs) -- right for the XLA-pipeline steps, whose bodies are
    #: plain jnp ops.  "wide" rebuilds the worker's own step at
    #: inner*stride lanes via _make_step: the SAME single-pallas_call
    #: program shape as a plain batch, just a longer (sequential) grid
    #: -- the only fused shape proven on the axon TPU backend, where a
    #: scan-wrapped pallas_call wedged the remote compile helper
    #: (TPU_PROBE_LOG_r04.md, round-4b finding).  "loop" is the
    #: kernel superstep: a scalar/small-buffer-carry fori_loop over
    #: ONE offset-aware compiled kernel (ops/superstep.
    #: make_loop_super_step) -- the sharded runtime's superstep shape
    #: on a single chip; it degrades loop -> wide -> per-batch.
    #: Pallas workers set "loop" or "wide"; kernels pay no extra HBM
    #: for either (tile state is VMEM, raw output is batch/4 bytes),
    #: unlike the XLA steps whose materialized candidate blocks scale
    #: with batch.
    SUPER_MODE = "scan"

    def _super_batch(self) -> int:
        """Keyspace indices consumed per super-step iteration."""
        return self.stride

    def _super_step(self, inner: int):
        from dprf_tpu.ops.superstep import make_super_step
        cache = getattr(self, "_super_cache", None)
        if cache is None:
            cache = self._super_cache = {}
        # keyed by the step OBJECT, not just inner: some workers swap
        # self.step between sweeps (descrypt's salt blocks).  The
        # cached entry holds a strong ref to its step so the id key
        # can never be reused by a successor object.
        key = (id(self.step), inner)
        entry = cache.get(key)
        if entry is None:
            entry = cache[key] = (self.step, make_super_step(
                self.step, inner, self._super_batch(), self._batch_flag))
        return entry[1]

    def _super_inner(self, remaining_chunks: int) -> int:
        """Power-of-two scan length for a super dispatch, or 0 for the
        per-batch path.  DPRF_SUPERSTEP=0 disables super dispatch."""
        from dprf_tpu.ops.superstep import max_inner
        from dprf_tpu.utils import env as envreg
        if getattr(self, "_super_disabled", False) or \
                not envreg.get_bool("DPRF_SUPERSTEP"):
            return 0
        cap = max_inner(self._super_batch(), self.SUPER_CAP)
        if remaining_chunks < self.SUPER_MIN or cap < self.SUPER_MIN:
            return 0
        return min(cap, 1 << (remaining_chunks.bit_length() - 1))

    def _make_step(self, batch: int):
        """Rebuild this worker's step at a different lane count.
        Wide-capable subclasses (SUPER_MODE == "wide") override; the
        contract is the per-batch step's exactly, with hit capacities
        scaled up by batch // self.stride (shape-derived at decode)."""
        raise NotImplementedError

    def _make_loop_parts(self, inner: int):
        """(offset-aware per-batch step, accumulation groups) for
        ops/superstep.make_loop_super_step, or None when this worker
        has no loop program.  Loop-capable subclasses (SUPER_MODE ==
        "loop") override; the step must be built with the WINDOW
        buffer capacities so its overflow/collision inflation exceeds
        the window buffers too."""
        return None

    def _loop_step(self, inner: int):
        from dprf_tpu.ops.superstep import make_loop_super_step
        cache = getattr(self, "_loop_cache", None)
        if cache is None:
            cache = self._loop_cache = {}
        entry = cache.get(inner)
        if entry is None:
            parts = self._make_loop_parts(inner)
            if parts is None:
                return None
            step, groups = parts
            entry = cache[inner] = make_loop_super_step(
                step, inner, self._super_batch(), groups)
        return entry

    def _loop_dispatch(self, inner: int, base, n_valid):
        """One loop-superstep dispatch (SUPER_MODE == "loop"), or None
        to degrade to the WIDE path.  The loop program is the proven
        fori_loop-of-one-kernel shape (bench inner-loop, sharded
        superstep); a backend that rejects it still gets wide's
        single-pallas_call program before falling to per-batch."""
        import jax.numpy as jnp
        try:
            ls = self._loop_step(inner)
            if ls is None:
                self._loop_disabled = True
                return None
            return ls(base, jnp.int32(n_valid))
        except Exception as e:        # noqa: BLE001 -- compiler errors
            from dprf_tpu.utils.logging import DEFAULT as log
            self._loop_disabled = True
            log.warn("loop super-step program failed to build; falling "
                     "back to wide dispatch", inner=inner, error=str(e))
            return None

    def _wide_step(self, sbatch: int):
        cache = getattr(self, "_wide_cache", None)
        if cache is None:
            cache = self._wide_cache = {}
        step = cache.get(sbatch)
        if step is None:
            step = cache[sbatch] = self._make_step(sbatch)
        return step

    def _wide_dispatch(self, sbatch: int, base, n_valid):
        """One wide dispatch, or None if its program will not build.
        A backend that rejects the wide program has already run the
        per-batch program (factory warmup), so the degradation target
        is per-batch dispatch -- NOT the scan super-step, which is an
        unproven third shape on the backend that just failed."""
        import jax.numpy as jnp
        try:
            ws = self._wide_step(sbatch)
            return ws(base, jnp.int32(n_valid))
        except Exception as e:        # noqa: BLE001 -- compiler errors
            from dprf_tpu.utils.logging import DEFAULT as log
            self._wide_disabled = True
            log.warn("wide-step program failed to build; falling back "
                     "to per-batch dispatch", sbatch=sbatch,
                     error=str(e))
            return None

    def _super_dispatch(self, inner: int, xs, n_valid):
        """One super dispatch, or None if its program will not build.
        Super programs compile lazily at the first big unit -- after
        the engine factory's warmup-time Pallas->XLA fallback has
        already run -- so a backend that rejects the scan-wrapped step
        must degrade THIS worker to per-batch dispatch, not kill the
        job mid-run."""
        import jax.numpy as jnp
        try:
            ss = self._super_step(inner)
            return ss(jnp.asarray(xs), jnp.int32(n_valid))
        except Exception as e:        # noqa: BLE001 -- compiler errors
            # are backend-specific exception types; anything raised
            # here means "no super program", never a wrong result
            from dprf_tpu.utils.logging import DEFAULT as log
            self._super_disabled = True
            log.warn("super-step program failed to build; falling back "
                     "to per-batch dispatch", inner=inner, error=str(e))
            return None

    def submit(self, unit: WorkUnit) -> PendingUnit:
        """Enqueue ALL device work for the unit and return a
        PendingUnit.  Large units go out as super-step dispatches --
        one scan program covering up to SUPER_CAP batches -- so the
        per-dispatch link overhead (argument transfers + enqueue) is
        paid once per ~10^9 candidates instead of once per batch; the
        remainder uses the per-batch step.  The unit-level hit flag is
        accumulated ON DEVICE across both kinds, so a hitless unit
        costs exactly one scalar readback."""
        import jax.numpy as jnp
        queued = []
        flag = None
        pos = unit.start
        # a wide-mode worker whose wide program failed to build must
        # fall back to PER-BATCH dispatch, never to the scan wrapper:
        # on the backend that just rejected the wide shape, scan-of-
        # pallas_call is the shape that silently wedges the compile
        # helper (TPU_PROBE_LOG_r04.md round-4b).  "loop" tries the
        # fori_loop superstep first and degrades loop -> wide ->
        # per-batch; a loop result decodes exactly like a wide one
        # (window-relative buffers), so it queues under the same kind.
        loop = self.SUPER_MODE == "loop"
        wide = loop or self.SUPER_MODE == "wide"
        fuse = not (wide and getattr(self, "_wide_disabled", False))
        while fuse:
            # _super_inner's max_inner(stride) budget bounds the wide
            # program's inner*stride lanes to int32 as well -- every
            # worker using THIS submit has _super_batch() == stride
            inner = self._super_inner((unit.end - pos) // self.stride)
            if inner < 2:
                break
            sstride = inner * self.stride
            if wide:
                base = jnp.asarray(self.gen.digits(pos), dtype=jnp.int32)
                result = None
                if loop and not getattr(self, "_loop_disabled", False):
                    result = self._loop_dispatch(inner, base, sstride)
                if result is None:
                    result = self._wide_dispatch(sstride, base, sstride)
                if result is None:
                    break                  # degraded to per-batch
                f = self._batch_flag(result)
                flag = f if flag is None else flag + f
                queued.append(("wide", (pos, sstride), result))
                pos += sstride
                continue
            digits = np.stack([
                np.asarray(self.gen.digits(pos + i * self.stride),
                           dtype=np.int32) for i in range(inner)])
            out = self._super_dispatch(inner, digits, sstride)
            if out is None:
                break                      # degraded to per-batch
            f, outs = out
            flag = f if flag is None else flag + f
            queued.append(("super", pos, outs))
            pos += sstride
        for bstart in range(pos, unit.end, self.stride):
            n_valid = min(self.stride, unit.end - bstart)
            base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
            result = self.step(base, jnp.int32(n_valid))
            # scalar adds ride the stream behind their batches; per-
            # batch count fetches would cost one link round trip per
            # batch -- over a high-latency transport that caps
            # throughput at batch/RTT regardless of chip speed.
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append(("batch", bstart, result))
        if flag is not None and hasattr(flag, "copy_to_host_async"):
            flag.copy_to_host_async()
        return PendingUnit(self, unit, queued, flag)

    def process(self, unit: WorkUnit) -> list[Hit]:
        return self.submit(unit).resolve()

    process._submit_based = True   # safe to pipeline via submit()

    @staticmethod
    def _super_rows(result, start: int, window: int, decode_row):
        """Stacked super-step outputs -> per-row decode at start + i *
        window.  Each row is exactly one per-batch step output tuple,
        so overflow/rescan semantics stay at one-batch granularity."""
        arrs = [np.asarray(a) for a in result]
        hits: list[Hit] = []
        for i in range(arrs[0].shape[0]):
            hits.extend(decode_row(start + i * window,
                                   tuple(a[i] for a in arrs)))
        return hits

    def _decode_queued(self, kind: str, start, result,
                       unit: WorkUnit) -> list[Hit]:
        """One queued dispatch -> Hit records; super rows and wide
        windows decode through the SAME _batch_hits path as plain
        batches (wide entries carry their window explicitly)."""
        if kind == "batch":
            return self._batch_hits(start, result, unit)
        if kind == "wide":
            pos, window = start
            return self._batch_hits(pos, result, unit, window=window)
        return self._super_rows(
            result, start, self.stride,
            lambda bstart, row: self._batch_hits(bstart, row, unit))

    def _decode_lanes(self, bstart: int, lanes_np, tpos_np) -> list[Hit]:
        """Hit-buffer arrays -> Hit records (lane -1 = unused slot).

        Probe-table steps emit an OUT-OF-RANGE target pos for lanes
        the device did not verify exactly (the degraded host-verify
        layout, or a sharded survivor-buffer overflow): those lanes
        are Bloom survivors, not confirmed hits, and resolve here
        with one oracle hash each -- false positives drop (the
        PallasMaskWorker multi-target maybe idiom)."""
        hits = []
        for lane, tp in zip(lanes_np, tpos_np):
            if lane < 0:
                continue
            gidx = bstart + int(lane)
            if self.multi and not 0 <= int(tp) < len(self._order):
                hits.extend(self._verify_probe_lane(gidx))
                continue
            ti = int(self._order[int(tp)]) if self.multi else 0
            hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits

    def _verify_probe_lane(self, gidx: int) -> list[Hit]:
        if self.oracle is None:
            raise RuntimeError(
                "unverified probe-table survivor and no oracle engine "
                "to resolve it with")
        plain = self.gen.candidate(gidx)
        ti = self._digest_map.get(self.oracle.hash_batch([plain])[0])
        return [Hit(ti, gidx, plain)] if ti is not None else []

    def _rescan(self, bstart: int, unit: WorkUnit,
                window: int = 0) -> list[Hit]:
        """Exact host rescan of one overflowed dispatch window
        (pathological case: more hits than the device hit buffer
        holds).  window defaults to one batch stride; wide dispatches
        pass their full window."""
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        end = min(bstart + (window or self.stride), unit.end)
        # coverage note (ISSUE 19): the exact rescan RE-sweeps this
        # range -- the audit trail must show the second pass was
        # deliberate, not a double-lease
        coverage.note("rescan", bstart, end, unit=unit.unit_id)
        sub = WorkUnit(-1, bstart, end - bstart)
        return CpuWorker(self.oracle, self.gen, self.targets).process(sub)

    def _batch_hits(self, bstart: int, result, unit: WorkUnit,
                    window: int = 0) -> list[Hit]:
        count, lanes, tpos = result
        count = int(count)
        if count == 0:
            return []
        # capacity is the buffer the step was BUILT with (wide steps
        # scale it), not the worker's nominal hit_capacity
        if count > lanes.shape[0]:
            if window > self.stride:
                return self._redrive_wide(bstart, window, unit)
            return self._rescan(bstart, unit, window)
        return self._decode_lanes(bstart, np.asarray(lanes), np.asarray(tpos))

    def _redrive_wide(self, bstart: int, window: int,
                      unit: WorkUnit) -> list[Hit]:
        """An overflowed wide window re-runs through the per-batch
        DEVICE step, so exact-rescan granularity stays one stride.
        The in-kernel collision sentinel (count = capacity + 1 on any
        two-hit tile) makes wide 'overflow' far more likely than real
        buffer exhaustion; a whole-window host rescan of 10^8+
        candidates here would stall the job for hours."""
        import jax.numpy as jnp
        hits: list[Hit] = []
        end = min(bstart + window, unit.end)
        # coverage note (ISSUE 19): this window re-runs per-batch on
        # device -- deliberate re-coverage, visible to the auditor
        coverage.note("redrive", bstart, end, unit=unit.unit_id)
        for bs in range(bstart, end, self.stride):
            nv = min(self.stride, end - bs)
            base = jnp.asarray(self.gen.digits(bs), dtype=jnp.int32)
            hits.extend(self._batch_hits(
                bs, self.step(base, jnp.int32(nv)), unit))
        return hits


class WordlistWorkerBase(MaskWorkerBase):
    """Wordlist-specific hit decoding + rescan shared by the single-
    device and sharded wordlist workers.  Subclasses set
    ``self.word_batch`` (words per step, = the step's flat-lane stride
    divisor) before using these."""

    ATTACK = "wordlist"

    def warmup_args(self) -> tuple:
        """Wordlist steps take (word-window start, n_valid words) --
        both scalars -- not a digit vector."""
        import jax.numpy as jnp
        return (jnp.int32(0), jnp.int32(0))

    def _collect_word_hits(self, lanes_np, tpos_np, ws: int,
                           unit: WorkUnit, lane_wb: int = 0) -> list[Hit]:
        """Flat rule-major step lanes -> in-unit Hit records."""
        R = self.gen.n_rules
        hits: list[Hit] = []
        for lane, tp in zip(lanes_np, tpos_np):
            if lane < 0:
                continue
            gidx = wordlist_lane_to_gidx(int(lane), ws,
                                         lane_wb or self.word_batch, R)
            if not unit.start <= gidx < unit.end:
                continue
            if self.multi and not 0 <= int(tp) < len(self._order):
                # probe-table survivor the device did not verify
                # exactly (host-verify layout / survivor overflow):
                # one oracle hash resolves it, false positives drop
                hits.extend(self._verify_probe_lane(gidx))
                continue
            ti = int(self._order[int(tp)]) if self.multi else 0
            hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits

    def _rescan_words(self, ws: int, nw: int, unit: WorkUnit) -> list[Hit]:
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        R = self.gen.n_rules
        start = max(unit.start, ws * R)
        end = min(unit.end, (ws + nw) * R)
        # coverage note (ISSUE 19): exact host re-sweep of the
        # overflowed word window, in candidate-index coordinates
        coverage.note("rescan", start, end, unit=unit.unit_id)
        sub = WorkUnit(-1, start, end - start)
        return CpuWorker(self.oracle, self.gen, self.targets).process(sub)


class DeviceWordlistWorker(WordlistWorkerBase):
    """Fused-pipeline worker for wordlist+rules attacks (config 3).

    Units are keyspace index ranges over words x rules (index = word *
    n_rules + rule).  The step covers whole words, so a unit whose
    boundaries are not rule-aligned is processed over the covering word
    range with out-of-unit hits filtered — correct for any unit size,
    though the CLI aligns unit_size to n_rules so nothing is rehashed.
    """

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.rules_pipeline import make_wordlist_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle, probe_ok=True)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.step = make_wordlist_crack_step(
            engine, gen, tgt, self.word_batch, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))

    def _super_batch(self) -> int:
        return self.word_batch

    def submit(self, unit: WorkUnit) -> PendingUnit:
        """Word-window analogue of MaskWorkerBase.submit: the step
        argument is a window start (scalar), n_valid counts WORDS, and
        super dispatches cover runs of full word windows."""
        import jax.numpy as jnp

        from dprf_tpu.ops.superstep import max_inner
        w_start, w_end = word_cover_range(unit, self.gen.n_rules)
        w_end = min(w_end, self.gen.n_words)
        queued = []
        flag = None
        ws = w_start
        # as in MaskWorkerBase.submit: a failed wide build degrades to
        # per-batch dispatch only, never to the scan wrapper
        wide = self.SUPER_MODE == "wide"
        fuse = not (wide and getattr(self, "_wide_disabled", False))
        while fuse:
            inner = self._super_inner((w_end - ws) // self.word_batch)
            if wide:
                # the wide program carries inner * stride rule-expanded
                # LANES; _super_inner budgeted per-word windows only
                inner = min(inner, max_inner(self.stride, self.SUPER_CAP))
            if inner < 2:
                break
            nw = inner * self.word_batch
            if wide:
                result = self._wide_dispatch(nw, jnp.int32(ws), nw)
                if result is None:
                    break                  # degraded to per-batch
                f = self._batch_flag(result)
                flag = f if flag is None else flag + f
                queued.append(("wwide", (ws, nw), result))
                ws += nw
                continue
            w0s = (np.arange(inner, dtype=np.int32) * self.word_batch
                   + np.int32(ws))
            out = self._super_dispatch(inner, w0s,
                                       inner * self.word_batch)
            if out is None:
                break                      # degraded to per-batch
            f, outs = out
            flag = f if flag is None else flag + f
            queued.append(("wsuper", ws, outs))
            ws += inner * self.word_batch
        while ws < w_end:
            nw = min(self.word_batch, w_end - ws)
            result = self.step(jnp.int32(ws), jnp.int32(nw))
            # device-accumulated unit flag; see MaskWorkerBase.submit
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append(("wbatch", (ws, nw), result))
            ws += nw
        if flag is not None and hasattr(flag, "copy_to_host_async"):
            flag.copy_to_host_async()
        return PendingUnit(self, unit, queued, flag)

    def process(self, unit: WorkUnit) -> list[Hit]:
        return self.submit(unit).resolve()

    process._submit_based = True   # safe to pipeline via submit()

    def _window_hits(self, ws: int, nw: int, result, unit: WorkUnit,
                     lane_wb: int = 0) -> list[Hit]:
        """lane_wb: word-batch stride the step's flat lanes were built
        with (lane = r * lane_wb + b) -- self.word_batch for plain
        windows, the full window for wide dispatches."""
        count, lanes, tpos = result
        count = int(count)
        if count == 0:
            return []
        if count > lanes.shape[0]:
            if nw > self.word_batch:
                return self._redrive_wide_words(ws, nw, unit)
            return self._rescan_words(ws, nw, unit)
        return self._collect_word_hits(
            np.asarray(lanes), np.asarray(tpos), ws, unit,
            lane_wb or self.word_batch)

    def _redrive_wide_words(self, ws: int, nw: int,
                            unit: WorkUnit) -> list[Hit]:
        """Overflowed wide word window -> per-batch device windows (see
        MaskWorkerBase._redrive_wide: the rules kernel's collision
        sentinel fires on any two-hit cell, so wide overflow must not
        mean a whole-window host rescan)."""
        import jax.numpy as jnp
        hits: list[Hit] = []
        end = ws + nw
        # coverage note (ISSUE 19): candidate-index coordinates of the
        # word window going back through per-batch dispatch
        R = self.gen.n_rules
        coverage.note("redrive", max(unit.start, ws * R),
                      min(unit.end, end * R), unit=unit.unit_id)
        w = ws
        while w < end:
            n = min(self.word_batch, end - w)
            hits.extend(self._window_hits(
                w, n, self.step(jnp.int32(w), jnp.int32(n)), unit))
            w += n
        return hits

    def _decode_queued(self, kind: str, start, result,
                       unit: WorkUnit) -> list[Hit]:
        if kind == "wbatch":
            ws, nw = start
            return self._window_hits(ws, nw, result, unit)
        if kind == "wwide":
            ws, nw = start
            return self._window_hits(ws, nw, result, unit, lane_wb=nw)
        if kind == "wsuper":
            return self._super_rows(
                result, start, self.word_batch,
                lambda ws, row: self._window_hits(
                    ws, self.word_batch, row, unit))
        return super()._decode_queued(kind, start, result, unit)


class PallasWordlistWorker(DeviceWordlistWorker):
    """Wordlist+rules worker over the in-VMEM rule-interpreter kernel
    (ops/pallas_rules.py) -- config 3's fast path.  Single target,
    exact in-kernel compare; the step keeps DeviceWordlistWorker's
    (w0, n_valid_words) -> (count, lanes, tpos) contract with
    rule-major flat lanes for ANY w0 (units need not be tile-aligned),
    so process/hit decode/rescan are inherited unchanged."""

    SUPER_MODE = "wide"

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None,
                 interpret: bool = False):
        from dprf_tpu.ops.pallas_rules import TILE_W, make_rules_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle)
        if self.multi:
            raise ValueError("rules kernel is single-target")
        word_batch = max(TILE_W,
                         (batch // max(1, gen.n_rules) // TILE_W)
                         * TILE_W)
        self._tgt_words = np.asarray(tgt)
        self._interpret = interpret
        self.step = make_rules_crack_step(
            engine.name, gen, self._tgt_words, word_batch,
            hit_capacity, interpret=interpret)
        self.word_batch = self.step.word_batch
        self.stride = self.word_batch * gen.n_rules

    def _make_step(self, n_words: int):
        """Rules-kernel step over an n_words window (wide dispatches:
        n_words = inner * word_batch, already a TILE_W multiple), with
        the hit buffer scaled to keep per-word capacity constant.

        All wide sizes share ONE device copy of the packed wordlist:
        a build whose window fits the current copy's padding reuses
        it; a larger one rebuilds with more padding, replaces the
        shared copy, AND evicts cached steps still closing over the
        old one -- so HBM holds at most the per-batch step's copy
        plus one wide copy, never one per cached size."""
        from dprf_tpu.ops.pallas_rules import make_rules_crack_step
        scale = max(1, n_words // self.word_batch)
        cap = max(self.hit_capacity,
                  min(self.hit_capacity * scale, 1024))
        old = getattr(self, "_wide_shared", None)
        step = make_rules_crack_step(
            self.engine.name, self.gen, self._tgt_words, n_words,
            cap, interpret=self._interpret, shared_words=old)
        if old is not None and step.words4 is not old[0]:
            # evict IN PLACE: _wide_step holds a reference to the dict
            cache = getattr(self, "_wide_cache", {})
            for k in [k for k, v in cache.items()
                      if getattr(v, "words4", None) is not step.words4]:
                del cache[k]
        self._wide_shared = (step.words4, step.lens3)
        return step


class PallasMaskWorker(MaskWorkerBase):
    """Mask worker over the hand-written Pallas kernels
    (ops/pallas_mask.py) -- the fast path where the whole
    decode->hash->compare->reduce chain stays in VMEM.

    Single target: exact in-kernel compare; tile collisions surface as
    count > hit_capacity, which reuses the exact-rescan fallback path.

    Multi target (config 2's 1k-hash list): the kernel runs a Bloom
    prefilter (ops/pallas_mask.bloom_tables); each single-maybe lane is
    verified here with ONE oracle hash against the target digest map,
    and each collided tile (>= 2 maybes, including any tile with two
    real hits) is exactly rescanned over its TILE-candidate range.
    """

    RESCAN_CAPACITY = 16
    SUPER_MODE = "loop"

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None,
                 interpret: bool = False,
                 sub: Optional[int] = None):
        from dprf_tpu.ops.pallas_mask import SUB

        tgt = self._setup_targets(engine, gen, targets, hit_capacity, oracle)
        # sub: sublanes per kernel tile (the `dprf tune` tile rung);
        # default is the DPRF_PALLAS_SUB knob
        self._sub = SUB if sub is None else sub
        tile = self._sub * 128
        batch = max(tile, (batch // tile) * tile)
        self.batch = self.stride = batch
        self._tile = tile
        self._interpret = interpret
        if self.multi:
            if oracle is None:
                raise ValueError("multi-target pallas worker needs an "
                                 "oracle engine to verify Bloom maybes")
            dt = "<u4" if engine.little_endian else ">u4"
            self._twords = np.stack([np.frombuffer(t.digest, dtype=dt)
                                     .astype(np.uint32)
                                     for t in self.targets])
            self._digest_map = {t.digest: i
                                for i, t in enumerate(self.targets)}
        else:
            self._twords = np.asarray(tgt)
        self.step = self._make_step(batch)

    def _make_step(self, batch: int):
        """Kernel step at `batch` lanes; wide steps (batch a multiple
        of self.batch) scale the hit/rescan buffers so per-candidate
        capacity matches the per-batch path, capped to keep the
        reduce buffers small."""
        from dprf_tpu.ops.pallas_mask import (make_pallas_mask_crack_step,
                                              make_pallas_multi_crack_step)
        scale = max(1, batch // self.batch)
        # never below the user's nominal capacity (a raised --hit-cap
        # must reach the per-batch step unclamped), never a wide
        # buffer smaller than one batch's
        cap = max(self.hit_capacity,
                  min(self.hit_capacity * scale, 1024))
        if self.multi:
            rcap = max(self.RESCAN_CAPACITY,
                       min(self.RESCAN_CAPACITY * scale, 256))
            return make_pallas_multi_crack_step(
                self.engine.name, self.gen, self._twords, batch, cap,
                rcap, interpret=self._interpret, sub=self._sub)
        return make_pallas_mask_crack_step(
            self.engine.name, self.gen, self._twords, batch, cap,
            interpret=self._interpret, sub=self._sub)

    def _make_loop_parts(self, inner: int):
        """Offset-aware per-batch kernel step + accumulation groups
        for the loop superstep: ONE compiled kernel invoked `inner`
        times per dispatch (the TPU-proven fori_loop shape), with hits
        folding into window-relative device buffers.

        The step is built at the per-batch lane count but with the
        WINDOW hit capacities (wide's cap-scaling policy), so the
        in-kernel collision sentinel -- count = capacity + 1 -- lands
        past the window buffer too and the wide-path overflow redrive
        applies unchanged."""
        from dprf_tpu.ops.pallas_mask import (CORES,
                                              make_pallas_mask_crack_step,
                                              make_pallas_multi_crack_step)
        if self.engine.name not in CORES:
            return None   # pallas_ext steps have no offset argument
        cap = max(self.hit_capacity,
                  min(self.hit_capacity * inner, 1024))
        grid = self.batch // self._tile
        if self.multi:
            rcap = max(self.RESCAN_CAPACITY,
                       min(self.RESCAN_CAPACITY * inner, 256))
            step = make_pallas_multi_crack_step(
                self.engine.name, self.gen, self._twords, self.batch,
                cap, rcap, interpret=self._interpret,
                with_offset=True, sub=self._sub)
            # maybe lanes globalize by the batch stride, collided
            # tiles by the per-batch grid length
            return step, ((0, 1, None, self.batch, cap),
                          (2, 3, None, grid, rcap))
        step = make_pallas_mask_crack_step(
            self.engine.name, self.gen, self._twords, self.batch, cap,
            interpret=self._interpret, with_offset=True, sub=self._sub)
        return step, ((0, 1, 2, self.batch, cap),)

    def _batch_flag(self, result):
        if not self.multi:
            return result[0]
        return result[0] + result[2]   # single maybes + collided tiles

    def _batch_hits(self, bstart: int, result, unit: WorkUnit,
                    window: int = 0) -> list[Hit]:
        if not self.multi:
            return super()._batch_hits(bstart, result, unit, window)
        n_single, lanes, n_collided, ctiles = result
        n_single, n_collided = int(n_single), int(n_collided)
        if n_single == 0 and n_collided == 0:
            return []
        if n_single > lanes.shape[0] or n_collided > ctiles.shape[0]:
            if window > self.stride:
                return self._redrive_wide(bstart, window, unit)
            return self._rescan(bstart, unit, window)  # pathological
        hits: list[Hit] = []
        for lane in np.asarray(lanes):
            if lane < 0:
                continue
            # one oracle hash verifies a Bloom maybe exactly (and
            # resolves its target index); false positives drop here
            gidx = bstart + int(lane)
            plain = self.gen.candidate(gidx)
            ti = self._digest_map.get(self.oracle.hash_batch([plain])[0])
            if ti is not None:
                hits.append(Hit(ti, gidx, plain))
        for t in np.asarray(ctiles):
            if t < 0:
                continue
            start = bstart + int(t) * self._tile
            end = min(start + self._tile, unit.end)
            sub = WorkUnit(-1, start, end - start)
            hits.extend(CpuWorker(self.oracle, self.gen,
                                  self.targets).process(sub))
        return hits


class DeviceCombinatorWorker(MaskWorkerBase):
    """Fused-pipeline worker for combinator / hybrid attacks: same
    (base_digits, n_valid) step contract as the mask workers (the
    combinator keyspace is a 2-digit mixed-radix system)."""

    ATTACK = "combinator"

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.combine import make_combinator_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle, probe_ok=True)
        self.batch = self.stride = batch
        self.step = make_combinator_crack_step(
            engine, gen, tgt, batch, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))


class DeviceMaskWorker(MaskWorkerBase):
    """Fused-pipeline worker for mask attacks on fast (unsalted) hashes.

    Bulk target lists (>= DPRF_TARGETS_PROBE_MIN) swap the replicated
    compare table for the probe table (dprf_tpu/targets/): the step
    builder understands a ProbeTable, so probe_ok is set here."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.pipeline import make_mask_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle, probe_ok=True)
        self.batch = self.stride = batch
        self.step = make_mask_crack_step(
            engine, gen, tgt, batch, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))

