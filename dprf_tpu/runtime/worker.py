"""Workers: fetch WorkUnit -> generate candidates -> hash -> report hits.

DeviceMaskWorker is the TPU path: one fused jitted step per job
(ops/pipeline.py), asynchronously dispatched per batch so the device
pipeline never drains; results are resolved after the whole unit is
queued.  Only hit buffers cross back to the host.

CpuWorker is the reference path (`--device=cpu`): oracle engines over
host-materialized candidates.  It is also the fallback that rescans a
batch exactly if a device hit buffer ever overflows.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.generators.base import CandidateGenerator
from dprf_tpu.runtime.workunit import WorkUnit


@dataclasses.dataclass(frozen=True)
class Hit:
    target_index: int      # position in the job's target list
    cand_index: int        # global keyspace index
    plaintext: bytes


def word_cover_range(unit: WorkUnit, n_rules: int) -> tuple:
    """Covering word range [w_start, w_end) of a keyspace-index unit
    (index = word * n_rules + rule; ceil on the end)."""
    return unit.start // n_rules, -(-unit.end // n_rules)


def wordlist_lane_to_gidx(lane: int, ws: int, word_batch: int,
                          n_rules: int) -> int:
    """Rule-major flat step lane (r*B + b) -> global keyspace index for
    a step whose word window starts at ws.  Single source of truth for
    the decode every wordlist worker uses."""
    r, b = divmod(lane, word_batch)
    return (ws + b) * n_rules + r


class CpuWorker:
    """Oracle-engine worker; handles salted and unsalted engines."""

    def __init__(self, engine: HashEngine, gen: CandidateGenerator,
                 targets: Sequence[Target], chunk: int = 2048):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.chunk = chunk
        self._digest_map = {t.digest: i for i, t in enumerate(self.targets)}

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for start in range(unit.start, unit.end, self.chunk):
            n = min(self.chunk, unit.end - start)
            # Rule-based generators may reject candidates (None): those
            # keyspace indices are holes — never hashed.
            pairs = [(start + j, c)
                     for j, c in enumerate(self.gen.candidates(start, n))
                     if c is not None]
            if not pairs:
                continue
            cands = [c for _, c in pairs]
            if self.engine.salted:
                for ti, t in enumerate(self.targets):
                    for (gidx, cand), d in zip(pairs, self.engine.hash_batch(
                            cands, params=t.params)):
                        if d == t.digest:
                            hits.append(Hit(ti, gidx, cand))
            else:
                for (gidx, cand), d in zip(pairs,
                                           self.engine.hash_batch(cands)):
                    ti = self._digest_map.get(d)
                    if ti is not None:
                        hits.append(Hit(ti, gidx, cand))
        return hits


class MaskWorkerBase:
    """Shared machinery for fused-pipeline mask workers.

    Subclasses set ``self.step`` (the jitted crack step) and
    ``self.stride`` (keyspace indices consumed per step call) in
    __init__ after calling ``_setup_targets``, and implement
    ``_batch_hits`` to decode one step result.
    """

    def _setup_targets(self, engine, gen, targets: Sequence[Target],
                       hit_capacity: int, oracle: Optional[HashEngine]):
        from dprf_tpu.ops import compare as cmp_ops
        from dprf_tpu.ops.pipeline import target_words

        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        digests = [t.digest for t in self.targets]
        self.multi = len(digests) > 1
        if self.multi:
            table = cmp_ops.make_target_table(
                digests, little_endian=engine.little_endian)
            self._order = table.order
            return table
        self._order = np.zeros(1, dtype=np.int64)
        return target_words(digests[0], engine.little_endian)

    def warmup(self) -> None:
        """Force the step's compile now (jit is lazy).  The engine
        factory calls this so a Mosaic/XLA compile failure surfaces at
        worker construction -- where it can fall back to another path --
        instead of mid-job."""
        import jax.numpy as jnp

        from dprf_tpu.utils.sync import hard_sync
        base = jnp.asarray(self.gen.digits(0), dtype=jnp.int32)
        # hard_sync (not block_until_ready) so a RUNTIME kernel fault
        # also surfaces here, not just a compile failure -- over the
        # axon tunnel block_until_ready returns at enqueue and the
        # fault would land on the first real batch instead
        hard_sync(self.step(base, jnp.int32(0)))

    def _batch_flag(self, result):
        """Scalar that is nonzero iff this batch needs host attention
        (hits or overflow).  Element 0 of every step result is its hit
        count; subclasses with extra buffers override."""
        return result[0]

    def process(self, unit: WorkUnit) -> list[Hit]:
        import jax.numpy as jnp
        queued = []
        flag = None
        for bstart in range(unit.start, unit.end, self.stride):
            n_valid = min(self.stride, unit.end - bstart)
            base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
            result = self.step(base, jnp.int32(n_valid))
            # unit-level hit indicator, accumulated ON DEVICE: scalar
            # adds ride the stream behind their batches, so the single
            # int() below is the only host readback a hitless unit
            # pays.  Per-batch count fetches would cost one link round
            # trip per batch -- over a high-latency transport (the axon
            # tunnel: ~60 ms RTT) that caps throughput at
            # batch/RTT regardless of chip speed.
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append((bstart, result))
        if flag is None or int(flag) == 0:
            return []
        hits: list[Hit] = []
        for bstart, result in queued:
            hits.extend(self._batch_hits(bstart, result, unit))
        return hits

    def _decode_lanes(self, bstart: int, lanes_np, tpos_np) -> list[Hit]:
        """Hit-buffer arrays -> Hit records (lane -1 = unused slot)."""
        hits = []
        for lane, tp in zip(lanes_np, tpos_np):
            if lane < 0:
                continue
            gidx = bstart + int(lane)
            ti = int(self._order[int(tp)]) if self.multi else 0
            hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits

    def _rescan(self, bstart: int, unit: WorkUnit) -> list[Hit]:
        """Exact host rescan of one overflowed batch (pathological case:
        more hits in a batch than the device hit buffer holds)."""
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        end = min(bstart + self.stride, unit.end)
        sub = WorkUnit(-1, bstart, end - bstart)
        return CpuWorker(self.oracle, self.gen, self.targets).process(sub)

    def _batch_hits(self, bstart: int, result, unit: WorkUnit) -> list[Hit]:
        count, lanes, tpos = result
        count = int(count)
        if count == 0:
            return []
        if count > self.hit_capacity:
            return self._rescan(bstart, unit)
        return self._decode_lanes(bstart, np.asarray(lanes), np.asarray(tpos))


class WordlistWorkerBase(MaskWorkerBase):
    """Wordlist-specific hit decoding + rescan shared by the single-
    device and sharded wordlist workers.  Subclasses set
    ``self.word_batch`` (words per step, = the step's flat-lane stride
    divisor) before using these."""

    def _collect_word_hits(self, lanes_np, tpos_np, ws: int,
                           unit: WorkUnit) -> list[Hit]:
        """Flat rule-major step lanes -> in-unit Hit records."""
        R = self.gen.n_rules
        hits: list[Hit] = []
        for lane, tp in zip(lanes_np, tpos_np):
            if lane < 0:
                continue
            gidx = wordlist_lane_to_gidx(int(lane), ws,
                                         self.word_batch, R)
            if not unit.start <= gidx < unit.end:
                continue
            ti = int(self._order[int(tp)]) if self.multi else 0
            hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits

    def _rescan_words(self, ws: int, nw: int, unit: WorkUnit) -> list[Hit]:
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        R = self.gen.n_rules
        start = max(unit.start, ws * R)
        end = min(unit.end, (ws + nw) * R)
        sub = WorkUnit(-1, start, end - start)
        return CpuWorker(self.oracle, self.gen, self.targets).process(sub)


class DeviceWordlistWorker(WordlistWorkerBase):
    """Fused-pipeline worker for wordlist+rules attacks (config 3).

    Units are keyspace index ranges over words x rules (index = word *
    n_rules + rule).  The step covers whole words, so a unit whose
    boundaries are not rule-aligned is processed over the covering word
    range with out-of-unit hits filtered — correct for any unit size,
    though the CLI aligns unit_size to n_rules so nothing is rehashed.
    """

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.rules_pipeline import make_wordlist_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.step = make_wordlist_crack_step(
            engine, gen, tgt, self.word_batch, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))

    def process(self, unit: WorkUnit) -> list[Hit]:
        import jax.numpy as jnp
        w_start, w_end = word_cover_range(unit, self.gen.n_rules)
        queued = []
        flag = None
        for ws in range(w_start, w_end, self.word_batch):
            nw = min(self.word_batch, w_end - ws, self.gen.n_words - ws)
            if nw <= 0:
                break
            result = self.step(jnp.int32(ws), jnp.int32(nw))
            # device-accumulated unit flag; see MaskWorkerBase.process
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append((ws, nw, result))
        if flag is None or int(flag) == 0:
            return []
        hits: list[Hit] = []
        for ws, nw, result in queued:
            count, lanes, tpos = result
            count = int(count)
            if count == 0:
                continue
            if count > self.hit_capacity:
                hits.extend(self._rescan_words(ws, nw, unit))
                continue
            hits.extend(self._collect_word_hits(
                np.asarray(lanes), np.asarray(tpos), ws, unit))
        return hits


class PallasWordlistWorker(DeviceWordlistWorker):
    """Wordlist+rules worker over the in-VMEM rule-interpreter kernel
    (ops/pallas_rules.py) -- config 3's fast path.  Single target,
    exact in-kernel compare; the step keeps DeviceWordlistWorker's
    (w0, n_valid_words) -> (count, lanes, tpos) contract with
    rule-major flat lanes for ANY w0 (units need not be tile-aligned),
    so process/hit decode/rescan are inherited unchanged."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None,
                 interpret: bool = False):
        from dprf_tpu.ops.pallas_rules import TILE_W, make_rules_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle)
        if self.multi:
            raise ValueError("rules kernel is single-target")
        word_batch = max(TILE_W,
                         (batch // max(1, gen.n_rules) // TILE_W)
                         * TILE_W)
        self.step = make_rules_crack_step(
            engine.name, gen, np.asarray(tgt), word_batch,
            hit_capacity, interpret=interpret)
        self.word_batch = self.step.word_batch
        self.stride = self.word_batch * gen.n_rules

    def warmup(self) -> None:
        import jax.numpy as jnp

        from dprf_tpu.utils.sync import hard_sync
        hard_sync(self.step(jnp.int32(0), jnp.int32(0)))


class PallasMaskWorker(MaskWorkerBase):
    """Mask worker over the hand-written Pallas kernels
    (ops/pallas_mask.py) -- the fast path where the whole
    decode->hash->compare->reduce chain stays in VMEM.

    Single target: exact in-kernel compare; tile collisions surface as
    count > hit_capacity, which reuses the exact-rescan fallback path.

    Multi target (config 2's 1k-hash list): the kernel runs a Bloom
    prefilter (ops/pallas_mask.bloom_tables); each single-maybe lane is
    verified here with ONE oracle hash against the target digest map,
    and each collided tile (>= 2 maybes, including any tile with two
    real hits) is exactly rescanned over its TILE-candidate range.
    """

    RESCAN_CAPACITY = 16

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None,
                 interpret: bool = False):
        from dprf_tpu.ops.pallas_mask import (TILE,
                                              make_pallas_mask_crack_step,
                                              make_pallas_multi_crack_step)

        tgt = self._setup_targets(engine, gen, targets, hit_capacity, oracle)
        batch = max(TILE, (batch // TILE) * TILE)
        self.batch = self.stride = batch
        self._tile = TILE
        if self.multi:
            if oracle is None:
                raise ValueError("multi-target pallas worker needs an "
                                 "oracle engine to verify Bloom maybes")
            dt = "<u4" if engine.little_endian else ">u4"
            twords = np.stack([np.frombuffer(t.digest, dtype=dt)
                               .astype(np.uint32) for t in self.targets])
            self._digest_map = {t.digest: i
                                for i, t in enumerate(self.targets)}
            self.step = make_pallas_multi_crack_step(
                engine.name, gen, twords, batch, hit_capacity,
                self.RESCAN_CAPACITY, interpret=interpret)
        else:
            self.step = make_pallas_mask_crack_step(
                engine.name, gen, np.asarray(tgt), batch, hit_capacity,
                interpret=interpret)

    def _batch_flag(self, result):
        if not self.multi:
            return result[0]
        return result[0] + result[2]   # single maybes + collided tiles

    def _batch_hits(self, bstart: int, result, unit: WorkUnit) -> list[Hit]:
        if not self.multi:
            return super()._batch_hits(bstart, result, unit)
        n_single, lanes, n_collided, ctiles = result
        n_single, n_collided = int(n_single), int(n_collided)
        if n_single == 0 and n_collided == 0:
            return []
        if n_single > self.hit_capacity or n_collided > self.RESCAN_CAPACITY:
            return self._rescan(bstart, unit)      # pathological overflow
        hits: list[Hit] = []
        for lane in np.asarray(lanes):
            if lane < 0:
                continue
            # one oracle hash verifies a Bloom maybe exactly (and
            # resolves its target index); false positives drop here
            gidx = bstart + int(lane)
            plain = self.gen.candidate(gidx)
            ti = self._digest_map.get(self.oracle.hash_batch([plain])[0])
            if ti is not None:
                hits.append(Hit(ti, gidx, plain))
        for t in np.asarray(ctiles):
            if t < 0:
                continue
            start = bstart + int(t) * self._tile
            end = min(start + self._tile, unit.end)
            sub = WorkUnit(-1, start, end - start)
            hits.extend(CpuWorker(self.oracle, self.gen,
                                  self.targets).process(sub))
        return hits


class DeviceCombinatorWorker(MaskWorkerBase):
    """Fused-pipeline worker for combinator / hybrid attacks: same
    (base_digits, n_valid) step contract as the mask workers (the
    combinator keyspace is a 2-digit mixed-radix system)."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.combine import make_combinator_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle)
        self.batch = self.stride = batch
        self.step = make_combinator_crack_step(
            engine, gen, tgt, batch, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))


class DeviceMaskWorker(MaskWorkerBase):
    """Fused-pipeline worker for mask attacks on fast (unsalted) hashes."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.pipeline import make_mask_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity, oracle)
        self.batch = self.stride = batch
        self.step = make_mask_crack_step(
            engine, gen, tgt, batch, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))

