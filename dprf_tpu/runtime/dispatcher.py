"""Dispatcher: keyspace splitter + work-unit lease ledger.

Units are generated lazily (a keyspace of 95^7 would be ~66M units --
never materialized).  The ledger tracks three populations:

  - issued-and-outstanding units, each with a lease deadline;
  - a reissue queue (failed or lease-expired units);
  - a completed-interval set, kept as merged [start, end) ranges so the
    resume journal stays tiny no matter how many units ran.

Failure detection / elastic recovery (SURVEY.md section 5): a worker
that stops heartbeating simply lets its lease expire; `reap_expired`
moves the unit to the reissue queue and another worker picks it up.

Two tuning hooks (ISSUE 2):

  - an optional AdaptiveUnitSizer resizes LAZILY-GENERATED units per
    leasing worker (already-split units -- resume gaps, reissues --
    keep their geometry; resizing them would tear the ledger); the
    dispatcher also reports every failed attempt / lease expiry to it,
    so a worker with a CRASH HISTORY gets smaller units, not just a
    slow one (ISSUE 4 satellite of a ROADMAP item);
  - a per-unit retry cap (default 5 failed attempts) PARKS a unit that
    keeps dying instead of reissuing it forever: a unit that crashes
    every worker that touches it (a generator edge case, a poisoned
    shape) must not livelock the whole job.  Parked ranges count as
    unreachable -- `done()` fires once everything else is covered --
    and surface in job status + dprf_units_poisoned_total, never as
    silent coverage.

Tracing (ISSUE 4): every unit gets a TRACE ID at split time; lease /
complete / fail / reissue / park events are recorded as spans into the
flight recorder (telemetry/trace.py), and `trace_context()` hands the
RPC layer the (trace id, lease span id) pair it propagates to remote
workers so their spans stitch onto the same timeline.
"""

from __future__ import annotations

import heapq
import time
from typing import Callable, Optional

from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.telemetry import get_registry
from dprf_tpu.telemetry.coverage import (CoverageLedger, IntervalSet,
                                         coverage_digest)
from dprf_tpu.telemetry.trace import get_tracer, new_trace_id, span_id

#: lock-discipline declaration (`dprf check` locks analyzer): the
#: Dispatcher has NO lock of its own -- every concurrent caller (the
#: RPC handlers, the server drain loop) serializes through
#: CoordinatorState.lock, which declares its ``dispatcher`` reference
#: guarded.  ``<extern>`` additionally forbids this class from ever
#: acquiring a declared lock itself: a hidden acquisition here would
#: be invisible to the callers' lock-order reasoning.  (The local
#: Coordinator drives its Dispatcher from one thread; no lock needed.)
GUARDED_BY = {"Dispatcher": {"<extern>": ()}}

#: re-export: the one interval implementation lives with the coverage
#: ledger now (telemetry/coverage.py); existing importers keep working
__all__ = ["Dispatcher", "IntervalSet"]


class Dispatcher:
    """Split [0, keyspace) into WorkUnits; lease, complete, reissue."""

    def __init__(self, keyspace: int, unit_size: int,
                 lease_timeout: float = 300.0,
                 clock: Optional[Callable[[], float]] = None,
                 registry=None, sizer=None,
                 max_unit_retries: Optional[int] = 5,
                 recorder=None, job_id: str = "j0", order=None):
        if unit_size <= 0:
            raise ValueError("unit_size must be positive")
        self.keyspace = keyspace
        self.unit_size = unit_size
        self.lease_timeout = lease_timeout
        #: rank<->index bijection (generators/order.py) or None for
        #: identity.  With an order, EVERY position in this ledger --
        #: unit spans, the done set, the split frontier, gaps -- is a
        #: RANK; the split frontier advancing is what makes low ranks
        #: (probable candidates) go out first.  Only the journal-facing
        #: views (completed_intervals, coverage_digest) translate to
        #: index space, so session artifacts stay order-independent.
        self.order = order
        #: the job this ledger belongs to (multi-tenant serve plane,
        #: jobs/scheduler.py): every unit-lifecycle metric and span
        #: this dispatcher records carries it, so per-job observability
        #: costs one label -- "j0" is the single-job/local default
        self.job_id = job_id
        #: tune.AdaptiveUnitSizer (or None): sizes fresh units per
        #: leasing worker toward a target seconds-per-unit
        self.sizer = sizer
        #: failed attempts (fail() or lease expiry) before a unit is
        #: parked; None = reissue forever (the pre-guard behavior)
        self.max_unit_retries = max_unit_retries
        self._clock = clock or time.monotonic
        self._next_start = 0
        self._next_id = 0
        #: min-heap of (start, unit_id, unit): reissues and resume
        #: resplits lease LOWEST RANK FIRST -- under an order, pending
        #:  units always hold the most probable uncovered candidates,
        #: so they must beat the frontier, not queue behind it
        self._pending: list[tuple] = []
        #: id -> (unit, worker, deadline, lease span id)
        self._outstanding: dict[int, tuple] = {}
        self._retries: dict[int, int] = {}         # id -> failed attempts
        self._parked: list[WorkUnit] = []
        self._parked_len = 0
        self._done = IntervalSet()
        self.tracer = get_tracer(recorder)
        #: unit id -> trace id, assigned at split time; entries are
        #: dropped on complete (bounded by live + parked units)
        self._trace_ids: dict[int, str] = {}
        # unit-lifecycle metrics carry the job id (ISSUE 8): one
        # declaration site, one label -- a multi-tenant coordinator's
        # /metrics splits cleanly per tenant job
        m = get_registry(registry)
        self._m_leased = m.counter(
            "dprf_units_leased_total", "WorkUnit leases handed out",
            labelnames=("job",))
        self._m_completed = m.counter(
            "dprf_units_completed_total", "WorkUnits marked done",
            labelnames=("job",))
        self._m_reissued = m.counter(
            "dprf_units_reissued_total",
            "WorkUnits returned to the queue",
            labelnames=("reason", "job"))
        self._g_outstanding = m.gauge(
            "dprf_units_outstanding", "leases currently held",
            labelnames=("job",))
        self._g_keyspace = m.gauge(
            "dprf_keyspace_total", "keyspace indices in the job",
            labelnames=("job",))
        self._g_covered = m.gauge(
            "dprf_keyspace_covered", "keyspace indices completed",
            labelnames=("job",))
        self._m_poisoned = m.counter(
            "dprf_units_poisoned_total",
            "units parked after exhausting their retry budget",
            labelnames=("job",))
        self._g_parked = m.gauge(
            "dprf_units_parked",
            "units currently parked (poisoned); drops to 0 on a "
            "retry-parked admin op", labelnames=("job",))
        self._g_keyspace.set(keyspace, job=job_id)
        self._g_covered.set(0, job=job_id)
        self._g_parked.set(0, job=job_id)
        #: coverage audit plane (ISSUE 19): every range-mutating
        #: lifecycle step below feeds this ledger through its one
        #: event API; it detects overlaps at insert, reports gaps
        #: against the keyspace, and carries the coverage digest
        self.coverage = CoverageLedger(keyspace, job_id=job_id,
                                       registry=registry, order=order)

    # -- construction from a resume journal ------------------------------

    @classmethod
    def from_completed(cls, keyspace: int, unit_size: int,
                       completed: list,
                       expect_digest: Optional[str] = None,
                       **kw) -> "Dispatcher":
        d = cls(keyspace, unit_size, **kw)
        if d.order is not None:
            # the journal records INDEX intervals (order-independent
            # session artifacts); fold them back through the bijection
            # so the rank-space ledger resumes -- and resplits below
            # the rank frontier -- exactly where the sweep stopped
            completed = d.order.rank_image(completed)
        for s, e in completed:
            d._done.add(s, e)
            d.coverage.event("restore", s, e)
            # restore spans mark a GENERATION boundary in the trace
            # stream and seed the new generation's covered set: the
            # offline replay (perfreport/audit.py) resets on them, so
            # a crash-restart legitimately re-sweeping ranges the
            # journal had not snapshotted yet is not misread as
            # double coverage -- while a true within-generation
            # double-complete still is
            d.tracer.record("restore", proc="coordinator",
                            job=d.job_id, start=s, length=e - s)
        d._g_covered.set(d._done.covered(), job=d.job_id)
        frontier = max((e for _, e in completed), default=0)
        for s, e in d._done.gaps(frontier):
            # re-split big gaps into unit-sized pieces
            d.coverage.event("resplit", s, e)
            for u in range(s, e, unit_size):
                unit = d._make_unit(u, min(unit_size, e - u))
                heapq.heappush(d._pending,
                               (unit.start, unit.unit_id, unit))
        d._next_start = frontier
        if expect_digest and d.coverage_digest() != expect_digest:
            # the PR 14 fingerprint discipline applied to coverage
            # state: a journal whose intervals do not reproduce the
            # digest it recorded describes a DIFFERENT sweep -- a
            # resume from it would punch silent coverage holes
            raise ValueError(
                "coverage digest mismatch on resume: journal recorded "
                f"{expect_digest} but its intervals rebuild to "
                f"{d.coverage_digest()} -- the journal is torn or "
                "edited; refusing to resume over silent holes")
        return d

    def _make_unit(self, start: int, length: int) -> WorkUnit:
        u = WorkUnit(self._next_id, start, length,
                     job_id=self.job_id,
                     order=(self.order.kind if self.order is not None
                            else "index"))
        self._next_id += 1
        # the unit's whole lifecycle -- every lease, failure, reissue,
        # wherever it lands -- shares this one trace id
        self._trace_ids[u.unit_id] = new_trace_id()
        self.coverage.event("split", u.start, u.end, unit=u.unit_id)
        return u

    def trace_context(self, unit_id: int) -> Optional[tuple]:
        """(trace id, lease span id) of the unit's CURRENT lease --
        what the RPC layer ships to the worker so its spans stitch
        onto this attempt; None once the unit is no longer leased."""
        entry = self._outstanding.get(unit_id)
        if entry is None:
            return None
        return self._trace_ids.get(unit_id), entry[3]

    # -- the worker-facing API -------------------------------------------

    def lease(self, worker_id: str = "local") -> Optional[WorkUnit]:
        """Hand out the next unit, or None if nothing is leasable now
        (either exhausted, or all remaining work is outstanding)."""
        self.reap_expired()
        if self._pending:
            unit = heapq.heappop(self._pending)[2]
        elif self._next_start < self.keyspace:
            size = (self.sizer.next_size(worker_id)
                    if self.sizer is not None else self.unit_size)
            length = min(size, self.keyspace - self._next_start)
            unit = self._make_unit(self._next_start, length)
            self._next_start += length
        else:
            return None
        lease_span = self.tracer.record(
            "lease", trace=self._trace_ids.get(unit.unit_id),
            proc="coordinator", worker=worker_id, unit=unit.unit_id,
            job=self.job_id, start=unit.start, length=unit.length,
            lease_timeout_s=self.lease_timeout,
            attempt=self._retries.get(unit.unit_id, 0) + 1)
        self._outstanding[unit.unit_id] = (
            unit, worker_id, self._clock() + self.lease_timeout,
            span_id(lease_span))
        self.coverage.event("lease", unit.start, unit.end,
                            unit=unit.unit_id)
        self._m_leased.inc(job=self.job_id)
        self._g_outstanding.set(len(self._outstanding),
                                job=self.job_id)
        return unit

    def lease_many(self, worker_id: str, n: int) -> list:
        """Up to n units for ONE worker in one call -- the RPC
        lease-ahead form: a pipelined remote worker holds several
        leases so the next super-step is on its device stream while
        the previous unit's hits decode and the report round trip
        flies.  Accounting stays strictly per-unit: each lease gets
        its own span, deadline, and reissue path, so an aheaded unit
        whose lease expires while queued is released exactly like a
        running one."""
        out = []
        for _ in range(max(0, int(n))):
            unit = self.lease(worker_id)
            if unit is None:
                break
            out.append(unit)
        return out

    def outstanding_for(self, worker_id: str) -> int:
        """Leases this worker currently holds (multi-outstanding
        accounting: the RPC layer caps lease-ahead against it)."""
        return sum(1 for (_, wid, _, _) in self._outstanding.values()
                   if wid == worker_id)

    def lease_holder(self, unit_id: int) -> Optional[str]:
        """Worker currently holding the unit's lease (None once it is
        completed, failed, or reaped)."""
        entry = self._outstanding.get(unit_id)
        return entry[1] if entry is not None else None

    def complete(self, unit_id: int, elapsed: Optional[float] = None,
                 worker_id: Optional[str] = None) -> bool:
        """Mark a leased unit done; returns True iff this call covered
        it.  A late completion of an already-reissued unit is
        idempotent: when ``worker_id`` is given and the lease moved to
        ANOTHER worker, the stale report is dropped (the live holder
        owns the completion -- no double-complete, no double count),
        and a unit with no live lease at all is simply ignored."""
        entry = self._outstanding.get(unit_id)
        if entry is None:
            return False
        if worker_id is not None and entry[1] != worker_id:
            return False   # reissued to another worker: stale report
        del self._outstanding[unit_id]
        unit, worker_id, _, lease_sid = entry
        self._done.add(unit.start, unit.end)
        self.coverage.event("complete", unit.start, unit.end,
                            unit=unit_id)
        self._retries.pop(unit_id, None)
        if self.sizer is not None and elapsed is not None:
            # throughput report feeds the ADAPTIVE sizer: the next unit
            # this worker leases is sized toward the target seconds
            self.sizer.observe(worker_id, unit.length, elapsed)
        # the span carries the unit's RANGE so the offline auditor
        # (perfreport/audit.py) can replay coverage from the trace
        # stream alone and cross-check it against the journal
        self.tracer.record(
            "complete", trace=self._trace_ids.pop(unit_id, None),
            parent=lease_sid, proc="coordinator", worker=worker_id,
            unit=unit_id, job=self.job_id, elapsed_s=elapsed,
            start=unit.start, length=unit.length)
        self._m_completed.inc(job=self.job_id)
        self._g_covered.set(self._done.covered(), job=self.job_id)
        self._g_outstanding.set(len(self._outstanding),
                                job=self.job_id)
        return True

    def _observe_failure(self, worker_id: Optional[str]) -> None:
        """Crash history -> unit sizing: every failed attempt / lease
        expiry shrinks the worker's NEXT units (tune.AdaptiveUnitSizer
        halves per recent failure), so a flaky host re-runs minutes of
        work when it dies, not hours -- low throughput alone would
        never catch a worker that is fast but keeps crashing."""
        if self.sizer is not None and worker_id is not None:
            observe = getattr(self.sizer, "observe_failure", None)
            if observe is not None:
                observe(worker_id)

    def _requeue(self, unit: WorkUnit, reason: str,
                 worker_id: Optional[str] = None,
                 lease_sid: Optional[str] = None) -> None:
        """Reissue a failed/expired unit -- unless it has burned its
        retry budget, in which case it is PARKED: its range becomes
        unreachable for this run (visible in status and the poisoned
        counter, and still a resume-journal gap) instead of bouncing
        between workers forever."""
        n = self._retries.get(unit.unit_id, 0) + 1
        self._retries[unit.unit_id] = n
        self._observe_failure(worker_id)
        tid = self._trace_ids.get(unit.unit_id)
        if (self.max_unit_retries is not None
                and n >= self.max_unit_retries):
            # parked ranges stay LIVE on the coverage ledger:
            # accounted, intentionally unreachable -- never a gap
            self.coverage.event("park", unit.start, unit.end,
                                unit=unit.unit_id)
            self._parked.append(unit)
            self._parked_len += unit.length
            self._m_poisoned.inc(job=self.job_id)
            self._g_parked.set(len(self._parked), job=self.job_id)
            self.tracer.record("park", trace=tid, parent=lease_sid,
                               proc="coordinator", unit=unit.unit_id,
                               job=self.job_id, worker=worker_id,
                               attempts=n, reason=reason)
            from dprf_tpu.utils.logging import DEFAULT as log
            log.warn("parking poisoned unit after repeated failures",
                     unit=unit.unit_id, start=unit.start,
                     length=unit.length, attempts=n, reason=reason)
        else:
            self.coverage.event("reissue", unit.start, unit.end,
                                unit=unit.unit_id)
            heapq.heappush(self._pending,
                           (unit.start, unit.unit_id, unit))
            self.tracer.record("reissue", trace=tid, parent=lease_sid,
                               proc="coordinator", unit=unit.unit_id,
                               job=self.job_id, worker=worker_id,
                               attempts=n, reason=reason)
            self._m_reissued.inc(reason=reason, job=self.job_id)

    def fail(self, unit_id: int,
             worker_id: Optional[str] = None) -> bool:
        """Release a leased unit back to the queue; returns True iff
        this call released it.  Stale-guarded like complete(): a fail
        report from a worker that no longer holds the lease must not
        tear the live holder's attempt off the ledger."""
        entry = self._outstanding.get(unit_id)
        if entry is None:
            return False
        if worker_id is not None and entry[1] != worker_id:
            return False   # reissued to another worker: stale report
        del self._outstanding[unit_id]
        unit, holder, _, lease_sid = entry
        self.coverage.event("fail", unit.start, unit.end,
                            unit=unit_id)
        self.tracer.record("fail",
                           trace=self._trace_ids.get(unit_id),
                           parent=lease_sid, proc="coordinator",
                           worker=holder, unit=unit_id,
                           job=self.job_id)
        self._requeue(unit, "failed", worker_id=holder,
                      lease_sid=lease_sid)
        self._g_outstanding.set(len(self._outstanding),
                                job=self.job_id)
        return True

    def reap_expired(self) -> int:
        now = self._clock()
        expired = [uid for uid, (_, _, dl, _) in self._outstanding.items()
                   if dl < now]
        for uid in expired:
            unit, worker_id, _, lease_sid = self._outstanding.pop(uid)
            self._requeue(unit, "lease_expired", worker_id=worker_id,
                          lease_sid=lease_sid)
        if expired:
            self._g_outstanding.set(len(self._outstanding),
                                    job=self.job_id)
        return len(expired)

    # -- status ----------------------------------------------------------

    def done(self) -> bool:
        # parked ranges are unreachable this run: waiting on them would
        # livelock the job, so "done" means everything REACHABLE is
        # covered (exhausted() still reports the honest full-coverage
        # answer)
        return (self._done.covered() >= self.keyspace - self._parked_len)

    def exhausted(self) -> bool:
        """True only when the WHOLE keyspace is covered (no parked
        holes) -- the answer `JobResult.exhausted` reports."""
        return self._done.covered() >= self.keyspace

    def idle(self) -> bool:
        """Nothing leasable and nothing outstanding (but not done:
        happens only transiently between reap and re-lease)."""
        return (not self._pending and not self._outstanding
                and self._next_start >= self.keyspace)

    def progress(self) -> tuple:
        return self._done.covered(), self.keyspace

    def completed_intervals(self) -> list[tuple]:
        """The covered set in INDEX space -- the journal/snapshot form.
        Under an order this is the index image of the rank-space done
        set, so the session artifacts a sweep leaves behind are
        identical no matter what order produced them."""
        if self.order is not None:
            return self.order.index_image(self._done.intervals())
        return self._done.intervals()

    def coverage_digest(self) -> str:
        """Order-independent digest of the covered set -- journaled
        with units snapshots and carried by JobResult; a resume must
        rebuild the same digest from the journaled intervals.
        Computed from the dispatcher's own done set (canonicalized to
        index space), so it never depends on the DPRF_COVERAGE
        telemetry knob."""
        return coverage_digest(self.keyspace, self.completed_intervals())

    def outstanding_count(self) -> int:
        return len(self._outstanding)

    def outstanding_indices(self) -> int:
        """Keyspace indices currently out on leases -- what a job
        quota (jobs/scheduler.py) is enforced against alongside the
        covered count."""
        return sum(u.length for u, _, _, _ in self._outstanding.values())

    def leasable(self) -> bool:
        """Whether a lease() call could hand out a unit right now
        (pending reissues, or unsplit keyspace left)."""
        return bool(self._pending) or self._next_start < self.keyspace

    def abandon(self) -> None:
        """Job-cancel teardown (jobs/scheduler.py): drop every pending
        and outstanding unit without completing or reissuing them.
        The ledger stops dead -- late reports from workers still
        holding these leases bounce off the scheduler's CANCELLED
        guard, so nothing lands after this."""
        self._pending.clear()
        self._outstanding.clear()
        self.coverage.event("abandon")
        self._g_outstanding.set(0, job=self.job_id)

    def parked_count(self) -> int:
        return len(self._parked)

    def parked_indices(self) -> int:
        """Keyspace indices inside parked (poisoned) units."""
        return self._parked_len

    def parked_units(self) -> list:
        return list(self._parked)

    def retry_parked(self) -> int:
        """Admin op (`dprf retry-parked` -> rpc.op_retry_parked):
        requeue every parked unit with a FRESH retry budget, without
        restarting the job.  The operator's tool for "the poison was
        environmental" (a bad worker build since replaced, a host that
        ran out of memory): the ranges become reachable again and
        `done()` stops treating them as holes.  Returns the number of
        units requeued.  dprf_units_poisoned_total keeps its count --
        it records parking EVENTS; the dprf_units_parked gauge drops
        to 0."""
        n = len(self._parked)
        for unit in self._parked:
            self._retries.pop(unit.unit_id, None)
            self.coverage.event("unpark", unit.start, unit.end,
                                unit=unit.unit_id)
            heapq.heappush(self._pending,
                           (unit.start, unit.unit_id, unit))
            self.tracer.record("reissue",
                               trace=self._trace_ids.get(unit.unit_id),
                               proc="coordinator", unit=unit.unit_id,
                               job=self.job_id, reason="retry_parked")
            self._m_reissued.inc(reason="retry_parked",
                                 job=self.job_id)
        self._parked = []
        self._parked_len = 0
        self._g_parked.set(0, job=self.job_id)
        if n:
            from dprf_tpu.utils.logging import DEFAULT as log
            log.info("requeued parked units with a fresh retry budget",
                     count=n)
        return n

    def outstanding_unit(self, unit_id: int) -> Optional[WorkUnit]:
        """The still-leased unit with this id (None once completed,
        failed, or reaped) -- lets the RPC layer attribute a completion
        report's candidate count without re-deriving unit geometry."""
        entry = self._outstanding.get(unit_id)
        return entry[0] if entry is not None else None

    def outstanding_leases(self) -> list:
        """Live-lease table for the ``dprf top`` view: every held
        lease with its worker, range, seconds until expiry, and trace
        id."""
        now = self._clock()
        return [{"unit": uid, "worker": wid, "start": u.start,
                 "length": u.length, "job": self.job_id,
                 "deadline_s": round(dl - now, 3),
                 "trace": self._trace_ids.get(uid)}
                for uid, (u, wid, dl, _) in self._outstanding.items()]
