"""Coordinator: owns the job -- issues WorkUnits, collects hits,
persists progress, decides when to stop.

The control plane (SURVEY.md section 1): everything here is thin host
code; the hot loop lives in the workers' fused device programs.  Hits
are deduped per target, written to the potfile and the session journal,
and the job stops when every target is cracked or the keyspace is
exhausted.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence

from dprf_tpu.engines.base import Target
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.potfile import Potfile
from dprf_tpu.runtime.session import SessionJournal
from dprf_tpu.runtime.worker import Hit
from dprf_tpu.telemetry import get_registry
from dprf_tpu.telemetry import perf as perf_mod
from dprf_tpu.telemetry.trace import get_tracer, jax_profile_ctx


@dataclasses.dataclass
class JobSpec:
    engine: str
    device: str
    attack: str                 # "mask" | "wordlist"
    attack_arg: str             # mask string or wordlist path
    keyspace: int
    fingerprint: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class JobResult:
    found: dict                  # target_index -> plaintext bytes
    tested: int
    elapsed: float
    exhausted: bool
    #: units parked by the dispatcher's retry cap (poisoned ranges the
    #: run could not cover; 0 on a healthy job)
    parked: int = 0
    #: order-independent digest of the covered index set (ISSUE 19):
    #: what the final journal snapshot recorded; `dprf audit` must
    #: rebuild the same value from the session artifacts alone
    coverage_digest: str = ""

    @property
    def rate(self) -> float:
        return self.tested / self.elapsed if self.elapsed > 0 else 0.0


def preload_potfile(found: dict, targets: Sequence[Target],
                    potfile) -> None:
    """Seed `found` with targets the potfile already cracked, so no
    keyspace is spent rediscovering them.  Shared by the local
    Coordinator and the distributed CoordinatorState (cli.cmd_serve)."""
    if potfile is None:
        return
    for i, t in enumerate(targets):
        plain = potfile.get(t.raw)
        if plain is not None:
            found.setdefault(i, plain)


def restore_hits_into(found: dict, hits: list) -> None:
    """Seed `found` from a session journal's hit records (tolerant of
    malformed entries).  Shared by local and distributed resume paths."""
    for h in hits:
        try:
            found.setdefault(int(h["target"]), bytes.fromhex(h["plaintext"]))
        except (KeyError, ValueError):
            continue


#: `dprf check` retrace analyzer: loops in these functions drive the
#: device per work unit -- host syncs and shape-varying jit calls
#: inside them are silent perf bugs the compile cache can't see.
HOT_PATHS = ("Coordinator.run",)


class Coordinator:
    def __init__(self, spec: JobSpec, targets: Sequence[Target],
                 dispatcher: Dispatcher, worker,
                 session: Optional[SessionJournal] = None,
                 potfile: Optional[Potfile] = None,
                 progress_cb: Optional[Callable] = None,
                 progress_interval: float = 5.0,
                 oracle=None, registry=None, recorder=None):
        self.spec = spec
        self.targets = list(targets)
        self.dispatcher = dispatcher
        self.worker = worker
        self.session = session
        self.potfile = potfile
        self.progress_cb = progress_cb
        self.progress_interval = progress_interval
        #: CPU oracle HashEngine.  Device hits are re-hashed on the host
        #: before they reach the potfile -- the same guard the distributed
        #: path applies in rpc.CoordinatorState (a kernel/XLA bug must
        #: not poison the potfile or silently end the search for a
        #: target it did not crack).  None = trust the worker (CPU path,
        #: where the worker IS the oracle).
        self.oracle = oracle
        self.rejected = 0
        self.found: dict[int, bytes] = {}
        #: flight recorder for the local job's sweep/hit_verify spans
        #: (the dispatcher records the lease ledger's into the same
        #: one by default)
        self.tracer = get_tracer(recorder)
        self._registry = get_registry(registry)
        #: per-phase sweep attribution (ISSUE 9): every Nth unit runs
        #: the sampled synced probe; verify timing is unsampled
        self._perf = perf_mod.PerfSampler(registry=self._registry,
                                          recorder=self.tracer)
        from dprf_tpu.telemetry import declare_job_metrics
        jm = declare_job_metrics(self._registry)
        self._m_hits = jm["hits"]
        self._m_rejects = jm["rejects"]
        self._m_cands = jm["cands"]
        self._h_unit = jm["unit_seconds"]
        self._g_targets = jm["targets"]
        self._g_found = jm["found"]
        self._g_targets.set(len(self.targets))
        self._g_found.set(len(self.found))

    # -- pre-run bookkeeping ---------------------------------------------

    def preload_found(self) -> None:
        """Mark targets already cracked (potfile) or recorded in a resumed
        session so work stops early / never starts."""
        preload_potfile(self.found, self.targets, self.potfile)
        self._g_found.set(len(self.found))

    def restore_hits(self, hits: list) -> None:
        restore_hits_into(self.found, hits)
        self._g_found.set(len(self.found))

    # -- the run loop ----------------------------------------------------

    def _all_found(self) -> bool:
        return len(self.found) >= len(self.targets)

    def _record(self, hit: Hit) -> bool:
        """Record one verified hit; returns False (and records nothing)
        if the oracle re-hash rejects it."""
        if hit.target_index in self.found:
            return True
        target = self.targets[hit.target_index]
        if self.oracle is not None and not self.oracle.verify(hit.plaintext,
                                                              target):
            from dprf_tpu.utils.logging import DEFAULT as log
            self.rejected += 1
            self._m_rejects.inc()
            log.warn("rejected unverifiable device hit; rescanning unit "
                     "with the CPU oracle", target=target.raw[:32],
                     cand_index=hit.cand_index)
            return False
        self.found[hit.target_index] = hit.plaintext
        self._m_hits.inc()
        self._g_found.set(len(self.found))
        if self.potfile is not None:
            self.potfile.add(target.raw, hit.plaintext)
        if self.session is not None:
            # job-tagged unconditionally (ISSUE 10): the journal's
            # header names this id as default_job, so resume folds
            # these lines back into the flat fields
            self.session.record_hit(hit.target_index, hit.cand_index,
                                    hit.plaintext,
                                    job=self.dispatcher.job_id)
        return True

    #: default units dispatched ahead of the oldest unresolved one
    #: (``DPRF_PIPELINE_DEPTH`` overrides -- worker.pipeline_depth is
    #: the one resolution site, shared with the remote worker_loop).
    #: Depth 2 is enough to overlap one unit's flag round trip with
    #: the next unit's compute (the only latency in the local loop);
    #: deeper queues just hold more leases without hiding more.
    PIPELINE_DEPTH = 2

    def _finish_unit(self, unit, hits) -> None:
        """Record a unit's resolved hits; any rejected hit means the
        device path is suspect for this range, so the whole unit is
        exactly rescanned with the CPU oracle (whose hits verify by
        construction) before the unit may count as covered."""
        rejected = False
        for hit in hits:
            rejected |= not self._record(hit)
        if rejected:
            from dprf_tpu.runtime.worker import CpuWorker, OrderedWorker
            rescan = CpuWorker(self.oracle, self.worker.gen,
                               self.worker.targets)
            order = getattr(self.worker, "order", None)
            if order is not None:
                # rank-ordered job: the unit's span is ranks, and the
                # rescan must decode it through the same bijection
                rescan = OrderedWorker(rescan, order)
            for hit in rescan.process(unit):
                self._record(hit)   # oracle-produced: verifies trivially

    def run(self) -> JobResult:
        from dprf_tpu.runtime.worker import UnitPipeline, pipeline_depth

        t0 = time.perf_counter()
        tested0 = self.dispatcher.progress()[0]
        last_report = t0
        # Overlapped warmup: kick the step compile onto a background
        # thread (a no-op for workers already warmed -- Pallas
        # factories -- or already started by the CLI) and join it only
        # at the first dispatch, so the compile overlaps session open
        # and the first leases instead of serializing with them.
        warmup_async = getattr(self.worker, "warmup_async", None)
        if warmup_async is not None:
            warmup_async()
        ensure_warm = getattr(self.worker, "ensure_warm", None)
        if self.session is not None:
            self.session.open(self.spec.as_dict(),
                              default_job=self.dispatcher.job_id)
        # Submit-ahead FIFO (shared with the remote worker_loop):
        # device work for every queued unit is already dispatched;
        # resolving the head overlaps its readback latency with the
        # tail's compute.
        pipeline = UnitPipeline(self.worker,
                                pipeline_depth(self.PIPELINE_DEPTH))
        warm_pending = ensure_warm is not None
        t_last_resolve = None
        # DPRF_JAX_PROFILE=<dir>: kernel-level drill-down beside the
        # span timeline (no-op when unset; degrades safely if a
        # profiler trace is already active via --profile)
        profile = jax_profile_ctx()
        profile.__enter__()
        try:
            while not self._all_found():
                while not pipeline.full and not self.dispatcher.done():
                    unit = self.dispatcher.lease()
                    if unit is None:
                        break
                    if ensure_warm is not None:
                        # join the background compile before the first
                        # step dispatch (submitting mid-compile would
                        # race the jit tracer against itself)
                        ensure_warm()
                    if warm_pending:
                        # trace the overlapped compile at its REAL cost
                        # (compile_seconds), parented onto the first
                        # lease so the cold start is legible per unit
                        warm_pending = False
                        warm_s = getattr(self.worker, "compile_seconds",
                                         None)
                        ctx = self.dispatcher.trace_context(unit.unit_id)
                        if warm_s is not None:
                            self.tracer.record(
                                "warmup", dur=float(warm_s),
                                trace=ctx[0] if ctx else None,
                                parent=ctx[1] if ctx else None,
                                proc="local", engine=self.spec.engine,
                                cache=getattr(self.worker,
                                              "compile_cache", None),
                                overlapped=True)
                    probe = None
                    if self._perf.take():
                        # sampled unit: serial synced sweep with
                        # per-phase attribution (declared PERF_PROBE)
                        pctx = self.dispatcher.trace_context(
                            unit.unit_id)
                        probe = (self._perf,
                                 pctx[0] if pctx else None)
                    pipeline.submit(unit, probe=probe)
                if not len(pipeline):
                    if self.dispatcher.done() or \
                            self.dispatcher.outstanding_count() == 0:
                        break        # exhausted
                    time.sleep(0.01)
                    continue
                unit, p, t_submit, _ = pipeline.pop()
                ctx = self.dispatcher.trace_context(unit.unit_id)
                hits = p.resolve()
                now_resolve = time.monotonic()
                unit_s = now_resolve - t_submit
                # inter-completion interval: the loop's true drain
                # rate once the pipeline is primed (unit_s includes
                # up to depth-1 units of queue wait) -- feeds the
                # roofline gauge; resets when the pipeline empties so
                # starvation never reads as slow hashing
                interval = (now_resolve - t_last_resolve
                            if t_last_resolve is not None else unit_s)
                t_last_resolve = (now_resolve if len(pipeline)
                                  else None)
                self.tracer.record(
                    "sweep", dur=unit_s,
                    trace=ctx[0] if ctx else None,
                    parent=ctx[1] if ctx else None, proc="local",
                    # a probed unit's sweep span carries the id its
                    # phase children were parented on
                    span=getattr(p, "sweep_span", None),
                    unit=unit.unit_id, length=unit.length,
                    hits=len(hits),
                    probed=getattr(p, "sweep_span", None) is not None)
                if hits:
                    t_verify = time.monotonic()
                    rejected0 = self.rejected
                    self._finish_unit(unit, hits)
                    verify_s = time.monotonic() - t_verify
                    self._perf.observe_verify(verify_s,
                                              engine=self.spec.engine,
                                              job=self.dispatcher.job_id)
                    self.tracer.record(
                        "hit_verify",
                        dur=verify_s,
                        trace=ctx[0] if ctx else None,
                        parent=ctx[1] if ctx else None,
                        proc="coordinator", unit=unit.unit_id,
                        hits=len(hits),
                        rejected=self.rejected - rejected0)
                self._h_unit.observe(unit_s)
                self._m_cands.inc(unit.length, engine=self.spec.engine,
                                  device=self.spec.device)
                if interval > 0:
                    # live roofline distance from the drain rate
                    perf_mod.publish_roofline(
                        self.spec.engine, unit.length / interval,
                        registry=self._registry)
                # submit-to-resolve time feeds the adaptive unit sizer;
                # it includes up to PIPELINE_DEPTH-1 units of queue
                # wait, so the EWMA under-estimates throughput a little
                # -- which only biases units SMALLER than the target,
                # the safe direction
                self.dispatcher.complete(unit.unit_id, elapsed=unit_s)
                if self.session is not None:
                    self.session.record_units(
                        self.dispatcher.completed_intervals(),
                        job=self.dispatcher.job_id,
                        digest=self.dispatcher.coverage_digest())
                now = time.perf_counter()
                if self.progress_cb and now - last_report >= self.progress_interval:
                    last_report = now
                    done, total = self.dispatcher.progress()
                    self.progress_cb(done, total, len(self.found),
                                     (done - tested0) / max(now - t0, 1e-9))
        finally:
            profile.__exit__(None, None, None)
            # Snapshot in finally: a Ctrl-C mid-job must not lose up to
            # snapshot_every-1 units of journaled coverage.
            if self.session is not None:
                self.session.snapshot(
                    self.dispatcher.completed_intervals(),
                    job=self.dispatcher.job_id,
                    digest=self.dispatcher.coverage_digest())
                self.session.close()
        elapsed = time.perf_counter() - t0
        done, total = self.dispatcher.progress()
        return JobResult(found=dict(self.found), tested=done - tested0,
                         elapsed=elapsed,
                         exhausted=self.dispatcher.exhausted(),
                         parked=self.dispatcher.parked_count(),
                         coverage_digest=self.dispatcher.coverage_digest())
