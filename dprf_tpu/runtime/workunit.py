"""WorkUnit: a contiguous keyspace shard.

The unit of distribution (SURVEY.md section 1): the Dispatcher carves
the candidate index space [0, keyspace) into contiguous ranges; a unit
is a pure function of its range, so reissuing one after a worker
failure is always safe (idempotent -- worst case a hit is reported
twice and deduped by the coordinator).

Indices are Python ints end-to-end on the host: keyspaces like 95^7
exceed 2^32 and the device never sees a raw 64-bit index (it gets a
mixed-radix digit vector instead; see generators/mask.py).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class WorkUnit:
    unit_id: int
    start: int
    length: int
    #: owning job (multi-tenant serve plane, jobs/scheduler.py): unit
    #: ids are only unique WITHIN a job's ledger, so every lease,
    #: complete, and journal record routes by (job_id, unit_id).  The
    #: default matches the single-job Dispatcher's default ledger id.
    job_id: str = "j0"
    #: enumeration order of the span (generators/order.py kinds):
    #: "index" means start/length ARE keyspace indices; any other kind
    #: means they are RANKS and a worker must decode the span through
    #: the job's rank<->index bijection before sweeping
    order: str = "index"

    @property
    def end(self) -> int:
        return self.start + self.length

    def __repr__(self) -> str:  # pragma: no cover
        return (f"WorkUnit({self.job_id}/{self.unit_id}: "
                f"[{self.start}, {self.end}))")
