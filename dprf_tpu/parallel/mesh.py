"""Device mesh construction for keyspace-parallel cracking.

The framework's only sharded axis is the keyspace (candidate-index)
dimension, so every mesh is 1-D with a single ``candidates`` axis
(``PartitionSpec('candidates')`` is the whole sharding story -- see
parallel/sharded.py, the one runtime every sharded step goes through).
On a pod slice the axis rides ICI; across hosts, `jax.distributed` +
the same mesh spans DCN with no code changes (XLA places the
collectives).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

SHARD_AXIS = "candidates"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable shard_map: ``jax.shard_map`` where it exists
    (jax >= 0.6), else ``jax.experimental.shard_map.shard_map`` with
    ``check_vma`` translated to its older ``check_rep`` spelling.  All
    sharded steps route through here so an installed-jax skew breaks
    ONE function, not fifteen call sites."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build the 1-D keyspace mesh over `n_devices` (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} present")
        devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> bool:
    """Form one device mesh ACROSS hosts (a pod slice spanning DCN).

    Wraps `jax.distributed.initialize`: after it, `jax.devices()` on
    every participating process reports the global device set, so the
    same `make_mesh()` + shard_map code shards a job over the whole
    slice with XLA placing the collectives (ICI within a host's chips,
    DCN across hosts).  This is the SINGLE-MESH multi-host mode; the
    WorkUnit RPC control plane (runtime/rpc.py) remains the loosely-
    coupled alternative where hosts lease independent keyspace ranges.

    On TPU pods the three arguments are auto-detected from the
    environment, so `init_multihost()` with no arguments is the normal
    call; on CPU/GPU fleets pass them explicitly.  Returns True if
    initialization ran, False if it was skipped because this process is
    already initialized (idempotent -- safe to call from the CLI on
    every invocation).
    """
    import jax as _jax

    is_init = getattr(_jax.distributed, "is_initialized", None)
    if is_init is None:
        # jax < 0.5 has no is_initialized(); the client handle on the
        # internal global state is the same answer
        def is_init():
            from jax._src import distributed as _dist
            return getattr(_dist.global_state, "client", None) is not None
    if is_init():
        return False      # already initialized: idempotent no-op
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    _jax.distributed.initialize(**kwargs)
    return True
