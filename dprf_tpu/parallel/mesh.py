"""Device mesh construction for keyspace-parallel cracking.

The framework's only sharded axis is the keyspace (candidate-index)
dimension, so every mesh is 1-D with a single ``shard`` axis.  On a pod
slice the axis rides ICI; across hosts, `jax.distributed` + the same
mesh spans DCN with no code changes (XLA places the collectives).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build the 1-D keyspace mesh over `n_devices` (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} present")
        devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.asarray(devices), (SHARD_AXIS,))
