"""Multi-chip worker: drives the sharded fused step over WorkUnits.

Shares all target setup and hit decoding with
runtime.worker.DeviceMaskWorker via MaskWorkerBase; the only differences
are the sharded step factory and that each step call covers an
``n_dev * batch_per_device`` super-batch whose hit buffers come back
per shard.  Lanes are super-batch-global, so ``bstart + lane`` is the
keyspace index exactly as in the single-device path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.runtime.worker import (Hit, MaskWorkerBase, PendingUnit,
                                     WordlistWorkerBase, word_cover_range)
from dprf_tpu.runtime.workunit import WorkUnit


class ShardedMaskWorker(MaskWorkerBase):
    """Fused-pipeline worker spread over a device mesh."""

    def __init__(self, engine, gen, targets: Sequence[Target], mesh,
                 batch_per_device: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.parallel.sharded import make_sharded_mask_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.super_batch = self.stride = mesh.devices.size * batch_per_device
        self.step = make_sharded_mask_crack_step(
            engine, gen, tgt, mesh, batch_per_device, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))

    def _batch_hits(self, bstart: int, result, unit: WorkUnit,
                    window: int = 0) -> list[Hit]:
        total, counts, lanes, tpos = result
        if int(total) == 0:
            return []
        counts_np = np.asarray(counts)
        # Check every shard BEFORE decoding any: an overflow rescan
        # replaces the whole super-batch, so mixing it with per-shard
        # decoded hits would double-report the non-overflowed shards.
        # Capacity is the step's built per-shard buffer width.
        if (counts_np > lanes.shape[-1]).any():
            return self._rescan(bstart, unit, window)
        lanes_np = np.asarray(lanes)
        tpos_np = np.asarray(tpos)
        hits: list[Hit] = []
        for d in range(lanes_np.shape[0]):
            hits.extend(self._decode_lanes(bstart, lanes_np[d], tpos_np[d]))
        return hits


class ShardedCombinatorWorker(ShardedMaskWorker):
    """Combinator / hybrid attack spread over a device mesh: the
    sharded combinator step with ShardedMaskWorker's hit decoding."""

    def __init__(self, engine, gen, targets: Sequence[Target], mesh,
                 batch_per_device: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.combine import (
            make_sharded_combinator_crack_step)

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle)
        self.mesh = mesh
        self.super_batch = self.stride = (mesh.devices.size
                                          * batch_per_device)
        self.step = make_sharded_combinator_crack_step(
            engine, gen, tgt, mesh, batch_per_device, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))


class ShardedWordlistWorker(WordlistWorkerBase):
    """Wordlist+rules attack spread over a device mesh.

    Each step covers ``n_dev * word_batch_per_device`` words; chip c
    expands+hashes its contiguous word slice locally (the packed
    wordlist is replicated to every chip's HBM once per job).  Hit
    lanes come back super-batch-flat: lane = r * super_words + global
    word lane, so the shared decode applies with word_batch =
    super_words.
    """

    def __init__(self, engine, gen, targets: Sequence[Target], mesh,
                 word_batch_per_device: int = 1 << 14,
                 hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.rules_pipeline import (
            make_sharded_wordlist_crack_step)

        tgt = self._setup_targets(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.step = make_sharded_wordlist_crack_step(
            engine, gen, tgt, mesh, word_batch_per_device, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))
        self.word_batch = self.super_words = self.step.super_words
        self.stride = self.super_words * gen.n_rules

    def submit(self, unit: WorkUnit) -> PendingUnit:
        """Enqueue ALL sharded device work for the unit and return a
        PendingUnit (the MaskWorkerBase.submit contract): the unit-
        level hit flag is accumulated on device, so a hitless unit
        costs one scalar readback and the worker pipelines through
        submit_or_process like the single-device paths."""
        import jax.numpy as jnp
        w_start, w_end = word_cover_range(unit, self.gen.n_rules)
        queued = []
        flag = None
        for ws in range(w_start, w_end, self.super_words):
            nw = min(self.super_words, w_end - ws, self.gen.n_words - ws)
            if nw <= 0:
                break
            result = self.step(jnp.int32(ws), jnp.int32(nw))
            # device-accumulated unit flag; see MaskWorkerBase.submit
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append(("wshard", (ws, nw), result))
        if flag is not None and hasattr(flag, "copy_to_host_async"):
            flag.copy_to_host_async()
        return PendingUnit(self, unit, queued, flag)

    def process(self, unit: WorkUnit) -> list[Hit]:
        return self.submit(unit).resolve()

    process._submit_based = True   # safe to pipeline via submit()

    def _decode_queued(self, kind: str, start, result,
                       unit: WorkUnit) -> list[Hit]:
        if kind != "wshard":
            return super()._decode_queued(kind, start, result, unit)
        ws, nw = start
        total, counts, lanes, tpos = result
        if int(total) == 0:
            return []
        if (np.asarray(counts) > self.hit_capacity).any():
            return self._rescan_words(ws, nw, unit)
        return self._collect_word_hits(
            np.asarray(lanes).ravel(), np.asarray(tpos).ravel(),
            ws, unit)
