"""Multi-chip workers: drive the unified sharded runtime over WorkUnits.

Shares all target setup and hit decoding with
runtime.worker.DeviceMaskWorker via MaskWorkerBase; the differences are
the runtime-built sharded step (parallel/sharded.py) and that each
dispatch covers an ``n_dev * batch_per_device`` super-batch whose hit
buffers come back per shard.

Large units go out as **sharded supersteps**: one dispatch fuses up to
``DPRF_SHARD_SUPER_CAP`` batches, generating candidates ON DEVICE per
shard from ``base + shard offset`` (the host ships one digit vector per
window, not per batch -- per-sweep h2d collapses to ~0) and
accumulating hits in a device-resident buffer with ONE collective round
per window.  Hit lanes are window-relative, so ``window start + lane``
is the keyspace index exactly as in the single-device path; hits drain
to host only at unit boundaries through the standard PendingUnit flag,
keeping the UnitPipeline submit/resolve contract intact.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.runtime.worker import (Hit, MaskWorkerBase, PendingUnit,
                                     WordlistWorkerBase, word_cover_range)
from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.telemetry import coverage

#: `dprf check` retrace analyzer: the sharded per-window dispatch
#: loops.  Everything submit() enqueues rides the device stream; a
#: host sync or a retrace inside them stalls every unit of every job.
HOT_PATHS = ("ShardedMaskWorker.submit", "ShardedWordlistWorker.submit")


def shard_super_cap(default: int = 256) -> int:
    """Batches fused per sharded superstep dispatch (power-of-two
    clamp; the int32 window budget of ops/superstep.max_inner still
    applies on top).  ONE resolution site for the knob."""
    from dprf_tpu.utils import env as envreg
    n = max(2, envreg.get_int("DPRF_SHARD_SUPER_CAP", int(default)))
    return 1 << (n.bit_length() - 1)


class _ShardedSuperstepMixin:
    """Superstep dispatch + ahead-of-time compile shared by the
    sharded workers (one degradation policy, one prewarm path)."""

    def _superstep_dispatch(self, inner: int, *args):
        """One superstep dispatch, or None if its program will not
        build -- the degradation target is per-batch dispatch (the
        program the factory already warmed), never a third shape."""
        try:
            return self.step.superstep(inner)(*args)
        except Exception as e:        # noqa: BLE001 -- compiler errors
            from dprf_tpu.utils.logging import DEFAULT as log
            self._super_disabled = True
            log.warn("sharded superstep failed to build; falling back "
                     "to per-batch dispatch", inner=inner, error=str(e))
            return None

    def _aot_chunks(self) -> int:
        """Per-batch chunks this job's whole keyspace could fill --
        what _super_inner sizes the steady-state window against."""
        raise NotImplementedError

    def aot_compile(self) -> None:
        """Prewarm BOTH sharded programs: the per-batch step and the
        capped superstep -- the program steady-state big units
        actually dispatch (``_super_inner`` saturates at the cap), so
        a fleet image covers the hot path, not just the remainder.
        Skipped when the job's keyspace is too small to ever fill a
        superstep window (the program would never run)."""
        super().aot_compile()
        inner = self._super_inner(self._aot_chunks())
        if inner < 2:
            return
        ss = self.step.superstep(inner)
        lower = getattr(ss, "lower", None)
        if lower is None:
            return
        from dprf_tpu.compilecache import compile_observer
        args = self.warmup_args()
        lowered = lower(*args)
        with compile_observer(getattr(self.engine, "name",
                                      "unknown")) as obs:
            compiled = lowered.compile()
        self.xla_compile_seconds = (
            getattr(self, "xla_compile_seconds", 0.0) + obs.seconds)
        self.compile_seconds = (
            getattr(self, "compile_seconds", 0.0) + obs.seconds)
        if obs.cache == "miss":
            self.compile_cache = "miss"
        # the superstep's own program record (telemetry/programs.py):
        # one dispatch covers inner * stride candidates, so its
        # per-candidate costs show what the fusion amortizes
        from dprf_tpu.telemetry import programs as programs_mod
        programs_mod.register_program(
            getattr(self.engine, "name", "unknown"),
            self.ATTACK + "+super", inner * self.stride,
            compiled=compiled, lowered=lowered)


class ShardedMaskWorker(_ShardedSuperstepMixin, MaskWorkerBase):
    """Fused-pipeline worker spread over a device mesh.

    Bulk target lists (>= DPRF_TARGETS_PROBE_MIN) swap the replicated
    compare table for the probe table (dprf_tpu/targets/): the sharded
    step builder carries it as replicated device state through
    supersteps, so probe_ok is set here.

    ``kernel`` (a dict of ops/pallas_mask options: ``sub``,
    ``interpret``, ``probe_fp``; an empty dict takes every default)
    swaps the XLA compute for the FUSED PALLAS KERNEL per shard
    (parallel/sharded.make_sharded_kernel_mask_step): candidates
    generate, hash, and compare(+probe) in VMEM, the host ships one
    digit vector per superstep window.  Multi-target kernel hits come
    back SENTINEL-tagged (in-kernel blocked-probe survivors), so an
    oracle engine is required to verify them."""

    def __init__(self, engine, gen, targets: Sequence[Target], mesh,
                 batch_per_device: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None,
                 kernel: Optional[dict] = None):
        from dprf_tpu.parallel.sharded import (
            make_sharded_kernel_mask_step, make_sharded_mask_step)

        if kernel is None:
            tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                      oracle, probe_ok=True)
            self.mesh = mesh
            self.step = make_sharded_mask_step(
                engine, gen, tgt, mesh, batch_per_device, hit_capacity,
                widen_utf16=getattr(engine, "widen_utf16", False))
        else:
            from dprf_tpu.ops.pallas_mask import SUB

            # the kernel compares against raw target words (exact or
            # blocked-probe), never the XLA table/probe structures
            tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                      oracle)
            self.ATTACK = self.ATTACK + "+kernel"
            if self.multi:
                if oracle is None:
                    raise ValueError(
                        "sharded kernel compute with multiple targets "
                        "needs an oracle engine to verify probe "
                        "survivors")
                dt = "<u4" if engine.little_endian else ">u4"
                twords = np.stack([np.frombuffer(t.digest, dtype=dt)
                                   .astype(np.uint32)
                                   for t in self.targets])
                self._digest_map = {t.digest: i
                                    for i, t in enumerate(self.targets)}
            else:
                twords = np.asarray(tgt)
            sub = kernel.get("sub") or SUB
            tile = sub * 128
            batch_per_device = max(tile,
                                   (batch_per_device // tile) * tile)
            self.mesh = mesh
            self.step = make_sharded_kernel_mask_step(
                engine.name, gen, twords, mesh, batch_per_device,
                hit_capacity, sub=sub,
                interpret=bool(kernel.get("interpret", False)),
                probe_fp=kernel.get("probe_fp"))
        self.super_batch = self.stride = self.step.super_batch
        #: instance override of MaskWorkerBase.SUPER_CAP: the sharded
        #: superstep has its own fusion knob
        self.SUPER_CAP = shard_super_cap()

    def submit(self, unit: WorkUnit) -> PendingUnit:
        """Enqueue ALL sharded device work for the unit and return a
        PendingUnit.  Full power-of-two windows go out as superstep
        dispatches (one digit vector + one dispatch + one collective
        round per window); the remainder uses the per-batch step.  The
        unit-level hit flag accumulates ON DEVICE across both kinds,
        so a hitless unit costs exactly one scalar readback."""
        import jax.numpy as jnp
        queued = []
        flag = None
        pos = unit.start
        while not getattr(self, "_super_disabled", False):
            inner = self._super_inner((unit.end - pos) // self.stride)
            if inner < 2:
                break
            window = inner * self.stride
            base = jnp.asarray(self.gen.digits(pos), dtype=jnp.int32)
            result = self._superstep_dispatch(inner, base,
                                              jnp.int32(window))
            if result is None:
                break                      # degraded to per-batch
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append(("sshard", (pos, window), result))
            # coverage note (ISSUE 19): superstep windows must tile
            # the unit exactly -- one cheap note per multi-million-
            # candidate window lets the auditor check that
            coverage.note("window", pos, pos + window,
                          unit=unit.unit_id, kind="sshard")
            pos += window
        for bstart in range(pos, unit.end, self.stride):
            n_valid = min(self.stride, unit.end - bstart)
            base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
            result = self.step(base, jnp.int32(n_valid))
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append(("batch", bstart, result))
            coverage.note("window", bstart, bstart + n_valid,
                          unit=unit.unit_id, kind="batch")
        if flag is not None and hasattr(flag, "copy_to_host_async"):
            flag.copy_to_host_async()
        return PendingUnit(self, unit, queued, flag)

    def process(self, unit: WorkUnit) -> list[Hit]:
        return self.submit(unit).resolve()

    process._submit_based = True   # safe to pipeline via submit()

    def _aot_chunks(self) -> int:
        return self.gen.keyspace // self.stride

    def _decode_queued(self, kind: str, start, result,
                       unit: WorkUnit) -> list[Hit]:
        if kind == "sshard":
            pos, window = start
            return self._batch_hits(pos, result, unit, window=window)
        return super()._decode_queued(kind, start, result, unit)

    def _batch_hits(self, bstart: int, result, unit: WorkUnit,
                    window: int = 0) -> list[Hit]:
        total, counts, lanes, tpos = result
        if int(total) == 0:
            return []
        counts_np = np.asarray(counts)
        # Check every shard BEFORE decoding any: an overflowed shard's
        # buffer is truncated, so mixing a redrive with per-shard
        # decoded hits would double-report the non-overflowed shards.
        # Capacity is the step's built per-shard buffer width.  An
        # overflowed superstep window redrives through the per-batch
        # DEVICE step (the inherited _redrive_wide loop), so exact-
        # rescan granularity stays one super-batch stride.
        if (counts_np > lanes.shape[-1]).any():
            if window > self.stride:
                return self._redrive_wide(bstart, window, unit)
            return self._rescan(bstart, unit, window)
        lanes_np = np.asarray(lanes)
        tpos_np = np.asarray(tpos)
        hits: list[Hit] = []
        for d in range(lanes_np.shape[0]):
            hits.extend(self._decode_lanes(bstart, lanes_np[d], tpos_np[d]))
        return hits


class ShardedCombinatorWorker(ShardedMaskWorker):
    """Combinator / hybrid attack spread over a device mesh: the
    runtime-built combinator step with ShardedMaskWorker's submit and
    hit decoding (same base_digits/n_valid contract -- the combinator
    keyspace is a 2-digit mixed-radix system)."""

    def __init__(self, engine, gen, targets: Sequence[Target], mesh,
                 batch_per_device: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.combine import (
            make_sharded_combinator_crack_step)

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle, probe_ok=True)
        self.mesh = mesh
        self.step = make_sharded_combinator_crack_step(
            engine, gen, tgt, mesh, batch_per_device, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))
        self.super_batch = self.stride = self.step.super_batch
        self.SUPER_CAP = shard_super_cap()


class ShardedWordlistWorker(_ShardedSuperstepMixin, WordlistWorkerBase):
    """Wordlist+rules attack spread over a device mesh.

    Each per-batch dispatch covers ``n_dev * word_batch_per_device``
    words; chip c expands+hashes its contiguous word slice locally (the
    packed wordlist is replicated to every chip's HBM once per job),
    and supersteps fuse many word windows per dispatch with the word
    cursor advancing ON DEVICE.  Hit lanes come back as window-relative
    keyspace offsets (relative to ``w0 * n_rules``), so the decode is
    ``w0 * n_rules + lane``.
    """

    def __init__(self, engine, gen, targets: Sequence[Target], mesh,
                 word_batch_per_device: int = 1 << 14,
                 hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.ops.rules_pipeline import (
            make_sharded_wordlist_crack_step)

        tgt = self._setup_targets(engine, gen, targets, hit_capacity,
                                  oracle, probe_ok=True)
        self.mesh = mesh
        self.step = make_sharded_wordlist_crack_step(
            engine, gen, tgt, mesh, word_batch_per_device, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))
        self.word_batch = self.super_words = self.step.super_words
        self.stride = self.super_words * gen.n_rules
        self.SUPER_CAP = shard_super_cap()

    def submit(self, unit: WorkUnit) -> PendingUnit:
        """Word-window analogue of ShardedMaskWorker.submit: full
        power-of-two runs of word windows fuse into superstep
        dispatches; the remainder uses per-window dispatches.  The
        unit-level hit flag is accumulated on device, so a hitless
        unit costs one scalar readback and the worker pipelines
        through submit_or_process like the single-device paths."""
        import jax.numpy as jnp
        w_start, w_end = word_cover_range(unit, self.gen.n_rules)
        w_end = min(w_end, self.gen.n_words)
        queued = []
        flag = None
        ws = w_start
        while not getattr(self, "_super_disabled", False):
            inner = self._super_inner((w_end - ws) // self.super_words)
            if inner < 2:
                break
            nw = inner * self.super_words
            result = self._superstep_dispatch(inner, jnp.int32(ws),
                                              jnp.int32(nw))
            if result is None:
                break                      # degraded to per-window
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append(("wshard", (ws, nw), result))
            # coverage note (ISSUE 19): word-window tiling evidence,
            # in candidate-index coordinates
            coverage.note("window", ws * self.gen.n_rules,
                          (ws + nw) * self.gen.n_rules,
                          unit=unit.unit_id, kind="wshard")
            ws += nw
        while ws < w_end:
            nw = min(self.super_words, w_end - ws)
            if nw <= 0:
                break
            result = self.step(jnp.int32(ws), jnp.int32(nw))
            # device-accumulated unit flag; see MaskWorkerBase.submit
            f = self._batch_flag(result)
            flag = f if flag is None else flag + f
            queued.append(("wshard", (ws, nw), result))
            coverage.note("window", ws * self.gen.n_rules,
                          (ws + nw) * self.gen.n_rules,
                          unit=unit.unit_id, kind="wwindow")
            ws += nw
        if flag is not None and hasattr(flag, "copy_to_host_async"):
            flag.copy_to_host_async()
        return PendingUnit(self, unit, queued, flag)

    def process(self, unit: WorkUnit) -> list[Hit]:
        return self.submit(unit).resolve()

    process._submit_based = True   # safe to pipeline via submit()

    def _super_inner(self, remaining_chunks: int) -> int:
        """Like MaskWorkerBase._super_inner, but budgeted on the
        rule-expanded lane stride (window-relative keyspace offsets
        must stay int32, and a window covers words * n_rules lanes)."""
        from dprf_tpu.ops.superstep import max_inner
        from dprf_tpu.utils import env as envreg
        if getattr(self, "_super_disabled", False) or \
                not envreg.get_bool("DPRF_SUPERSTEP"):
            return 0
        cap = max_inner(self.stride, self.SUPER_CAP)
        if remaining_chunks < self.SUPER_MIN or cap < self.SUPER_MIN:
            return 0
        return min(cap, 1 << (remaining_chunks.bit_length() - 1))

    def _aot_chunks(self) -> int:
        return self.gen.n_words // self.super_words

    def _decode_queued(self, kind: str, start, result,
                       unit: WorkUnit) -> list[Hit]:
        if kind != "wshard":
            return super()._decode_queued(kind, start, result, unit)
        ws, nw = start
        total, counts, lanes, tpos = result
        if int(total) == 0:
            return []
        if (np.asarray(counts) > lanes.shape[-1]).any():
            if nw > self.super_words:
                return self._redrive_sharded_words(ws, nw, unit)
            return self._rescan_words(ws, nw, unit)
        R = self.gen.n_rules
        base = ws * R
        hits: list[Hit] = []
        for lane, tp in zip(np.asarray(lanes).ravel(),
                            np.asarray(tpos).ravel()):
            if lane < 0:
                continue
            gidx = base + int(lane)
            if not unit.start <= gidx < unit.end:
                continue
            if self.multi and not 0 <= int(tp) < len(self._order):
                # probe-table survivor left unverified on device (see
                # sharded.probe_lane_compare): one oracle hash each
                hits.extend(self._verify_probe_lane(gidx))
                continue
            ti = int(self._order[int(tp)]) if self.multi else 0
            hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits

    def _redrive_sharded_words(self, ws: int, nw: int,
                               unit: WorkUnit) -> list[Hit]:
        """Overflowed superstep word window -> per-window device
        redrive (exact-rescan granularity stays one super-batch)."""
        import jax.numpy as jnp
        hits: list[Hit] = []
        end = ws + nw
        # coverage note (ISSUE 19): the overflowed superstep window
        # goes back through per-window dispatch -- deliberate
        # re-coverage, in candidate-index coordinates
        R = self.gen.n_rules
        coverage.note("redrive", max(unit.start, ws * R),
                      min(unit.end, end * R), unit=unit.unit_id)
        w = ws
        while w < end:
            n = min(self.super_words, end - w)
            hits.extend(self._decode_queued(
                "wshard", (w, n),
                self.step(jnp.int32(w), jnp.int32(n)), unit))
            w += n
        return hits
