"""Multi-chip worker: drives the sharded fused step over WorkUnits.

Shares all target setup and hit decoding with
runtime.worker.DeviceMaskWorker via MaskWorkerBase; the only differences
are the sharded step factory and that each step call covers an
``n_dev * batch_per_device`` super-batch whose hit buffers come back
per shard.  Lanes are super-batch-global, so ``bstart + lane`` is the
keyspace index exactly as in the single-device path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.runtime.worker import Hit, MaskWorkerBase
from dprf_tpu.runtime.workunit import WorkUnit


class ShardedMaskWorker(MaskWorkerBase):
    """Fused-pipeline worker spread over a device mesh."""

    def __init__(self, engine, gen, targets: Sequence[Target], mesh,
                 batch_per_device: int = 1 << 18, hit_capacity: int = 64,
                 oracle: Optional[HashEngine] = None):
        from dprf_tpu.parallel.sharded import make_sharded_mask_crack_step

        tgt = self._setup_targets(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.super_batch = self.stride = mesh.devices.size * batch_per_device
        self.step = make_sharded_mask_crack_step(
            engine, gen, tgt, mesh, batch_per_device, hit_capacity,
            widen_utf16=getattr(engine, "widen_utf16", False))

    def _batch_hits(self, bstart: int, result, unit: WorkUnit) -> list[Hit]:
        total, counts, lanes, tpos = result
        if int(total) == 0:
            return []
        counts_np = np.asarray(counts)
        # Check every shard BEFORE decoding any: an overflow rescan
        # replaces the whole super-batch, so mixing it with per-shard
        # decoded hits would double-report the non-overflowed shards.
        if (counts_np > self.hit_capacity).any():
            return self._rescan(bstart, unit)
        lanes_np = np.asarray(lanes)
        tpos_np = np.asarray(tpos)
        hits: list[Hit] = []
        for d in range(lanes_np.shape[0]):
            hits.extend(self._decode_lanes(bstart, lanes_np[d], tpos_np[d]))
        return hits
