"""The multi-chip fused crack step: shard_map over the keyspace mesh.

Each chip owns a contiguous `batch_per_device`-lane slice of every
super-batch: chip c decodes candidates ``base + c*batch_per_device ..
base + (c+1)*batch_per_device``, hashes and compares them locally, and
compacts its own fixed-size hit buffer.  The only cross-chip traffic is
one scalar `psum` of the per-chip hit counts (rides ICI); hit buffers
come back per-shard, so host-side traffic stays O(capacity * n_dev)
regardless of keyspace size.

This is the framework's full distributed step (SURVEY.md section 1: the
domain's parallelism is data parallelism over candidate-index ranges --
there are no layers/sequences to shard, so the keyspace axis is the
whole story).
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.parallel.mesh import SHARD_AXIS, shard_map


def make_sharded_pertarget_mask_step(gen, mesh, batch_per_device: int,
                                     digest_fn, n_params: int,
                                     hit_capacity: int = 64):
    """Generic multi-chip mask step for per-target-sweep engines
    (phpass/crypt-family/pbkdf2 style): chip c owns lane slice
    [c*B, (c+1)*B); `digest_fn(cand, lens, *params)` computes the
    digest words; the LAST step argument is the target word vector.

    step(base_digits, n_valid, *params, target) ->
        (total, counts[n_dev], lanes[n_dev, cap] super-batch-global, _)
    with replicated hit buffers (see module docstring).
    """
    flat = gen.flat_charsets
    length = gen.length
    B = batch_per_device

    def shard_fn(base_digits, n_valid, *args):
        *params, target = args
        dev = lax.axis_index(SHARD_AXIS)
        offset = (dev * B).astype(jnp.int32)
        cand = gen.decode_batch(base_digits, flat, B, lane_offset=offset)
        lens = jnp.full((B,), length, jnp.int32)
        digest = digest_fn(cand, lens, *params)
        lane_global = offset + jnp.arange(B, dtype=jnp.int32)
        found = cmp_ops.compare_single(digest, target) & \
            (lane_global < n_valid)
        cnt, lanes, tpos = cmp_ops.compact_hits(
            found, jnp.zeros((B,), jnp.int32), hit_capacity)
        lanes = jnp.where(lanes >= 0, lanes + offset, lanes)
        total = lax.psum(cnt, SHARD_AXIS)
        return (total[None],
                lax.all_gather(cnt, SHARD_AXIS),
                lax.all_gather(lanes, SHARD_AXIS),
                lax.all_gather(tpos, SHARD_AXIS))

    sharded = shard_map(
        shard_fn, mesh=mesh, in_specs=(P(),) * (3 + n_params),
        out_specs=(P(), P(), P(), P()), check_vma=False)

    @jax.jit
    def step(base_digits, n_valid, *args):
        total, counts, lanes, tpos = sharded(base_digits, n_valid, *args)
        return total[0], counts, lanes, tpos

    step.super_batch = mesh.devices.size * B
    return step


def make_sharded_mask_crack_step(
        engine, gen: MaskGenerator,
        targets: Union[jnp.ndarray, cmp_ops.TargetTable],
        mesh: Mesh, batch_per_device: int, hit_capacity: int = 64,
        widen_utf16: bool = False):
    """Build the jitted multi-chip fused step for a mask attack.

    Returns step(base_digits int32[L], n_valid int32) ->
        (total int32,                       # psum'd hit count, replicated
         counts int32[n_dev],               # per-chip hit counts
         lanes int32[n_dev, cap],           # global super-batch lane idx, -1 pad
         tpos  int32[n_dev, cap])           # sorted-table pos (multi-target)

    The super-batch is ``n_dev * batch_per_device`` lanes starting at the
    unit's base index; `n_valid` counts valid lanes over the whole
    super-batch.
    """
    flat = gen.flat_charsets
    length = gen.length
    multi = isinstance(targets, cmp_ops.TargetTable)
    n_dev = mesh.devices.size
    batch = batch_per_device

    def shard_fn(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        dev = lax.axis_index(SHARD_AXIS)
        offset = (dev * batch).astype(jnp.int32)
        cand = gen.decode_batch(base_digits, flat, batch, lane_offset=offset)
        if widen_utf16:
            cand = jnp.reshape(
                jnp.stack([cand, jnp.zeros_like(cand)], axis=-1),
                (batch, 2 * length))
            digest = engine.digest_candidates(cand, 2 * length)
        else:
            digest = engine.digest_candidates(cand, length)
        if multi:
            found, tpos = cmp_ops.compare_multi(digest, targets)
        else:
            found = cmp_ops.compare_single(digest, targets)
            tpos = jnp.zeros((batch,), jnp.int32)
        lane_global = offset + jnp.arange(batch, dtype=jnp.int32)
        found = found & (lane_global < n_valid)
        count, lanes, tpos = cmp_ops.compact_hits(found, tpos, hit_capacity)
        # Local lane -> super-batch lane (keep -1 padding).
        lanes = jnp.where(lanes >= 0, lanes + offset, lanes)
        total = lax.psum(count, SHARD_AXIS)
        # Hit buffers are all_gathered to every shard (a few hundred
        # bytes over ICI) so the outputs are REPLICATED: on a multi-host
        # mesh every process can read the full buffers from its local
        # devices -- per-shard outputs would only be addressable on the
        # host that owns the shard.
        return (total[None],
                lax.all_gather(count, SHARD_AXIS),
                lax.all_gather(lanes, SHARD_AXIS),
                lax.all_gather(tpos, SHARD_AXIS))

    sharded = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False)

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        total, counts, lanes, tpos = sharded(base_digits, n_valid)
        return total[0], counts, lanes, tpos

    step.super_batch = n_dev * batch
    return step
