"""ONE mesh-native sharded runtime: every multi-chip crack step is the
same ``shard_map`` program over the 1-D ``candidates`` mesh axis, built
here from a per-shard *compute* callback.

The runtime owns everything that used to be copy-pasted across the
per-engine ``make_sharded_*`` factories (mask / combinator / wordlist /
per-target-salted): the ``lax.axis_index`` lane-slice bookkeeping, hit
compaction, lane globalization, and the collective round.  An engine
contributes ONLY its math -- a ``compute(offset, *step_args) ->
(found, payload)`` callback over its shard's lane slice -- and gets two
programs back:

* the **per-batch step** (``step(*args)``), keeping the historical
  ``(total, counts[n_dev], lanes[n_dev, cap], tpos[n_dev, cap])``
  contract with replicated hit buffers (multi-host addressable); and
* the **superstep** (``step.superstep(inner)``), the tentpole program:
  ONE dispatch covers ``inner`` consecutive batches.  Candidates are
  generated **on device** per shard from ``base + shard offset`` (the
  only host->device traffic is the tiny base argument -- a digit
  vector or a scalar window start -- so the packed candidate tensor
  never materializes on host and the per-sweep ``h2d`` phase collapses
  to ~0), hits accumulate in a fixed ``hit_capacity`` **device-resident
  buffer** carried through the loop, and exactly ONE ``psum`` +
  ``all_gather`` round runs per superstep instead of one per batch.

Hit-buffer lane values are *window-relative*: the keyspace offset of
the hit inside the dispatched window (for wordlist steps, relative to
``w0 * n_rules``).  A window is bounded to int32 by the callers'
``ops/superstep.max_inner`` budget, so huge keyspaces never force
64-bit lane math on device; the host adds the unit base.  A shard
whose window collects more than ``hit_capacity`` hits reports the true
count (the buffer truncates, the count does not), and the workers
redrive the window through the per-batch program -- same overflow
discipline as the wide/scan paths.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.parallel.mesh import SHARD_AXIS, shard_map


def _append_hits(carry, found, payload, rel, capacity: int,
                 true_count=None):
    """Fold one shard-batch's matches into the device-resident hit
    buffer carried across a superstep.  ``rel`` maps each local lane
    to its window-relative value; slots past ``capacity`` drop (the
    count keeps the truth, so overflow is detectable on drain).

    ``true_count`` overrides the compacted count when the compute
    itself is the authority -- the TILE-compute (kernel) contract,
    where per-tile collisions inflate the count past the buffer so
    the drain path redrives the window exactly."""
    count, lanes_buf, pay_buf = carry
    c, lanes, pay = cmp_ops.compact_hits(found, payload, capacity)
    ok = lanes >= 0
    rel_lanes = jnp.where(ok, jnp.take(rel, jnp.maximum(lanes, 0)), -1)
    slots = jnp.where(ok, count + jnp.arange(capacity, dtype=jnp.int32),
                      capacity)
    lanes_buf = lanes_buf.at[slots].set(rel_lanes, mode="drop")
    pay_buf = pay_buf.at[slots].set(pay, mode="drop")
    c = c if true_count is None else true_count
    return count + c, lanes_buf, pay_buf


def make_sharded_step(compute: Callable, mesh, span_per_shard: int,
                      n_args: int, hit_capacity: int = 64,
                      globalize: Optional[Callable] = None):
    """Build the unified sharded step from a per-shard compute.

    compute(offset, *step_args) -> (found bool[K], payload int32[K]):
    the engine's whole per-shard pipeline (decode -> digest -> compare,
    **including validity masking against its n_valid argument**) over
    the lane block starting at window-relative offset ``offset``
    (int32, traced; in span units -- keyspace lanes for mask-style
    steps, words for wordlist steps).

    A compute may instead return the TILE-compute 4-tuple
    ``(found bool[G], payload int32[G], rel int32[G], count int32)``
    (the fused Pallas kernel contract, ops/pallas_mask.
    make_shard_mask_compute): ``rel`` carries each element's
    window-relative lane directly (the kernel reports one hit lane
    per grid cell, not per lane) and ``count`` is the authoritative
    hit count -- inflated past ``hit_capacity`` when a tile held more
    hits than it can report, landing in the workers' existing
    overflow redrive.  The arity is inspected at trace time, so
    legacy 2-tuple computes are untouched.

    span_per_shard: span units one shard covers per batch; one step
    call covers ``n_dev * span_per_shard`` (``step.super_span``).

    globalize(local_lane, offset) -> window-relative lane value stored
    in the hit buffer (default ``offset + local_lane``; the wordlist
    step maps its rule-major flat lanes to keyspace offsets here).

    Returns the jitted per-batch step with attributes ``super_span``,
    ``hit_capacity``, ``n_devices`` and ``superstep(inner)`` (cached
    jitted superstep programs -- one per power-of-two ``inner``).
    """
    n_dev = mesh.devices.size
    span_step = n_dev * span_per_shard
    if globalize is None:
        def globalize(lane, offset):
            return lane + offset

    def _program(inner: int):
        def shard_fn(*args):
            dev = lax.axis_index(SHARD_AXIS)
            init = (jnp.int32(0),
                    jnp.full((hit_capacity,), -1, jnp.int32),
                    jnp.full((hit_capacity,), -1, jnp.int32))

            def body(i, carry):
                offset = (i * span_step
                          + dev * span_per_shard).astype(jnp.int32)
                out = compute(offset, *args)
                if len(out) == 4:          # TILE-compute (kernel) path
                    found, payload, rel, true_count = out
                else:
                    found, payload = out
                    lanes = jnp.arange(found.shape[0], dtype=jnp.int32)
                    rel = globalize(lanes, offset)
                    true_count = None
                return _append_hits(carry, found, payload, rel,
                                    hit_capacity,
                                    true_count=true_count)

            if inner == 1:
                count, lanes, payload = body(jnp.int32(0), init)
            else:
                count, lanes, payload = lax.fori_loop(0, inner, body,
                                                      init)
            # the ONE collective round of the dispatch: a scalar psum
            # for the unit flag plus all_gathers of the fixed-size
            # buffers, so the outputs are REPLICATED -- on a multi-host
            # mesh every process reads the full buffers from its local
            # devices (per-shard outputs would only be addressable on
            # the owning host).
            total = lax.psum(count, SHARD_AXIS)
            return (total[None],
                    lax.all_gather(count, SHARD_AXIS),
                    lax.all_gather(lanes, SHARD_AXIS),
                    lax.all_gather(payload, SHARD_AXIS))

        sharded = shard_map(
            shard_fn, mesh=mesh, in_specs=(P(),) * n_args,
            out_specs=(P(), P(), P(), P()), check_vma=False)

        @jax.jit
        def step(*args):
            total, counts, lanes, payload = sharded(*args)
            return total[0], counts, lanes, payload

        return step

    step = _program(1)
    programs = {1: step}

    def superstep(inner: int):
        """The fused program covering ``inner`` consecutive batches in
        one dispatch (one collective round, device-resident hit
        accumulation).  Cached per inner -- callers pick power-of-two
        sizes so the compile count stays log-bounded."""
        p = programs.get(inner)
        if p is None:
            p = programs[inner] = _program(inner)
        return p

    step.superstep = superstep
    step.super_span = span_step
    step.hit_capacity = hit_capacity
    step.n_devices = n_dev
    return step


# ---------------------------------------------------------------------------
# compute builders: the per-family math the runtime wraps.  Wordlist
# and combinator computes live next to their single-device twins
# (ops/rules_pipeline.py, ops/combine.py); these two cover every
# digest_candidates engine and the whole per-target salted family.

def probe_lane_compare(targets, n_lanes: int):
    """Shared probe-table verify stage for sharded computes: build
    ``fn(digest, maybe) -> (found, tpos)`` over an ``n_lanes``-lane
    digest block, where ``maybe`` is the (validity-masked) Bloom
    survivor mask.  Used by the mask, wordlist, and combinator
    computes so the survivor-compaction / sentinel discipline exists
    exactly once.

    Device layout: survivors compact into a fixed buffer, their
    digests re-gather and verify exactly against the sorted table; a
    survivor overflow could hide a real hit past the buffer, so THAT
    batch degrades to sentinel-tagged maybes.  Host-verify layout
    (no exact table on device): every survivor goes back
    sentinel-tagged (tpos == num_targets, out of range) and the
    workers resolve each with one oracle hash."""
    survivors = 0
    if targets.table is not None:
        from dprf_tpu.targets import probe as probe_mod
        survivors = probe_mod.survivor_cap(targets, n_lanes)
    sentinel = targets.num_targets

    def fn(digest, maybe):
        if targets.table is None:
            return maybe, jnp.full((n_lanes,), sentinel, jnp.int32)
        n_maybe = maybe.sum(dtype=jnp.int32)
        slot = jnp.cumsum(maybe.astype(jnp.int32)) - 1
        slot = jnp.where(maybe, slot, survivors)
        surv = jnp.full((survivors,), -1, jnp.int32).at[slot].set(
            jnp.arange(n_lanes, dtype=jnp.int32), mode="drop")
        found_s, tpos_s = cmp_ops.compare_multi(
            digest[jnp.maximum(surv, 0)], targets.table)
        found_s = found_s & (surv >= 0)
        back = jnp.where(surv >= 0, surv, n_lanes)
        verified = jnp.zeros((n_lanes,), bool).at[back].set(
            found_s, mode="drop")
        tpos = jnp.zeros((n_lanes,), jnp.int32).at[back].set(
            tpos_s, mode="drop")
        overflow = n_maybe > survivors
        found = jnp.where(overflow, maybe, verified)
        tpos = jnp.where(overflow,
                         jnp.full((n_lanes,), sentinel, jnp.int32),
                         tpos)
        return found, tpos

    return fn


def make_sharded_kernel_mask_step(engine_name: str, gen,
                                  target_words, mesh,
                                  batch_per_device: int,
                                  hit_capacity: int = 64,
                                  sub=None, interpret: bool = False,
                                  probe_fp: Optional[float] = None):
    """Mask attack with the FUSED PALLAS KERNEL as the per-shard
    compute: the whole decode -> hash -> compare(+probe) chain runs
    in VMEM per shard, and the sharded superstep drives it with
    on-device generation from ``base + shard/window offset``.

    Same step/superstep contract as make_sharded_mask_step; the hit
    payload is tpos 0 (single target) or the SENTINEL num_targets
    (multi target -- every kernel-probe survivor is host-verified
    with one oracle hash, see ops/pallas_mask.make_shard_mask_compute).
    batch_per_device must be tile-aligned (check_batch enforces)."""
    from dprf_tpu.ops import pallas_mask

    compute = pallas_mask.make_shard_mask_compute(
        engine_name, gen, target_words, batch_per_device, hit_capacity,
        sub=sub, interpret=interpret, probe_fp=probe_fp)
    step = make_sharded_step(compute, mesh, batch_per_device, 2,
                             hit_capacity=hit_capacity)
    step.super_batch = step.super_span
    step.tile = compute.tile
    return step


def make_sharded_mask_step(engine, gen, targets, mesh,
                           batch_per_device: int, hit_capacity: int = 64,
                           widen_utf16: bool = False):
    """Mask attack through the unified runtime: any engine exposing
    ``digest_candidates`` (single- or multi-target).

    step(base_digits int32[L], n_valid int32) ->
        (total, counts[n_dev], lanes[n_dev, cap], tpos[n_dev, cap])
    with window-relative lanes; ``step.superstep(inner)`` fuses inner
    batches per dispatch (on-device generation via ``decode_batch``'s
    traced lane_offset -- no host digits per batch, no reshard).

    Bulk lists arrive as a ``targets.probe.ProbeTable``: its Bloom
    bitmap and exact-verify buckets are closure constants of the
    shard function, so they ride through every superstep as
    REPLICATED device state (no per-dispatch transfer).  Lanes the
    device cannot verify exactly -- the host-verify layout, or a
    survivor-buffer overflow -- come back with target pos ==
    num_targets (out of range), which the workers' lane decode
    resolves with one oracle hash each.
    """
    from dprf_tpu.targets import probe as probe_mod

    flat = gen.flat_charsets
    length = gen.length
    B = batch_per_device
    multi = isinstance(targets, cmp_ops.TargetTable)
    probe = isinstance(targets, probe_mod.ProbeTable)
    _probe_compute = probe_lane_compare(targets, B) if probe else None

    def compute(offset, base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, B, lane_offset=offset)
        if widen_utf16:
            cand = jnp.reshape(
                jnp.stack([cand, jnp.zeros_like(cand)], axis=-1),
                (B, 2 * length))
            digest = engine.digest_candidates(cand, 2 * length)
        else:
            digest = engine.digest_candidates(cand, length)
        lane = offset + jnp.arange(B, dtype=jnp.int32)
        if probe:
            return _probe_compute(
                digest, probe_mod.bloom_maybe(digest, targets)
                & (lane < n_valid))
        if multi:
            found, tpos = cmp_ops.compare_multi(digest, targets)
        else:
            found = cmp_ops.compare_single(digest, targets)
            tpos = jnp.zeros((B,), jnp.int32)
        return found & (lane < n_valid), tpos

    step = make_sharded_step(compute, mesh, B, 2,
                             hit_capacity=hit_capacity)
    step.super_batch = step.super_span
    return step


def make_sharded_pertarget_step(gen, mesh, batch_per_device: int,
                                digest_fn, n_params: int,
                                hit_capacity: int = 64):
    """Per-target-sweep engines (phpass / crypt family / pbkdf2 /
    mscache / hmac / salted / krb5 style) through the unified runtime:
    ``digest_fn(cand, lens, *params)`` computes the digest words; the
    LAST step argument is the target word vector.

    step(base_digits, n_valid, *params, target) ->
        (total, counts[n_dev], lanes[n_dev, cap], _)
    """
    flat = gen.flat_charsets
    length = gen.length
    B = batch_per_device

    def compute(offset, base_digits, n_valid, *args):
        *params, target = args
        cand = gen.decode_batch(base_digits, flat, B, lane_offset=offset)
        lens = jnp.full((B,), length, jnp.int32)
        digest = digest_fn(cand, lens, *params)
        lane = offset + jnp.arange(B, dtype=jnp.int32)
        found = cmp_ops.compare_single(digest, target) & (lane < n_valid)
        return found, jnp.zeros((B,), jnp.int32)

    step = make_sharded_step(compute, mesh, B, 3 + n_params,
                             hit_capacity=hit_capacity)
    step.super_batch = step.super_span
    return step
