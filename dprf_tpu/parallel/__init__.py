"""Multi-chip execution: mesh construction + the ONE sharded runtime.

Parallelism in this domain is pure data parallelism over the keyspace
(SURVEY.md section 1): every chip owns a contiguous lane range of each
super-batch, decodes/hashes/compares locally, and only fixed-size hit
buffers plus a psum'd hit count cross chip boundaries (over ICI) --
once per superstep, not per batch (parallel/sharded.py).
"""

from dprf_tpu.parallel.mesh import make_mesh
from dprf_tpu.parallel.sharded import (make_sharded_mask_step,
                                       make_sharded_pertarget_step,
                                       make_sharded_step)
from dprf_tpu.parallel.worker import ShardedMaskWorker

__all__ = ["make_mesh", "make_sharded_step", "make_sharded_mask_step",
           "make_sharded_pertarget_step", "ShardedMaskWorker"]
