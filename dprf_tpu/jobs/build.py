"""Server-side job construction (op_job_submit) and per-job resume.

A submitted spec is CLIENT data: the coordinator rebuilds the whole
job from it -- parse the target lines with the named engine, build the
generator (wordlist/rule paths are read on the COORDINATOR host, same
placement contract as `dprf serve`), derive max_len, compute the
fingerprint -- and only then admits it to the scheduler.  The
resulting wire job is byte-for-byte the shape `dprf serve` ships at
hello, so `cli.cmd_worker`'s rebuild-and-fingerprint-check path works
unchanged for scheduler-assigned jobs.

``restore_jobs`` is the resume half: `dprf serve --restore` replays
the session journal's job records (spec + completed intervals + hits
+ last state) back into a fresh scheduler, so a coordinator restart
loses no tenant's coverage.
"""

from __future__ import annotations

from typing import Optional

from dprf_tpu.jobs.scheduler import CANCELLED, DONE, PAUSED

#: spec keys a submission must carry; everything else has defaults
REQUIRED_SPEC_KEYS = ("engine", "attack", "attack_arg", "targets")

DEFAULT_UNIT_SIZE = 1 << 22
DEFAULT_HIT_CAP = 64


def build_job_runtime(spec: dict, job_id: str, log=None,
                      lease_timeout: float = 300.0, registry=None,
                      recorder=None, completed=None,
                      expect_digest=None):
    """Wire spec -> (wire_job, dispatcher, targets, verifier).

    Raises ValueError on a malformed spec (missing keys, unparsable
    targets, generator construction failure, or a client-supplied
    fingerprint that disagrees with the server-side rebuild).
    ``completed`` (resume): prior coverage intervals the dispatcher is
    rebuilt around; ``expect_digest`` is the journal's coverage digest
    for them -- the rebuilt ledger must reproduce it (ISSUE 19), or
    the resume is refused rather than sweeping around silent holes.
    """
    from dprf_tpu import cli as _cli
    from dprf_tpu import get_engine
    from dprf_tpu.runtime.dispatcher import Dispatcher
    from dprf_tpu.runtime.session import job_fingerprint
    from dprf_tpu.utils.hashlist import parse_lines
    from dprf_tpu.utils.logging import DEFAULT as _default_log

    log = log or _default_log
    if not isinstance(spec, dict):
        raise ValueError("job spec must be a dict")
    for k in REQUIRED_SPEC_KEYS:
        if k not in spec:
            raise ValueError(f"job spec missing {k!r}")
    engine = get_engine(str(spec["engine"]), device="cpu")
    lines = spec["targets"]
    if not isinstance(lines, list) or not lines:
        raise ValueError("job spec needs a non-empty 'targets' list")
    hl = parse_lines(engine, [str(ln) for ln in lines])
    for no, _text, err in hl.skipped:
        log.warn("job submit: skipping target line", line=no, error=err)
    if not hl.targets:
        raise ValueError("no valid targets in the submitted hashlist")
    customs = {int(i): bytes.fromhex(v)
               for i, v in (spec.get("customs") or {}).items()}
    attack = str(spec["attack"])
    # device only shapes wordlist packing width (max_len); the job
    # itself is device-agnostic -- workers pick their own backend
    device = str(spec.get("device") or "jax")
    gen, attack_desc, max_len = _cli._build_gen(
        attack, str(spec["attack_arg"]), customs, spec.get("rules"),
        None, engine, device, log, markov=spec.get("markov"))
    fingerprint = job_fingerprint(engine.name, attack_desc,
                                  gen.keyspace,
                                  [t.digest for t in hl.targets])
    theirs = spec.get("fingerprint")
    if theirs is not None and theirs != fingerprint:
        raise ValueError(
            f"submitted fingerprint {theirs!r} disagrees with the "
            f"coordinator's rebuild {fingerprint!r} (divergent "
            "wordlist/rules/stats content on this host?)")
    their_targets = spec.get("targets_fingerprint")
    if their_targets is not None:
        from dprf_tpu.targets import TargetStore
        store = TargetStore(engine, hl.targets, hl.skipped,
                            hl.duplicates)
        if their_targets != store.fingerprint:
            raise ValueError(
                f"submitted targets fingerprint {their_targets!r} "
                f"disagrees with the coordinator's rebuild "
                f"{store.fingerprint!r} (target lines corrupted or "
                "reordered with losses in transit?)")
    from dprf_tpu.generators.order import build_order
    order_kind = str(spec.get("order") or "index")
    if order_kind != "index" and not spec.get("markov"):
        raise ValueError(
            "--order markov needs trained stats (submit with "
            "--markov): without frequency-reordered charsets the rank "
            "order is meaningless")
    try:
        order_split = (int(spec["order_split"])
                       if spec.get("order_split") else None)
    except (TypeError, ValueError):
        order_split = None
    # the coordinator resolves the split ONCE (env knobs or the
    # client's explicit value) and pins it on the wire job below, so
    # every worker rebuilds the identical bijection regardless of its
    # own environment
    order = build_order(order_kind, gen, split=order_split)

    unit_size = _cli._align_unit_size(
        int(spec.get("unit_size") or DEFAULT_UNIT_SIZE), attack, gen)
    try:
        batch = int(spec.get("batch") or _cli.DEFAULT_BATCH)
    except (TypeError, ValueError):
        batch = _cli.DEFAULT_BATCH
    hit_cap = int(spec.get("hit_cap") or DEFAULT_HIT_CAP)

    kw = {"lease_timeout": lease_timeout, "registry": registry,
          "recorder": recorder, "job_id": job_id, "order": order}
    try:
        unit_seconds = float(spec.get("unit_seconds", 20.0))
    except (TypeError, ValueError):
        unit_seconds = 20.0
    if unit_seconds > 0:
        from dprf_tpu.tune import AdaptiveUnitSizer
        align = gen.n_rules if attack == "wordlist" else 1
        kw["sizer"] = AdaptiveUnitSizer(
            unit_size, target_seconds=unit_seconds, align=align,
            min_unit=max(align, min(unit_size, 1 << 10)),
            registry=registry)
    if completed:
        dispatcher = Dispatcher.from_completed(
            gen.keyspace, unit_size, list(completed),
            expect_digest=expect_digest, **kw)
    else:
        dispatcher = Dispatcher(gen.keyspace, unit_size, **kw)

    targets = hl.targets

    def verifier(ti: int, plain: bytes) -> bool:
        if engine.verify(plain, targets[ti]):
            return True
        log.warn("rejected unverifiable hit", job=job_id,
                 target=targets[ti].raw[:32])
        return False

    # the exact wire shape cmd_serve ships at hello -- a worker's
    # rebuild-and-fingerprint path is identical for every job source
    wire_job = {
        "engine": engine.name,
        "attack": attack,
        "attack_arg": str(spec["attack_arg"]),
        "customs": {str(i): v.hex() for i, v in customs.items()},
        "rules": spec.get("rules"),
        "markov": spec.get("markov"),
        "max_len": max_len,
        "targets": [t.raw for t in targets],
        "keyspace": gen.keyspace,
        "unit_size": unit_size,
        # persisted so a journal-restored rebuild sizes units exactly
        # like the original admission did
        "unit_seconds": unit_seconds,
        "batch": batch,
        "hit_cap": hit_cap,
        # candidate order + the resolved bijection split: workers
        # rebuild the rank<->index map from these two fields alone
        "order": order_kind,
        "order_split": order.split if order is not None else 0,
        # sharding request: workers shard this job's units over N of
        # their local chips (cli.cmd_worker; their --devices overrides)
        "devices": max(1, int(spec.get("devices") or 1)),
        "fingerprint": fingerprint,
    }
    return wire_job, dispatcher, targets, verifier


def restore_jobs(state, jobs: dict, log=None,
                 lease_timeout: float = 300.0) -> int:
    """Replay a session journal's scheduler-submitted job records
    (``SessionState.jobs``; the DEFAULT job is restored by the serve
    front-end's existing single-job path) into ``state``'s scheduler.
    Returns the number of jobs restored."""
    from dprf_tpu.utils.logging import DEFAULT as _default_log

    log = log or _default_log
    n = 0
    for jid in sorted(jobs, key=_job_sort_key):
        rec = jobs[jid]
        spec = rec.get("spec")
        if not spec:
            log.warn("journaled job has no spec; skipping", job=jid)
            continue
        try:
            wire, dispatcher, targets, verifier = build_job_runtime(
                spec, jid, log=log, lease_timeout=lease_timeout,
                registry=state.registry, recorder=state.tracer,
                completed=rec.get("completed") or (),
                expect_digest=rec.get("coverage_digest"))
        except (ValueError, OSError, KeyError) as e:
            log.warn("journaled job failed to rebuild; skipping",
                     job=jid, error=str(e))
            continue
        with state.lock:
            job = state.scheduler.add(
                wire, dispatcher, len(targets), verifier=verifier,
                owner=str(rec.get("owner") or "?"),
                priority=int(rec.get("priority") or 1),
                quota=rec.get("quota"), rate=rec.get("rate"),
                job_id=jid)
            for h in rec.get("hits") or ():
                try:
                    job.record_hit(int(h["target"]), int(h["index"]),
                                   bytes.fromhex(h["plaintext"]))
                except (KeyError, ValueError, TypeError):
                    continue
            last = rec.get("state")
            if last == CANCELLED:
                state.scheduler.cancel(jid)
            elif last == PAUSED:
                state.scheduler.pause(jid)
            elif last == DONE:
                state.scheduler.refresh_job_state(job)
        n += 1
        done, total = dispatcher.progress()
        log.info("restored job", job=jid, covered=done, total=total,
                 hits=len(job.hits), state=job.state)
    state.refresh_found_gauge()
    return n


def _job_sort_key(jid: str):
    try:
        return (0, int(jid.lstrip("j")))
    except ValueError:
        return (1, jid)
