"""Job records + the weighted fair-share scheduler.

One Job wraps everything the single-job CoordinatorState used to own
directly: a spec (the wire job description workers rebuild from), a
Dispatcher (its OWN unit ledger -- per-job keyspace accounting, stale
guards, and poison parking come for free), the per-job found set and
an ordered hit buffer for cursor-based delivery (``op_hits_pull``),
the CPU-oracle verifier, and the tenant knobs: owner, priority, quota,
lease rate.

Selection is STRIDE SCHEDULING (deterministic weighted fair share):
every job carries a ``pass`` value; each lease picks the runnable job
with the smallest pass and advances it by 1/weight, so over any window
the lease counts of two runnable jobs approach their weight ratio
exactly -- testable to tight bounds, no RNG.  A job with nothing
leasable right now (all of its remaining work outstanding) is skipped
WITHOUT advancing its pass, so it is not penalized for a full ledger.

Limits:

  - ``quota``: a cap on keyspace indices the job may SWEEP.  A job
    whose covered + outstanding indices reach the quota stops leasing;
    once covered alone reaches it, the job is DONE (reason "quota").
    The cap is accounting, not geometry: the dispatcher keeps the full
    keyspace, so raising the quota later needs no re-split.
  - ``rate``: a token-bucket lease rate (units/second, burst = one
    second's worth, minimum 1).  The cheap fleet-protection knob: a
    low-priority bulk job can be pinned to a trickle no matter how
    idle the fleet is.
  - ``owner_quotas`` (ISSUE 13 satellite): per-OWNER aggregate sweep
    caps enforced across every job the owner holds -- on submit
    (``owner_quota_error``) and on lease (``_leasable``), so a tenant
    cannot dodge its cap by splitting work over many jobs.  An
    owner-capped job stays RUNNING (like pause, raising the quota is
    operator action the fleet keeps polling for).

Thread model: the scheduler is driven entirely under the caller's lock
(rpc.CoordinatorState.lock) -- same contract as the Dispatcher it
multiplexes, declared ``<extern>`` below for the `dprf check` locks
analyzer.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.telemetry import get_registry
from dprf_tpu.utils import env as envreg

#: seconds between age-based GC sweeps (the TTL itself is the
#: DPRF_JOB_TTL_S knob; this only rate-limits the table scan on the
#: lease path)
GC_CHECK_INTERVAL_S = 30.0

#: per-job SLO accounting (ISSUE 10, driven by update_slos on the
#: health-plane evaluation loop): coverage-rate EWMA smoothing, and
#: the consecutive flat windows after which a RUNNING job counts as
#: STALLED (the dprf_job_stalled gauge the job_stalled alert rule
#: thresholds)
SLO_RATE_ALPHA = 0.4
STALL_WINDOWS = 3

#: job lifecycle states
QUEUED = "queued"
RUNNING = "running"
PAUSED = "paused"
DONE = "done"
CANCELLED = "cancelled"

STATES = (QUEUED, RUNNING, PAUSED, DONE, CANCELLED)

#: lock-discipline declaration (`dprf check` locks analyzer): every
#: concurrent caller (the RPC handler threads) serializes through
#: CoordinatorState.lock, which declares its ``scheduler`` reference
#: guarded -- exactly the Dispatcher contract.  ``<extern>`` also
#: forbids this class from acquiring a declared lock itself.
GUARDED_BY = {"JobScheduler": {"<extern>": ()}}


class Job:
    """One tenant job: spec + ledger + results + limits.  Pure data
    plus derived accessors; all mutation happens through the
    scheduler (under the caller's lock)."""

    __slots__ = ("job_id", "spec", "dispatcher", "n_targets",
                 "verifier", "owner", "priority", "quota", "rate",
                 "state", "done_reason", "created", "found", "hits",
                 "rejected", "leases", "pass_value", "_tokens",
                 "_token_t", "finished_at", "first_hit_at",
                 "last_lease_at", "_slo_prev", "_slo_rate", "_slo_t",
                 "_slo_flat")

    def __init__(self, job_id: str, spec: dict, dispatcher: Dispatcher,
                 n_targets: int, verifier: Optional[Callable] = None,
                 owner: str = "local", priority: int = 1,
                 quota: Optional[int] = None,
                 rate: Optional[float] = None,
                 created: float = 0.0):
        self.job_id = job_id
        self.spec = spec
        self.dispatcher = dispatcher
        self.n_targets = n_targets
        #: (target_index, plaintext) -> bool; None = trust reports
        self.verifier = verifier
        self.owner = owner
        self.priority = max(1, int(priority))
        self.quota = None if quota is None else max(0, int(quota))
        self.rate = None if rate is None else max(0.001, float(rate))
        self.state = QUEUED
        self.done_reason: Optional[str] = None
        self.created = created
        self.found: dict = {}            # target_index -> plaintext
        #: ordered hit buffer for op_hits_pull: the cursor is the list
        #: index, so a pull client never re-reads or skips a hit
        self.hits: list = []
        self.rejected = 0
        self.leases = 0                  # fair-share accounting
        self.pass_value = 0.0            # stride scheduler state
        self._tokens = 1.0               # lease-rate token bucket
        self._token_t: Optional[float] = None
        #: when the job entered a terminal state (scheduler clock) --
        #: the age-based GC's reference point
        self.finished_at: Optional[float] = None
        #: SLO accounting (ISSUE 10, update_slos): time-to-first-hit,
        #: lease-wait, and the coverage-rate EWMA the per-job ETA and
        #: stall detector derive from
        self.first_hit_at: Optional[float] = None
        self.last_lease_at: Optional[float] = None
        self._slo_prev = 0
        self._slo_rate: Optional[float] = None
        self._slo_t: Optional[float] = None
        self._slo_flat = 0

    @property
    def weight(self) -> float:
        return float(self.priority)

    def terminal(self) -> bool:
        return self.state in (DONE, CANCELLED)

    def runnable(self) -> bool:
        return self.state in (QUEUED, RUNNING)

    def covered(self) -> int:
        return self.dispatcher.progress()[0]

    def swept_or_leased(self) -> int:
        """Indices covered plus indices currently out on leases --
        what the quota is enforced against (an aheaded lease counts;
        otherwise a deep pipeline would overshoot the quota by a
        fleet's worth of units)."""
        return self.covered() + self.dispatcher.outstanding_indices()

    def record_hit(self, target_index: int, cand_index: int,
                   plaintext: bytes) -> bool:
        """Append a VERIFIED hit; returns False for duplicates."""
        if target_index in self.found:
            return False
        self.found[target_index] = plaintext
        self.hits.append({"seq": len(self.hits),
                          "target": target_index,
                          "cand": cand_index,
                          "plaintext": plaintext.hex()})
        return True

    def summary(self) -> dict:
        """The op_job_list / op_job_status record (no spec: that ships
        only from op_job_status, where one job was asked for)."""
        done, total = self.dispatcher.progress()
        return {"id": self.job_id, "owner": self.owner,
                "priority": self.priority, "state": self.state,
                "reason": self.done_reason, "done": done,
                "total": total, "quota": self.quota, "rate": self.rate,
                "found": len(self.found), "targets": self.n_targets,
                "rejected": self.rejected, "leases": self.leases,
                "outstanding": self.dispatcher.outstanding_count(),
                "parked": self.dispatcher.parked_count()}


class JobScheduler:
    """Queue of Jobs + stride fair-share lease selection.  Driven
    under the owner's lock (see GUARDED_BY above)."""

    #: jobs a coordinator will hold at once (ids are server-assigned
    #: -- "j0", "j1", ... -- so the per-job metric label cardinality
    #: is bounded by this, not by client behavior)
    MAX_JOBS = 64

    def __init__(self, registry=None, clock=None, owner_quotas=None):
        self._jobs: dict = {}            # job_id -> Job, insert-ordered
        self._next_id = 0
        self._clock = clock or time.monotonic
        self._gc_next = 0.0
        #: per-OWNER aggregate sweep quotas (ISSUE 13 satellite):
        #: {owner: max keyspace indices the owner's jobs may sweep,
        #: summed across all of them}.  Enforced on submit
        #: (owner_quota_error) and on lease (_leasable) -- a tenant
        #: cannot dodge its cap by splitting work over many jobs.
        self.owner_quotas: dict = dict(owner_quotas or {})
        m = get_registry(registry)
        self._g_jobs = m.gauge(
            "dprf_jobs", "jobs known to the scheduler, by state",
            labelnames=("state",))
        self._m_job_hits = m.counter(
            "dprf_job_hits_total", "verified cracks per job",
            labelnames=("job",))
        self._m_gc = m.counter(
            "dprf_jobs_gc_total",
            "terminal jobs reaped by the age-based GC "
            "(DPRF_JOB_TTL_S)")
        # per-job SLO surface (ISSUE 10): published by update_slos on
        # the health-plane evaluation loop, consumed by the alert
        # engine's job_stalled rule and the dprf health CLI
        self._g_eta = m.gauge(
            "dprf_job_eta_seconds",
            "remaining keyspace / the coverage-rate EWMA: when this "
            "job finishes at the current fleet pace",
            labelnames=("job",))
        self._g_stalled = m.gauge(
            "dprf_job_stalled",
            "1 when a RUNNING job's coverage stayed flat for "
            "STALL_WINDOWS consecutive evaluation windows",
            labelnames=("job",))
        self._g_ttfh = m.gauge(
            "dprf_job_ttfh_seconds",
            "time from job admission to its first verified hit",
            labelnames=("job",))
        self._h_lease_wait = m.histogram(
            "dprf_job_lease_wait_seconds",
            "interval between consecutive lease grants to a job "
            "(from admission, for the first) -- fair-share latency, "
            "p95 readable from the buckets",
            labelnames=("job",))
        self._refresh_states()

    # -- registry --------------------------------------------------------

    def _refresh_states(self) -> None:
        counts = {s: 0 for s in STATES}
        for j in self._jobs.values():
            counts[j.state] += 1
        for s, n in counts.items():
            self._g_jobs.set(n, state=s)

    def full(self) -> bool:
        """Admission check BEFORE the expensive server-side build
        (op_job_submit): a rejected submission must not have parsed
        targets, built a generator, or registered per-job metric
        series first."""
        return len(self._jobs) >= self.MAX_JOBS

    def reserve_id(self) -> str:
        """Claim the next job id (call under the lock; the expensive
        spec build then happens OUTSIDE it against a stable id)."""
        jid = f"j{self._next_id}"
        self._next_id += 1
        return jid

    def add(self, spec: dict, dispatcher: Dispatcher, n_targets: int,
            verifier: Optional[Callable] = None, owner: str = "local",
            priority: int = 1, quota: Optional[int] = None,
            rate: Optional[float] = None,
            job_id: Optional[str] = None, state: str = RUNNING) -> Job:
        if len(self._jobs) >= self.MAX_JOBS:
            raise ValueError(f"job table full ({self.MAX_JOBS} jobs)")
        if job_id is None:
            job_id = self.reserve_id()
        elif job_id in self._jobs:
            raise ValueError(f"job id {job_id!r} already exists")
        else:
            # restored ids ("j3") must not collide with future ones
            try:
                n = int(job_id.lstrip("j"))
                self._next_id = max(self._next_id, n + 1)
            except ValueError:
                pass
        job = Job(job_id, spec, dispatcher, n_targets,
                  verifier=verifier, owner=owner, priority=priority,
                  quota=quota, rate=rate, created=self._clock())
        job.state = state
        # a late-submitted job starts at the current pass frontier:
        # fair share is forward-looking, not a retroactive catch-up
        # burst that would starve every older job
        passes = [j.pass_value for j in self._jobs.values()
                  if j.runnable()]
        job.pass_value = min(passes) if passes else 0.0
        self._jobs[job_id] = job
        self._refresh_states()
        return job

    def get(self, job_id: Optional[str]) -> Optional[Job]:
        if job_id is None:
            return self.default()
        return self._jobs.get(job_id)

    def default(self) -> Optional[Job]:
        """The first job -- what a pre-multi-tenant client that never
        names a job id is talking about."""
        for j in self._jobs.values():
            return j
        return None

    def jobs(self) -> list:
        return list(self._jobs.values())

    # -- per-owner aggregate quotas (ISSUE 13 satellite) -------------------

    def owner_swept(self, owner: str) -> int:
        """Indices covered plus outstanding across ALL of an owner's
        non-cancelled jobs -- the quantity the aggregate quota caps
        (same swept-or-leased accounting as the per-job quota, so a
        deep pipeline cannot overshoot it by a fleet's worth)."""
        return sum(j.swept_or_leased() for j in self._jobs.values()
                   if j.owner == owner and j.state != CANCELLED)

    def _owner_capped(self, owner: str) -> bool:
        quota = self.owner_quotas.get(owner)
        return quota is not None and self.owner_swept(owner) >= quota

    def owner_quota_error(self, owner: str) -> Optional[str]:
        """Submit-time admission check: a rejection string when the
        owner's aggregate quota is already consumed, else None (the
        expensive server-side build should not even start)."""
        quota = self.owner_quotas.get(owner)
        if quota is None:
            return None
        swept = self.owner_swept(owner)
        if swept < quota:
            return None
        return (f"owner {owner!r} aggregate quota exhausted "
                f"({swept}/{quota} indices swept or leased across "
                "its jobs)")

    # -- lease-time selection --------------------------------------------

    def _leasable(self, job: Job, now: float) -> bool:
        if not job.runnable():
            return False
        if job.quota is not None and job.swept_or_leased() >= job.quota:
            return False
        if self._owner_capped(job.owner):
            return False
        if not job.dispatcher.leasable():
            return False
        if job.rate is not None:
            if job._token_t is not None:
                job._tokens = min(max(1.0, job.rate),
                                  job._tokens
                                  + (now - job._token_t) * job.rate)
            job._token_t = now
            if job._tokens < 1.0:
                return False
        return True

    def lease_many(self, worker_id: str, n: int) -> list:
        """Up to n (job, unit) pairs for one worker, stride-selected
        across every leasable job."""
        out: list = []
        now = self._clock()
        skip: set = set()
        for _ in range(max(0, int(n))):
            best = None
            for j in self._jobs.values():
                if j.job_id in skip or not self._leasable(j, now):
                    continue
                if best is None or (j.pass_value, j.created) \
                        < (best.pass_value, best.created):
                    best = j
            if best is None:
                break
            unit = best.dispatcher.lease(worker_id)
            if unit is None:
                # everything left is outstanding: skip without a pass
                # advance (no penalty for a full ledger)
                skip.add(best.job_id)
                continue
            if best.state == QUEUED:
                best.state = RUNNING
                self._refresh_states()
            best.pass_value += 1.0 / best.weight
            best.leases += 1
            # lease-wait SLO: how long this job sat between grants
            # (fair-share latency a tenant actually feels)
            self._h_lease_wait.observe(
                max(0.0, now - (best.last_lease_at
                                if best.last_lease_at is not None
                                else best.created)),
                job=best.job_id)
            best.last_lease_at = now
            if best.rate is not None:
                best._tokens -= 1.0
            out.append((best, unit))
        return out

    def reap_expired(self) -> int:
        n = 0
        for j in self._jobs.values():
            if not j.terminal():
                n += j.dispatcher.reap_expired()
        return n

    def outstanding_for(self, worker_id: str) -> int:
        return sum(j.dispatcher.outstanding_for(worker_id)
                   for j in self._jobs.values() if not j.terminal())

    def total_outstanding(self) -> int:
        return sum(j.dispatcher.outstanding_count()
                   for j in self._jobs.values() if not j.terminal())

    # -- completion / termination ----------------------------------------

    def complete(self, job: Job, unit_id: int,
                 elapsed: Optional[float] = None,
                 worker_id: Optional[str] = None) -> bool:
        """Route a completion to the job's ledger.  A CANCELLED job
        drops the report outright -- a mid-flight cancel must not keep
        counting coverage (or hits) from units leased before it."""
        if job.state == CANCELLED:
            return False
        landed = job.dispatcher.complete(unit_id, elapsed=elapsed,
                                         worker_id=worker_id)
        if landed:
            self.refresh_job_state(job)
        return landed

    def fail(self, job: Job, unit_id: int,
             worker_id: Optional[str] = None) -> bool:
        if job.state == CANCELLED:
            return False
        return job.dispatcher.fail(unit_id, worker_id=worker_id)

    def record_hit(self, job: Job, target_index: int, cand_index: int,
                   plaintext: bytes) -> bool:
        new = job.record_hit(target_index, cand_index, plaintext)
        if new:
            if job.first_hit_at is None:
                # time-to-first-hit SLO anchor (update_slos publishes)
                job.first_hit_at = self._clock()
            self._m_job_hits.inc(job=job.job_id)
            self.refresh_job_state(job)
        return new

    def refresh_job_state(self, job: Job) -> None:
        """Promote a job to DONE when it has nothing left to do:
        every target cracked, keyspace (minus parked) covered, or the
        sweep quota reached."""
        if job.terminal() or job.state == PAUSED:
            return
        if job.n_targets and len(job.found) >= job.n_targets:
            job.state, job.done_reason = DONE, "all targets found"
        elif job.dispatcher.done():
            job.state, job.done_reason = DONE, "keyspace exhausted"
        elif job.quota is not None and job.covered() >= job.quota:
            job.state, job.done_reason = DONE, "quota reached"
        else:
            return
        job.finished_at = self._clock()
        self._refresh_states()

    # -- per-job SLOs (ISSUE 10) ------------------------------------------

    def update_slos(self) -> None:
        """One SLO accounting pass, driven by the health-plane
        evaluation loop (CoordinatorState.health_tick, under the
        owner's lock like every other scheduler call): fold each
        job's coverage delta into its rate EWMA, publish the derived
        ETA, time-to-first-hit, and the STALL flag -- coverage flat
        for STALL_WINDOWS consecutive windows while RUNNING (the
        "job stalled" first-class condition)."""
        now = self._clock()
        for j in self._jobs.values():
            if j.first_hit_at is not None:
                # published even for terminal jobs: a job that cracked
                # everything instantly still has a TTFH worth reading
                self._g_ttfh.set(j.first_hit_at - j.created,
                                 job=j.job_id)
            if j.terminal():
                # clear the live-progress gauges: a cancelled job must
                # not advertise a frozen ETA/stall forever on /metrics
                if j._slo_flat:
                    j._slo_flat = 0
                    self._g_stalled.set(0, job=j.job_id)
                if j._slo_rate is not None:
                    j._slo_rate = None
                    self._g_eta.set(0, job=j.job_id)
                continue
            covered = j.covered()
            if j._slo_t is None:
                j._slo_t = now
                j._slo_prev = covered
                continue
            dt = now - j._slo_t
            if dt <= 0:
                continue
            delta = covered - j._slo_prev
            rate = delta / dt
            j._slo_rate = (rate if j._slo_rate is None
                           else j._slo_rate
                           + SLO_RATE_ALPHA * (rate - j._slo_rate))
            j._slo_prev = covered
            j._slo_t = now
            total = j.dispatcher.progress()[1]
            if j._slo_rate and j._slo_rate > 0:
                self._g_eta.set(max(0.0, (total - covered)
                                    / j._slo_rate), job=j.job_id)
            # a PAUSED job's flat coverage is policy, not a stall
            j._slo_flat = (j._slo_flat + 1
                           if j.state == RUNNING and delta <= 0
                           else 0)
            self._g_stalled.set(
                1 if j._slo_flat >= STALL_WINDOWS else 0,
                job=j.job_id)

    def slo_summaries(self) -> list:
        """Per-job SLO rows for op_health / `dprf health`."""
        out = []
        for j in self._jobs.values():
            covered, total = j.dispatcher.progress()
            # terminal jobs have no live rate/ETA to report (their
            # gauges are cleared by update_slos for the same reason)
            rate = None if j.terminal() else j._slo_rate
            eta = None
            if j.terminal():
                eta = None
            elif total <= covered:
                eta = 0.0
            elif rate and rate > 0:
                eta = round((total - covered) / rate, 1)
            out.append({
                "job": j.job_id, "owner": j.owner, "state": j.state,
                "covered": covered, "total": total,
                "rate_ips": round(rate, 3) if rate else None,
                "eta_s": eta,
                "stalled": j._slo_flat >= STALL_WINDOWS,
                "ttfh_s": (round(j.first_hit_at - j.created, 3)
                           if j.first_hit_at is not None else None),
                "found": len(j.found), "targets": j.n_targets})
        return out

    # -- admin -----------------------------------------------------------

    def retry_parked(self) -> int:
        """Requeue every job's parked units with a fresh retry budget
        (the op_retry_parked admin op).  A job the park-as-unreachable
        rule already marked DONE ("keyspace exhausted") comes back to
        RUNNING when its ranges become reachable again -- otherwise
        the requeued units could never lease."""
        n = 0
        for j in self._jobs.values():
            if j.state == CANCELLED:
                continue
            requeued = j.dispatcher.retry_parked()
            n += requeued
            if requeued and j.state == DONE \
                    and not j.dispatcher.done():
                j.state, j.done_reason = RUNNING, None
                j.finished_at = None
        if n:
            self._refresh_states()
        return n

    def maybe_gc(self, keep=(), force: bool = False) -> list:
        """Age-based job GC (``DPRF_JOB_TTL_S``): reap DONE/CANCELLED
        jobs whose terminal age exceeds the TTL, so a long-lived
        fleet's table never wedges at MAX_JOBS.  Rate-limited to one
        scan per GC_CHECK_INTERVAL_S unless ``force`` (op_job_submit
        forces when the table is full).  ``keep`` protects job ids
        that must never leave the table (the default job: the serve
        front-end aliases its found dict).  Returns the reaped Jobs
        so the caller can journal ``job_gc`` records."""
        ttl = envreg.get_float("DPRF_JOB_TTL_S")
        if not ttl or ttl <= 0:
            return []
        now = self._clock()
        if not force and now < self._gc_next:
            return []
        self._gc_next = now + GC_CHECK_INTERVAL_S
        reaped = []
        for jid, j in list(self._jobs.items()):
            if jid in keep or not j.terminal():
                continue
            if j.finished_at is None or now - j.finished_at < ttl:
                continue
            del self._jobs[jid]
            reaped.append(j)
            self._m_gc.inc()
        if reaped:
            self._refresh_states()
        return reaped

    def cancel(self, job_id: str) -> Optional[Job]:
        """Cancel a job: no more leases, in-flight completes dropped,
        outstanding leases abandoned (their workers' reports bounce
        off the CANCELLED guard).  Terminal states stay terminal."""
        job = self._jobs.get(job_id)
        if job is None:
            return None
        if not job.terminal():
            job.state, job.done_reason = CANCELLED, "cancelled"
            job.finished_at = self._clock()
            job.dispatcher.abandon()
            self._refresh_states()
        return job

    def pause(self, job_id: str, resume: bool = False) -> Optional[Job]:
        """Pause (or resume) a job: a paused job leases nothing, but
        outstanding units may still complete -- they were honestly
        leased -- and workers keep polling (pause is not stop)."""
        job = self._jobs.get(job_id)
        if job is None or job.terminal():
            return job
        if resume:
            if job.state == PAUSED:
                job.state = RUNNING
                self.refresh_job_state(job)
        else:
            job.state = PAUSED
        self._refresh_states()
        return job

    # -- aggregate status -------------------------------------------------

    def all_finished(self) -> bool:
        """Every job terminal (the multi-job _stopped condition) --
        False while the table is empty only because an empty
        coordinator shouldn't exist (the default job is added at
        construction)."""
        jobs = self._jobs.values()
        if not jobs:
            return False
        for j in jobs:
            self.refresh_job_state(j)
        return all(j.terminal() for j in jobs)

    def idle_stop(self) -> bool:
        """Should an empty lease response tell the worker to stop?
        Yes only when no non-terminal job could EVER hand out work
        again without operator action: nothing outstanding and nothing
        pending anywhere, and no job is merely paused (paused jobs
        keep the fleet polling for the resume)."""
        for j in self._jobs.values():
            if j.terminal():
                continue
            if j.state == PAUSED:
                return False
            if j.dispatcher.outstanding_count() \
                    or j.dispatcher.leasable():
                return False
        return True

    def progress(self) -> tuple:
        """(covered, total) summed over non-cancelled jobs."""
        done = total = 0
        for j in self._jobs.values():
            if j.state == CANCELLED:
                continue
            d, t = j.dispatcher.progress()
            done += d
            total += t
        return done, total

    def found_total(self) -> int:
        return sum(len(j.found) for j in self._jobs.values())

    def targets_total(self) -> int:
        return sum(j.n_targets for j in self._jobs.values())

    def parked_total(self) -> int:
        return sum(j.dispatcher.parked_count()
                   for j in self._jobs.values())

    def parked_indices_total(self) -> int:
        return sum(j.dispatcher.parked_indices()
                   for j in self._jobs.values())

    def summaries(self) -> list:
        return [j.summary() for j in self._jobs.values()]
