"""Multi-tenant serve plane (ISSUE 8): many jobs, many users, one fleet.

The coordinator ran one job per session; this package turns the serve
plane into a scheduler of MANY jobs sharing one worker fleet --
HashKitty's platform shape (PAPERS.md): users submit tasks to a
service that schedules them across nodes.

  scheduler.py   Job records + JobScheduler: weighted fair-share
                 (stride) selection across runnable jobs at lease
                 time, per-job keyspace accounting, quota and lease-
                 rate limits, per-job hit buffers for cursor-based
                 delivery, and job states
                 (queued/running/paused/done/cancelled).
  build.py       Server-side job construction from a wire spec
                 (op_job_submit): targets/generator/fingerprint/
                 dispatcher/verifier -- the same composition the
                 `dprf serve` front-end performs -- plus per-job
                 session-journal resume.

The RPC surface (op_job_submit/list/status/cancel/pause, op_hits_pull)
lives on rpc.CoordinatorState, which owns one JobScheduler; the
`dprf jobs` CLI is the admin client.
"""

from dprf_tpu.jobs.scheduler import (CANCELLED, DONE, PAUSED, QUEUED,
                                     RUNNING, Job, JobScheduler)

__all__ = ["Job", "JobScheduler", "QUEUED", "RUNNING", "PAUSED",
           "DONE", "CANCELLED"]
