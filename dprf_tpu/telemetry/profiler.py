"""Kernel-level profiling plane (ISSUE 15): on-demand jax.profiler
capture + dependency-free perfetto analysis.

Every observability layer so far stops at the sweep boundary: the
per-phase probes (telemetry/perf.py) say how long ``device`` took and
the program registry (telemetry/programs.py) says what XLA *predicted*
it costs -- nothing says where device time actually goes inside a
dispatch.  This module closes that gap in three pieces:

  1. **ProfileCapture** -- the single owner of every
     ``jax.profiler.start_trace`` in the repo.  jax allows ONE active
     trace per process, so the ``--profile`` flag, the
     ``DPRF_JAX_PROFILE`` env knob, and on-demand capture windows all
     route through its single-flight guard: a second starter degrades
     to a logged no-op instead of an exception mid-job.  On-demand
     captures are BOUNDED WINDOWS -- ``begin_window`` starts the
     trace, the caller keeps doing its normal work, and ``poll()``
     stops + analyzes once the window elapsed (so the capture records
     the real workload, not a synthetic one).  Raw capture dirs are
     size-capped (``DPRF_PROFILE_MAX_BYTES`` drops the .xplane.pb
     bulk) with keep-last-N retention (``DPRF_PROFILE_KEEP``).

  2. **The analyzer** -- ``analyze_trace`` parses the emitted
     ``perfetto_trace.json.gz`` (gzip JSON trace events; verified
     parseable on jax 0.4.37) with NO dependencies beyond stdlib:
     lanes come from the process/thread-name metadata events,
     per-event SELF time from the nesting stack, and every device-op
     event is classified by name (fusion / collective / copy-convert
     / custom-call) with compile and host-python lanes accounted
     separately.  The summary carries a top-ops table,
     compute/collective/copy fractions, and a generate/hash/compare
     sub-phase split mapped through per-engine declared name patterns
     (``PROFILE_PHASES`` on the engine classes; defaults below) --
     finally splitting the wordlist ``device`` blob and making Pallas
     custom-calls (which under-report flops to ``cost_analysis``)
     and superstep collective time measurable.

  3. **The divergence gauge** -- when a capture knows how many
     candidates were swept during its window, measured device-op
     seconds per candidate are compared against the program
     registry's ANALYZED cost at the chip's int32 issue ceiling
     (``dprf_profile_cost_divergence{engine}``): > 1 means the chip
     spent more device time than the XLA cost model predicts.

The fleet path (op_profile / op_profile_push RPC, alert-triggered
auto-capture) lives in runtime/rpc.py; the surfaces are ``dprf
profile``, ``dprf report``'s kernel-profile section, and ``dprf bench
--profile``.

Summary schema (``schema: 1``; wire-shipped summaries pass
``sanitize_summary`` -- bounded, known keys only)::

    {"schema": 1, "ts": <epoch s>, "window_s": <float>,
     "trigger": "manual|env|cli|bench|straggler|job_stalled",
     "path": "<capture dir on the capturing host>",
     "engine": "<engine or null>", "events": <int>,
     "seconds": {"fusion": s, "op": s, "collective": s, "copy": s,
                 "custom_call": s, "compile": s, "host": s,
                 "infra": s},
     "device_s": <float>, "fractions": {"compute": f,
     "collective": f, "copy": f},
     "phases": {"generate": s, "hash": s, "compare": s, "other": s},
     "top_ops": [{"name", "class", "self_s", "count"} x <= 20],
     "candidates": <int|null>, "device_s_per_cand": <float|null>,
     "divergence": <float|null>, "error": "<only on failure>"}
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from dprf_tpu.utils import env as envreg

#: opt-in: wrap sweep loops in a jax.profiler trace written here (the
#: historical knob; trace.jax_profile_ctx delegates to session_ctx)
PROFILE_ENV = "DPRF_JAX_PROFILE"
AUTOPROFILE_ENV = "DPRF_AUTOPROFILE"
COOLDOWN_ENV = "DPRF_PROFILE_COOLDOWN_S"
WINDOW_ENV = "DPRF_PROFILE_SECONDS"
KEEP_ENV = "DPRF_PROFILE_KEEP"
MAX_BYTES_ENV = "DPRF_PROFILE_MAX_BYTES"
DIR_ENV = "DPRF_PROFILE_DIR"

SUMMARY_SCHEMA = 1

#: op classes the analyzer buckets self-time into.  The first five are
#: DEVICE classes (their sum is ``device_s``); compile/host/infra are
#: the non-device lanes.
DEVICE_CLASSES = ("fusion", "op", "custom_call", "collective", "copy")
OP_CLASSES = DEVICE_CLASSES + ("compile", "host", "infra")

#: top-ops table length (and the wire bound on ingested summaries)
TOP_OPS = 20

#: largest trace file the analyzer will parse (compressed bytes): a
#: runaway capture must fail fast with an error summary, not pin a
#: worker loop parsing gigabytes of JSON
MAX_TRACE_BYTES = 128 << 20

#: wire-summary sanitization bounds (worker-shipped summaries are
#: client-controlled, like trace spans and heartbeat payloads)
MAX_SUMMARY_STR = 256
SUMMARY_KEYS = ("schema", "ts", "window_s", "trigger", "path",
                "engine", "events", "seconds", "device_s",
                "fractions", "phases", "top_ops", "candidates",
                "device_s_per_cand", "divergence", "error",
                "request_id")

#: summaries ProfileCapture keeps in memory (local history; the
#: coordinator keeps its own per-worker table)
HISTORY_MAX = 8

#: fallback phase patterns: matched (substring, lowercased) against
#: each device op's name + metadata text.  Engines refine these with a
#: ``PROFILE_PHASES`` class attribute (engines/device/engines.py) --
#: the per-engine declaration site the analyzer merges over these.
#: Order matters: generate and compare are matched BEFORE hash, whose
#: patterns are deliberately broad (the fused digest body is most of
#: a crack step).
DEFAULT_PROFILE_PHASES = {
    "generate": ("decode", "iota", "digit", "generate", "expand_word"),
    "compare": ("compare", "equal", " eq", "match", "hit",
                "reduce-or", "any_hit"),
    "hash": ("fusion", "hash", "round", "digest", "while", "crack",
             "custom-call", "mosaic"),
}
PHASE_ORDER = ("generate", "compare", "hash")

#: lock-discipline declaration (`dprf check` locks analyzer): the
#: capture object is touched by the worker loop, RPC handler threads
#: (request delivery), and CLI threads; all mutable capture state
#: moves under ``_lock``.  The jax start/stop calls themselves run
#: OUTSIDE the lock -- they can take seconds and must not stall a
#: concurrent single-flight check.  The module-level ``_deps`` warm
#: state is shared by every capture object.
GUARDED_BY = {
    "ProfileCapture": {
        "_lock": ("_owner", "_window", "_done", "_history",
                  "_last_ts"),
    },
    "<module>": {"_deps_lock": ("_deps",)},
}

# -- lazy-dependency warmup --------------------------------------------------
# jax.profiler.start_trace lazily imports its trace-export stack on
# first use (tensorflow + its scipy/sklearn/pandas train on stock
# installs) -- measured 60-90 s COLD on a throttled box, which would
# wedge a worker loop mid-sweep long enough to trip worker_missing.
# The warm runs on a daemon thread kicked at window-arm time; poll()
# refuses to start the trace until it finished, so the stall overlaps
# normal sweeping instead of blocking it.

_deps_lock = threading.Lock()
_deps: dict = {"state": None}     # None | "warming" | "ready"


def _warm_deps_thread() -> None:
    try:
        import tensorflow  # noqa: F401 -- the lazy stack start_trace
        # pulls in on first use; absent installs just skip the warm
    except Exception:   # noqa: BLE001
        pass
    try:
        import jax.profiler  # noqa: F401
    except Exception:   # noqa: BLE001
        pass
    with _deps_lock:
        _deps["state"] = "ready"


def warm_deps_async() -> bool:
    """Kick (once) the background import of the profiler's lazy
    dependency stack; True when a trace can start WITHOUT paying a
    cold-import stall inline."""
    with _deps_lock:
        if _deps["state"] == "ready":
            return True
        if _deps["state"] is None:
            _deps["state"] = "warming"
            threading.Thread(target=_warm_deps_thread, daemon=True,
                             name="dprf-profiler-warm").start()
        return False


def default_window_s() -> float:
    v = envreg.get_float(WINDOW_ENV, 3.0)
    return max(0.5, float(v or 3.0))


def autoprofile_enabled() -> bool:
    return envreg.get_bool(AUTOPROFILE_ENV)


def cooldown_s() -> float:
    v = envreg.get_float(COOLDOWN_ENV, 600.0)
    return max(0.0, float(v or 0.0))


def profile_dir() -> str:
    """Where a worker writes on-demand capture dirs: the declared
    knob, else a stable per-process dir under the temp root (raw
    traces never ship over the wire -- the summary names this
    path)."""
    d = envreg.get_path(DIR_ENV)
    if d:
        return d
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        f"dprf-profile-{os.getpid()}")


def _captures_counter(registry=None):
    from dprf_tpu.telemetry import get_registry
    return get_registry(registry).counter(
        "dprf_profile_captures_total",
        "kernel-profile capture windows completed, by trigger "
        "(manual/env/cli/bench or the firing alert rule)",
        labelnames=("trigger",))


def _divergence_gauge(registry=None):
    from dprf_tpu.telemetry import get_registry
    return get_registry(registry).gauge(
        "dprf_profile_cost_divergence",
        "measured device-op seconds per candidate / the program "
        "registry's analyzed cost at the int32 issue ceiling "
        "(> 1: the chip spends more device time than the XLA cost "
        "model predicts)", labelnames=("engine",))


def publish_divergence(engine: str, device_s_per_cand: float,
                       registry=None) -> Optional[float]:
    """Measured-vs-analyzed cost ratio for one capture; None when the
    engine has no analyzed program in this process (nothing honest to
    divide by)."""
    from dprf_tpu.telemetry import perf as perf_mod
    from dprf_tpu.telemetry import programs as programs_mod
    ops = programs_mod.analyzed_ops_per_candidate(engine)
    if not ops or not device_s_per_cand or device_s_per_cand <= 0:
        return None
    predicted = ops / perf_mod.CHIP_INT_OPS_BAND[1]
    ratio = device_s_per_cand / predicted
    _divergence_gauge(registry).set(ratio, engine=engine)
    return ratio


# ---------------------------------------------------------------------------
# the dependency-free perfetto analyzer

def find_trace(path: str) -> Optional[str]:
    """The newest ``perfetto_trace.json.gz`` under a capture dir (jax
    writes ``plugins/profile/<ts>/``), or the file itself when handed
    one directly."""
    if os.path.isfile(path):
        return path
    hits = glob.glob(os.path.join(
        path, "**", "perfetto_trace.json.gz"), recursive=True)
    if not hits:
        return None
    return max(hits, key=lambda p: os.path.getmtime(p))


def _load_events(trace_file: str) -> list:
    opener = gzip.open if trace_file.endswith(".gz") else open
    with opener(trace_file, "rt", encoding="utf-8",
                errors="replace") as fh:
        doc = json.load(fh)
    evs = doc.get("traceEvents") if isinstance(doc, dict) else None
    return evs if isinstance(evs, list) else []


#: lane kinds, decided from the process/thread-name metadata: the
#: device-op lane holds per-HLO events (TPU: the "XLA Ops" threads of
#: "/device:*" processes; CPU backend: the TfrtCpuClient execution
#: threads), the compile lanes hold codegen/compile-pass work, the
#: host lane holds the $file:line python frames.
def _lane_kind(proc_name: str, thread_name: str) -> str:
    p, t = proc_name.lower(), thread_name.lower()
    if "llvm-codegen" in t or "xlacompile" in t or "compile" in t:
        return "compile"
    if "/device:" in p:
        # xprof device processes: the op lane is "XLA Ops"; module/
        # step lanes would double-count every op's time
        if "xla ops" in t:
            return "device"
        if "xla modules" in t or t.startswith("step"):
            return "skip"
        return "device" if not t else "skip"
    if "tfrtcpuclient" in t or "xla:cpu" in t or "stream" in t:
        return "device"
    if t == "python" or "host" in p and t.startswith("py"):
        return "host"
    return "infra"


_COLLECTIVE_PAT = ("all-reduce", "all-gather", "all-to-all",
                   "reduce-scatter", "collective", "psum", "permute")
_COPY_PAT = ("copy", "convert", "transpose", "bitcast")
_CUSTOM_PAT = ("custom-call", "custom_call", "pallas", "mosaic")
_INFRA_PAT = ("threadpoollistener", "thunkexecutor", "taskdispatcher",
              "streamexecutor", "wait for ")


def classify_op(name: str, lane: str) -> str:
    """One event's class.  Host/compile lanes classify by lane; the
    device lane splits by op name so the fractions can separate
    compute from collectives and copies."""
    n = name.lower()
    if lane == "host" or n.startswith("$"):
        return "host"
    if lane == "compile":
        return "compile"
    if any(p in n for p in _INFRA_PAT):
        return "infra"
    if lane != "device":
        return "infra"
    if any(p in n for p in _COLLECTIVE_PAT):
        return "collective"
    if any(p in n for p in _CUSTOM_PAT):
        return "custom_call"
    if "fusion" in n:
        return "fusion"
    if any(n.startswith(p) or p in n for p in _COPY_PAT):
        return "copy"
    return "op"


def phase_patterns(engine: Optional[str]) -> dict:
    """The generate/hash/compare name patterns for an engine: the
    engine class's declared ``PROFILE_PHASES`` merged over the
    defaults.  Resolution is best-effort -- the analyzer must stay
    usable on a host without jax/the engine registry installed."""
    merged = {k: tuple(v) for k, v in DEFAULT_PROFILE_PHASES.items()}
    if not engine:
        return merged
    try:
        from dprf_tpu import get_engine
        eng = get_engine(engine, device="jax")
        declared = getattr(type(eng), "PROFILE_PHASES", None) or {}
        for k, pats in declared.items():
            if k in merged and isinstance(pats, (tuple, list)):
                merged[k] = tuple(str(p).lower() for p in pats) \
                    + merged[k]
    except Exception:   # noqa: BLE001 -- no jax / unknown engine:
        pass            # defaults still split most traces usefully
    return merged


def _self_times(events: list, lanes: dict) -> list:
    """(lane_kind, name, self_seconds) per event, self time via the
    per-lane nesting stack (an event's own dur minus its children's).
    Device lanes can hold overlapping async events; the stack model
    treats a later-starting overlap as a child, which attributes the
    overlap once -- the honest choice for wall-time fractions."""
    by_lane: dict = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name")
        if not isinstance(name, str):
            continue
        try:
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        kind = lanes.get((e.get("pid"), e.get("tid")), "infra")
        if kind == "skip" or dur < 0:
            continue
        by_lane.setdefault((e.get("pid"), e.get("tid"), kind),
                           []).append((ts, dur, name))
    out = []
    for (_, _, kind), evs in by_lane.items():
        evs.sort(key=lambda x: (x[0], -x[1]))
        stack: list = []    # [(end_ts, self_acc)]
        for ts, dur, name in evs:
            while stack and stack[-1][0] <= ts + 1e-9:
                stack.pop()
            if stack:
                stack[-1][1][0] -= dur
            acc = [dur]
            stack.append((ts + dur, acc))
            out.append((kind, name, acc))
    return [(k, n, max(0.0, a[0]) * 1e-6) for k, n, a in out]


def analyze_trace(path: str, engine: Optional[str] = None,
                  candidates: Optional[int] = None,
                  top: int = TOP_OPS, registry=None) -> dict:
    """Parse + aggregate one capture into the summary schema (module
    docstring).  ``path`` is a capture dir or the perfetto file
    itself; ``candidates`` (when the caller knows how many were swept
    during the window) turns on per-candidate cost and the
    divergence gauge."""
    trace_file = find_trace(path)
    if trace_file is None:
        return {"schema": SUMMARY_SCHEMA, "path": path, "engine": engine,
                "error": "no perfetto_trace.json.gz under this path"}
    try:
        size = os.path.getsize(trace_file)
    except OSError:
        size = 0
    if size > MAX_TRACE_BYTES:
        return {"schema": SUMMARY_SCHEMA, "path": path, "engine": engine,
                "error": f"trace too large to analyze ({size} bytes "
                f"> {MAX_TRACE_BYTES})"}
    try:
        events = _load_events(trace_file)
    except (OSError, ValueError) as e:
        return {"schema": SUMMARY_SCHEMA, "path": path, "engine": engine,
                "error": f"unparsable trace: {e}"}
    procs: dict = {}
    threads: dict = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            procs[e.get("pid")] = str(args.get("name", ""))
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = \
                str(args.get("name", ""))
    lanes = {key: _lane_kind(procs.get(key[0], ""), tname)
             for key, tname in threads.items()}

    classes = {c: 0.0 for c in OP_CLASSES}
    per_op: dict = {}
    patterns = phase_patterns(engine)
    phases = {"generate": 0.0, "hash": 0.0, "compare": 0.0,
              "other": 0.0}
    n_events = 0
    for kind, name, self_s in _self_times(events, lanes):
        n_events += 1
        cls = classify_op(name, kind)
        classes[cls] += self_s
        if cls in DEVICE_CLASSES:
            rec = per_op.setdefault(name, [cls, 0.0, 0])
            rec[1] += self_s
            rec[2] += 1
            low = name.lower()
            for ph in PHASE_ORDER:
                if any(p in low for p in patterns[ph]):
                    phases[ph] += self_s
                    break
            else:
                phases["other"] += self_s
    device_s = sum(classes[c] for c in DEVICE_CLASSES)
    fractions = {"compute": 0.0, "collective": 0.0, "copy": 0.0}
    if device_s > 0:
        fractions = {
            "compute": (classes["fusion"] + classes["op"]
                        + classes["custom_call"]) / device_s,
            "collective": classes["collective"] / device_s,
            "copy": classes["copy"] / device_s,
        }
    top_ops = sorted(
        ({"name": name, "class": rec[0],
          "self_s": round(rec[1], 6), "count": rec[2]}
         for name, rec in per_op.items()),
        key=lambda r: -r["self_s"])[:max(1, top)]
    out = {
        "schema": SUMMARY_SCHEMA,
        "ts": round(time.time(), 3),
        "path": path,
        "engine": engine,
        "events": n_events,
        "seconds": {c: round(classes[c], 6) for c in OP_CLASSES},
        "device_s": round(device_s, 6),
        "fractions": {k: round(v, 4) for k, v in fractions.items()},
        "phases": {k: round(v, 6) for k, v in phases.items()},
        "top_ops": top_ops,
        "candidates": candidates,
        "device_s_per_cand": None,
        "divergence": None,
    }
    if candidates and candidates > 0 and device_s > 0:
        spc = device_s / candidates
        out["device_s_per_cand"] = spc
        if engine:
            out["divergence"] = publish_divergence(
                engine, spc, registry=registry)
            # feed the roofline fallback chain: programs whose HLO
            # reports no flop count (probe-table steps) get an op
            # model from this measured cost (perf.ops_per_candidate)
            from dprf_tpu.telemetry import perf as perf_mod
            perf_mod.record_measured_cost(engine, spc,
                                          registry=registry)
    return out


def sanitize_summary(summary) -> Optional[dict]:
    """Bounded, known-keys-only view of a worker-shipped summary
    (client-controlled, like ingested spans): strings truncated,
    numeric fields coerced, top_ops capped at TOP_OPS entries."""
    if not isinstance(summary, dict):
        return None
    out: dict = {}
    for k in SUMMARY_KEYS:
        if k not in summary:
            continue
        v = summary[k]
        if k == "top_ops":
            rows = []
            for r in (v if isinstance(v, list) else [])[:TOP_OPS]:
                if not isinstance(r, dict):
                    continue
                try:
                    rows.append({
                        "name": str(r.get("name", "?"))[:MAX_SUMMARY_STR],
                        "class": str(r.get("class", "?"))[:32],
                        "self_s": float(r.get("self_s") or 0.0),
                        "count": int(r.get("count") or 0)})
                except (TypeError, ValueError):
                    continue
            out[k] = rows
        elif k in ("seconds", "fractions", "phases"):
            if isinstance(v, dict):
                clean = {}
                for kk, vv in list(v.items())[:16]:
                    try:
                        clean[str(kk)[:32]] = float(vv)
                    except (TypeError, ValueError):
                        continue
                out[k] = clean
        elif v is None or isinstance(v, bool):
            out[k] = v
        elif isinstance(v, (int, float)):
            out[k] = v
        else:
            out[k] = str(v)[:MAX_SUMMARY_STR]
    if not out:
        return None
    out.setdefault("schema", SUMMARY_SCHEMA)
    return out


def render_summary(doc: dict) -> str:
    """The human rendering (``dprf profile`` stdout / the report's
    kernel-profile section body)."""
    lines = []
    if doc.get("error"):
        lines.append(f"capture FAILED: {doc['error']}")
    head = (f"engine {doc.get('engine') or '?'} | "
            f"{doc.get('events', 0)} events | device "
            f"{doc.get('device_s', 0.0):.4f}s")
    if doc.get("window_s"):
        head += f" | window {doc['window_s']:.1f}s"
    if doc.get("trigger"):
        head += f" | trigger {doc['trigger']}"
    lines.append(head)
    fr = doc.get("fractions") or {}
    if fr:
        lines.append("  device fractions  "
                     + "  ".join(f"{k} {100.0 * fr.get(k, 0.0):.1f}%"
                                 for k in ("compute", "collective",
                                           "copy")))
    secs = doc.get("seconds") or {}
    aux = [f"{k} {secs[k]:.4f}s" for k in ("compile", "host")
           if secs.get(k)]
    if aux:
        lines.append("  off-device        " + "  ".join(aux))
    ph = doc.get("phases") or {}
    if any(ph.values()):
        lines.append("  phases            "
                     + "  ".join(f"{k} {ph.get(k, 0.0):.4f}s"
                                 for k in ("generate", "hash",
                                           "compare", "other")))
    if doc.get("device_s_per_cand"):
        d = doc.get("divergence")
        lines.append(f"  per candidate     "
                     f"{doc['device_s_per_cand']:.3e}s"
                     + (f"  (divergence {d:.2f}x vs analyzed cost)"
                        if d else ""))
    ops = doc.get("top_ops") or []
    if ops:
        lines.append(f"  {'OP':44s} {'CLASS':>11s} {'SELF':>10s} "
                     f"{'COUNT':>6s}")
        for r in ops:
            lines.append(f"  {r['name'][:44]:44s} {r['class']:>11s} "
                         f"{r['self_s']:>9.4f}s {r['count']:>6d}")
    if doc.get("path"):
        lines.append(f"  raw trace: {doc['path']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# retention

def enforce_caps(root: str, keep: Optional[int] = None,
                 max_bytes: Optional[int] = None) -> None:
    """Bound the raw artifacts under a profile root: capture dirs
    (``plugins/profile/<ts>``) beyond keep-last-N are deleted oldest
    first, and a capture whose files exceed the byte cap drops its
    ``.xplane.pb`` bulk (the perfetto JSON -- what the analyzer reads
    -- is always kept)."""
    import shutil
    keep = envreg.get_int(KEEP_ENV) if keep is None else keep
    max_bytes = (envreg.get_int(MAX_BYTES_ENV)
                 if max_bytes is None else max_bytes)
    base = os.path.join(root, "plugins", "profile")
    try:
        runs = sorted(
            (os.path.join(base, d) for d in os.listdir(base)
             if os.path.isdir(os.path.join(base, d))),
            key=lambda p: os.path.getmtime(p))
    except OSError:
        return
    if keep and keep > 0:
        for old in runs[:-keep]:
            shutil.rmtree(old, ignore_errors=True)
        runs = runs[-keep:]
    if not max_bytes or max_bytes <= 0:
        return
    for run in runs:
        files = []
        total = 0
        for r, _, fns in os.walk(run):
            for fn in fns:
                p = os.path.join(r, fn)
                try:
                    total += os.path.getsize(p)
                except OSError:
                    continue
                files.append(p)
        if total <= max_bytes:
            continue
        for p in files:
            if p.endswith(".xplane.pb"):
                try:
                    os.unlink(p)
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# the single-flight capture owner

class ProfileCapture:
    """The one object allowed to start/stop jax profiler traces in
    this process.  Three entry shapes share its single-flight slot:

      - ``session(dir)``: a context manager wrapping a whole run
        (the ``--profile`` flag and ``DPRF_JAX_PROFILE``);
      - ``begin_window`` / ``poll()``: the on-demand bounded window
        (op_profile requests, auto-capture) -- poll is ONE attribute
        read when no window is active, so the dispatch path pays
        nothing while capture is disabled;
      - ``capture(seconds)``: the synchronous convenience (bench,
        tests) -- begin, run ``busy_fn`` (or sleep), poll to done.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._owner: Optional[str] = None
        #: active bounded window: {"deadline", "dir", "trigger",
        #: "engine", "request_id", "counter_fn", "cands0",
        #: "seconds"} -- None when idle (the poll fast path)
        self._window: Optional[dict] = None
        #: finished-but-unconsumed summaries, drained by poll().  A
        #: separate queue (not a state on the window) so a new window
        #: armed while the previous one is still analyzing on its
        #: background thread can never clobber an undelivered
        #: summary -- each request's result reaches its poller.
        self._done: deque = deque(maxlen=HISTORY_MAX)
        self._history: deque = deque(maxlen=HISTORY_MAX)
        #: per-trigger last capture wall time (the coordinator keeps
        #: its own cooldown ledger; this one rate-limits env-local
        #: paths)
        self._last_ts: dict = {}
        self._registry = registry

    # -- single-flight slot ---------------------------------------------

    def _acquire(self, owner: str) -> bool:
        with self._lock:
            if self._owner is not None:
                return False
            self._owner = owner
            return True

    def _release(self, owner: str) -> None:
        with self._lock:
            if self._owner == owner:
                self._owner = None

    def busy(self) -> Optional[str]:
        """The current owner label, or None when the slot is free."""
        with self._lock:
            return self._owner

    # -- session-length traces (--profile / DPRF_JAX_PROFILE) -----------

    @contextlib.contextmanager
    def session(self, directory: str, owner: str = "session",
                log=None):
        """Wrap a whole run in one trace.  Degrades to a no-op (with
        a logged warning) instead of killing the job when the slot is
        taken or the profiler cannot start -- e.g. ``--profile`` and
        ``DPRF_JAX_PROFILE`` naming different dirs on one process."""
        if not self._acquire(owner):
            if log is not None:
                log.warn("profiler busy; trace NOT started",
                         dir=directory, owner=self.busy())
            yield self
            return
        started = False
        try:
            import jax
            jax.profiler.start_trace(directory,
                                     create_perfetto_trace=True)
            started = True
        except Exception as e:   # noqa: BLE001 -- diagnostics only
            if log is not None:
                log.warn("jax profiler trace failed to start",
                         dir=directory, error=str(e))
        try:
            yield self
        finally:
            if started:
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:    # noqa: BLE001
                    pass
                enforce_caps(directory)
                _captures_counter(self._registry).inc(trigger=owner)
            self._release(owner)

    # -- bounded on-demand windows --------------------------------------

    def begin_window(self, seconds: Optional[float] = None,
                     directory: Optional[str] = None,
                     trigger: str = "manual",
                     engine: Optional[str] = None,
                     request_id=None,
                     counter_fn: Optional[Callable] = None,
                     log=None) -> bool:
        """ARM a bounded capture window; the caller keeps doing its
        normal work and calls ``poll()`` until the summary lands.
        The trace itself starts LAZILY at the next ``poll()`` call --
        a worker that receives a request right before a minutes-long
        warmup compile must capture its steady-state sweeps, not a
        giant compile-stall trace (the loop only polls between
        units).  False when the single-flight slot is taken
        (callers report that in-band -- the collision contract)."""
        seconds = default_window_s() if seconds is None else \
            max(0.5, float(seconds))
        directory = directory or profile_dir()
        owner = f"window:{trigger}"
        if not self._acquire(owner):
            if log is not None:
                log.warn("profiler busy; capture window refused",
                         trigger=trigger, owner=self.busy())
            return False
        warm_deps_async()      # overlap the cold import with sweeping
        with self._lock:
            self._window = {
                "state": "armed", "deadline": None,
                "seconds": seconds, "dir": directory,
                "trigger": trigger, "engine": engine,
                "request_id": request_id, "counter_fn": counter_fn,
                "cands0": None, "owner": owner,
            }
        return True

    def _fail_window(self, w: dict, error: str) -> dict:
        self._release(w["owner"])
        return {"schema": SUMMARY_SCHEMA, "trigger": w["trigger"],
                "engine": w["engine"], "request_id": w["request_id"],
                "error": error}

    def poll(self) -> Optional[dict]:
        """Drive an armed window through its states: the first call
        (with the dep warm done) starts the trace; once the deadline
        elapsed the stop + analyze run on a BACKGROUND thread -- a
        million-event trace can take minutes to parse on a loaded
        host, and blocking the worker loop that long would trip the
        very worker_missing alert a capture is investigating; a later
        poll returns the finished summary exactly once.  One
        uncontended lock probe when no window is active -- the
        near-zero-overhead contract for the dispatch path (asserted
        in tests/test_profiler.py)."""
        start_me = None
        with self._lock:
            if self._done:
                return self._done.popleft()
            w = self._window
            if w is None:
                return None
            if w["state"] == "armed":
                if not warm_deps_async():
                    # the lazy import stack is still loading on the
                    # warm thread: keep sweeping, start next poll
                    return None
                w["state"] = "starting"
                start_me = w
            elif (w["state"] == "running"
                  and time.monotonic() >= w["deadline"]):
                w["state"] = "finishing"
                threading.Thread(target=self._finish_window,
                                 args=(w,), daemon=True,
                                 name="dprf-profiler-finish").start()
                return None
            else:
                return None
        w = start_me
        try:
            os.makedirs(w["dir"], exist_ok=True)
            import jax
            jax.profiler.start_trace(w["dir"],
                                     create_perfetto_trace=True)
        except Exception as e:   # noqa: BLE001 -- capture is
            # diagnostics; a broken profiler must not kill the
            # sweep -- the failure ships in-band as the summary
            with self._lock:
                self._window = None
            return self._fail_window(w, f"start_trace failed: {e}")
        if w["counter_fn"] is not None:
            try:
                w["cands0"] = int(w["counter_fn"]())
            except Exception:   # noqa: BLE001
                w["cands0"] = None
        with self._lock:
            w["deadline"] = time.monotonic() + w["seconds"]
            w["state"] = "running"
        return None

    def _finish_window(self, w: dict) -> None:
        """Background half of poll(): stop the trace (the perfetto
        gzip write alone can take seconds), free the single-flight
        slot, analyze, and queue the summary for the next poll to
        drain.  This thread is the SOLE releaser of a finishing
        window's slot (abort_window leaves it alone), so the release
        can never free a successor owner's slot."""
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:   # noqa: BLE001
            summary = self._fail_window(w, f"stop_trace failed: {e}")
        else:
            self._release(w["owner"])
            enforce_caps(w["dir"])
            cands = None
            if w["counter_fn"] is not None and w["cands0"] is not None:
                try:
                    cands = max(0, int(w["counter_fn"]()) - w["cands0"])
                except Exception:   # noqa: BLE001
                    cands = None
            summary = analyze_trace(w["dir"], engine=w["engine"],
                                    candidates=cands,
                                    registry=self._registry)
            summary["trigger"] = w["trigger"]
            summary["window_s"] = w["seconds"]
            if w["request_id"] is not None:
                summary["request_id"] = w["request_id"]
        _captures_counter(self._registry).inc(trigger=w["trigger"])
        with self._lock:
            if self._window is w:
                self._window = None
            self._done.append(summary)
            self._history.append(summary)
            self._last_ts[w["trigger"]] = time.time()

    def window_active(self) -> bool:
        with self._lock:
            return self._window is not None

    def finish_now(self, timeout_s: float = 120.0) -> Optional[dict]:
        """Drive the active window to completion synchronously (loop
        shutdown): a RUNNING window stops early -- a shorter capture
        than asked, but real data beats a silent abort when the job's
        last unit lands mid-window -- a FINISHING one is waited on
        (bounded; a 1M-event trace analyzes in ~15 s on one slow
        core), and an ARMED one that never started returns an
        in-band error summary so the requester gets an answer
        instead of a timeout.  Also drains a leftover undrained
        summary; None only when nothing landed inside the grace."""
        with self._lock:
            w = self._window
            st = w["state"] if w else None
        if w is None:
            return self.poll()       # drain any leftover summary
        if st == "armed":
            with self._lock:
                mine = self._window is w
                if mine:
                    self._window = None
            if mine:
                return self._fail_window(
                    w, "capture window never started before the job "
                    "ended")
            return self.poll()
        if st == "running":
            with self._lock:
                if self._window is w and w["state"] == "running":
                    w["state"] = "finishing"
                else:
                    w = None
            if w is not None:
                threading.Thread(target=self._finish_window,
                                 args=(w,), daemon=True,
                                 name="dprf-profiler-finish").start()
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            s = self.poll()
            if s is not None:
                return s
            time.sleep(0.05)
        return None

    def abort_window(self) -> None:
        """Discard an in-flight window (loop shutdown): stop the
        trace (if it ever started) and free the slot without
        analyzing.  A window already FINISHING stays with its
        background thread -- that thread stops/releases/queues on
        its own, and releasing here too would free a successor
        owner's slot.  No-op when idle."""
        with self._lock:
            w = self._window
            if w is None or w["state"] == "finishing":
                return
            self._window = None
        if w["state"] == "running":
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:   # noqa: BLE001
                pass
        self._release(w["owner"])

    def capture(self, seconds: Optional[float] = None,
                directory: Optional[str] = None,
                trigger: str = "manual",
                engine: Optional[str] = None,
                counter_fn: Optional[Callable] = None,
                busy_fn: Optional[Callable] = None,
                log=None) -> Optional[dict]:
        """Synchronous bounded capture: begin, keep the process busy
        (``busy_fn`` runs the real workload; default just sleeps the
        window), poll to completion.  None when the slot was taken."""
        if not self.begin_window(seconds, directory, trigger=trigger,
                                 engine=engine, counter_fn=counter_fn,
                                 log=log):
            return None
        while True:
            if busy_fn is not None:
                busy_fn()
            else:
                time.sleep(0.05)
            s = self.poll()
            if s is not None:
                return s

    # -- reads -----------------------------------------------------------

    def last_summary(self) -> Optional[dict]:
        with self._lock:
            return self._history[-1] if self._history else None

    def summaries(self) -> list:
        with self._lock:
            return list(self._history)

    def last_capture_ts(self, trigger: Optional[str] = None
                        ) -> Optional[float]:
        with self._lock:
            if trigger is not None:
                return self._last_ts.get(trigger)
            return max(self._last_ts.values(), default=None)


#: process-wide capture owner (the utils/logging.DEFAULT pattern):
#: worker loops, the CLI, and the env-knob path all share ONE
#: single-flight slot because jax allows one active trace per process
DEFAULT = ProfileCapture()


def get_profiler(profiler: Optional[ProfileCapture] = None
                 ) -> ProfileCapture:
    return profiler if profiler is not None else DEFAULT


def jax_profile_ctx(log=None):
    """``DPRF_JAX_PROFILE=<dir>``: a session trace context for a sweep
    loop, routed through the single-flight guard (a run also launched
    with ``--profile`` degrades this to a logged no-op); a null
    context when unset."""
    d = envreg.get_path(PROFILE_ENV)
    if not d:
        return contextlib.nullcontext()
    return DEFAULT.session(d, owner="env", log=log)
