"""Fleet health plane (ISSUE 10): worker heartbeats, a
healthy->degraded->missing->dead state machine, and straggler
detection.

PRs 1/4/9 built the measurement side of observability; nothing turned
those streams into actionable signals -- a dead worker was only
noticed passively when its lease expired, and the
``dprf_worker_last_seen_timestamp`` gauge covered lease-HOLDERS only.
This module is the coordinator-side half of the fix:

  - every worker contact (an explicit ``op_heartbeat``, or the
    lease/complete traffic that makes one redundant) lands in a
    ``HealthRegistry`` via ``observe()``, carrying an optional
    capability/health payload (device kind, pipeline depth, queue
    depth, recent H/s, last error);
  - ``evaluate()`` (driven on the ``DPRF_ALERT_EVAL_S`` loop by
    ``CoordinatorState.health_tick``) ages each worker against the
    ``DPRF_HEARTBEAT_S`` interval -- HEALTHY within 2 beats, DEGRADED
    past 2, MISSING past 4, DEAD past 12 -- and flags STRAGGLERS: a
    worker whose throughput EWMA sits far below the fleet's robust
    median (modified z-score over the median absolute deviation; with
    a degenerate MAD, anything under half the median).

State lands in three places: the ``dprf_worker_health_state{worker}``
gauge (0=healthy 1=degraded 2=missing 3=dead -- the alert engine's
``worker_missing`` rule thresholds it), ``dprf_worker_straggler`` /
``dprf_worker_rate_hs`` gauges, and a TRANSITION queue the caller
drains from ``evaluate()`` -- ``cli.cmd_serve`` journals each one as a
``{"type": "worker_health"}`` session record, so a post-mortem can
replay exactly when the fleet decayed.

Thread model: ``observe()`` is called from RPC handler threads (under
``CoordinatorState.lock``) and ``evaluate()`` from the health-monitor
thread; all mutable state moves under ``_lock`` (declared below).
Transition CALLBACKS never fire under ``_lock`` -- they are queued and
drained by ``evaluate()``'s caller, which may take the coordinator
lock around journaling without creating a lock cycle.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dprf_tpu.telemetry import get_registry
from dprf_tpu.utils import env as envreg

#: worker health states, in decay order; gauge values are the index
STATE_NAMES = ("healthy", "degraded", "missing", "dead")
HEALTHY, DEGRADED, MISSING, DEAD = range(4)

#: decay thresholds, in multiples of the heartbeat interval: one
#: missed beat is network noise, two is degraded, four is missing
#: (the ``worker_missing`` alert condition), twelve is dead
DEGRADED_AFTER = 2.0
MISSING_AFTER = 4.0
DEAD_AFTER = 12.0

#: distinct worker ids tracked (ids are client-controlled; past the
#: cap new ids share one "_overflow" record so churn cannot grow
#: coordinator memory -- same stance as the last-seen gauge cap)
MAX_WORKERS = 256

#: straggler rule: modified z-score (0.6745 * dev / MAD) at or below
#: -STRAGGLER_Z flags the worker; fleets smaller than the minimum
#: have no meaningful median to deviate from
STRAGGLER_Z = 3.5
STRAGGLER_MIN_FLEET = 3
#: MAD-degenerate fallback (a homogeneous fleet has MAD 0): a worker
#: under this fraction of the median is a straggler
STRAGGLER_FLOOR_FRAC = 0.5

#: throughput EWMA smoothing for the per-worker rate estimate
RATE_ALPHA = 0.3

#: heartbeat payload sanitization (client-controlled data).  The hbm_*
#: fields are the worker's device-memory totals (telemetry/devstats
#: summary; ISSUE 13) -- how the coordinator sees fleet HBM headroom
#: without a second RPC.
PAYLOAD_KEYS = ("engine", "device", "chips", "depth", "queue",
                "rate_hs", "error", "hbm_in_use", "hbm_limit",
                "hbm_peak", "profile_ts", "profile_trigger")
MAX_PAYLOAD_STR = 200

#: lock-discipline declaration (`dprf check` locks analyzer): observe
#: runs on RPC handler threads, evaluate on the monitor thread --
#: the worker table and transition queue move only under ``_lock``.
#: Gauges are set OUTSIDE the lock (the TraceRecorder contract: code
#: holding a declared lock never calls into other locked subsystems).
GUARDED_BY = {
    "HealthRegistry": {
        "_lock": ("_workers", "_transitions"),
    },
}


def heartbeat_interval(default: float = 10.0) -> float:
    """The ``DPRF_HEARTBEAT_S`` cadence; 0 disables explicit
    heartbeats (lease/complete traffic still counts as contact)."""
    v = envreg.get_float("DPRF_HEARTBEAT_S", default)
    return max(0.0, float(v or 0.0))


def _median(vals: list) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    if n % 2:
        return s[mid]
    return 0.5 * (s[mid - 1] + s[mid])


def _clean_payload(payload) -> dict:
    """Bounded, known-keys-only view of a worker's heartbeat payload
    (client-controlled, like ingested trace spans)."""
    if not isinstance(payload, dict):
        return {}
    out = {}
    for k in PAYLOAD_KEYS:
        if k not in payload:
            continue
        v = payload[k]
        if v is None or isinstance(v, bool) or isinstance(v, (int, float)):
            out[k] = v
        else:
            out[k] = str(v)[:MAX_PAYLOAD_STR]
    return out


class WorkerHealth:
    """One worker's live health record (mutated under the registry's
    lock only)."""

    __slots__ = ("worker", "state", "first_seen", "last_seen",
                 "rate_hs", "straggler", "payload", "contacts")

    def __init__(self, worker: str, now: float):
        self.worker = worker
        self.state = HEALTHY
        self.first_seen = now
        self.last_seen = now
        #: throughput EWMA from completed units (cands/s); None until
        #: the first complete carries an elapsed report
        self.rate_hs: Optional[float] = None
        self.straggler = False
        self.payload: dict = {}
        self.contacts = 0

    def as_dict(self, now: float) -> dict:
        return {"state": STATE_NAMES[self.state],
                "age_s": round(max(0.0, now - self.last_seen), 3),
                "rate_hs": (round(self.rate_hs, 3)
                            if self.rate_hs is not None else None),
                "straggler": self.straggler,
                "contacts": self.contacts,
                "payload": dict(self.payload)}


class HealthRegistry:
    """The coordinator's worker-health table + state machine."""

    def __init__(self, registry=None, clock=None, wall=None,
                 heartbeat_s: Optional[float] = None):
        self._clock = clock or time.monotonic
        self._wall = wall or time.time
        #: the aging unit; a 0/None interval falls back to the default
        #: so the state machine still works on fleets that disabled
        #: explicit heartbeats (lease traffic feeds observe instead)
        self.heartbeat_s = (heartbeat_s if heartbeat_s
                            else heartbeat_interval() or 10.0)
        self._lock = threading.Lock()
        self._workers: dict = {}
        #: queued transition dicts, drained (and only then surfaced to
        #: callbacks) by evaluate() -- see the module docstring
        self._transitions: list = []
        m = get_registry(registry)
        self._g_state = m.gauge(
            "dprf_worker_health_state",
            "worker health state machine: 0=healthy 1=degraded "
            "2=missing 3=dead (ages in DPRF_HEARTBEAT_S multiples; "
            "covers every contacting worker, not just lease holders)",
            labelnames=("worker",))
        self._g_straggler = m.gauge(
            "dprf_worker_straggler",
            "1 when the worker's throughput EWMA sits below the "
            "fleet's robust median by the MAD z-score threshold",
            labelnames=("worker",))
        self._g_rate = m.gauge(
            "dprf_worker_rate_hs",
            "per-worker throughput EWMA from completed units "
            "(the straggler detector's input)",
            labelnames=("worker",))

    def _entry(self, worker: str, now: float):
        """Get-or-create under the lock, with the id cap applied."""
        w = self._workers.get(worker)
        if w is None:
            if len(self._workers) >= MAX_WORKERS:
                worker = "_overflow"
                w = self._workers.get(worker)
            if w is None:
                w = self._workers[worker] = WorkerHealth(worker, now)
        return w
    _entry._holds_lock = "_lock"

    def _transition(self, w: WorkerHealth, to: int) -> None:
        self._transitions.append({
            "worker": w.worker, "from": STATE_NAMES[w.state],
            "to": STATE_NAMES[to], "ts": self._wall(),
            "age_s": round(max(0.0, self._clock() - w.last_seen), 3)})
        w.state = to
    _transition._holds_lock = "_lock"

    # -- contact ---------------------------------------------------------

    def observe(self, worker: str, payload=None,
                rate_hs: Optional[float] = None) -> None:
        """One sign of life from a worker: an explicit heartbeat
        (with payload), a lease poll, or a landed complete (with the
        unit's throughput).  Any contact resets the decay clock; a
        missing/dead worker REJOINS (transition back to healthy,
        journaled like the decay was)."""
        now = self._clock()
        gauge = None
        with self._lock:
            w = self._entry(str(worker), now)
            w.last_seen = now
            w.contacts += 1
            if payload is not None:
                w.payload.update(_clean_payload(payload))
            if rate_hs is not None and rate_hs > 0:
                w.rate_hs = (rate_hs if w.rate_hs is None
                             else w.rate_hs
                             + RATE_ALPHA * (rate_hs - w.rate_hs))
            if w.state != HEALTHY:
                self._transition(w, HEALTHY)
            gauge = (w.worker, w.state, w.rate_hs)
        self._g_state.set(gauge[1], worker=gauge[0])
        if gauge[2] is not None:
            self._g_rate.set(gauge[2], worker=gauge[0])

    # -- evaluation ------------------------------------------------------

    def _target_state(self, age: float) -> int:
        hb = self.heartbeat_s
        if age > DEAD_AFTER * hb:
            return DEAD
        if age > MISSING_AFTER * hb:
            return MISSING
        if age > DEGRADED_AFTER * hb:
            return DEGRADED
        return HEALTHY

    def _flag_stragglers(self) -> None:
        """MAD z-score of each live worker's throughput EWMA against
        the fleet median: robust to one outlier dragging the mean,
        deterministic, and cheap at fleet sizes."""
        live = [w for w in self._workers.values()
                if w.state <= DEGRADED and w.rate_hs is not None]
        flags: dict = {}
        if len(live) >= STRAGGLER_MIN_FLEET:
            rates = [w.rate_hs for w in live]
            med = _median(rates)
            mad = _median([abs(r - med) for r in rates])
            for w in live:
                if mad > 0:
                    z = 0.6745 * (w.rate_hs - med) / mad
                    flags[w.worker] = z <= -STRAGGLER_Z
                else:
                    flags[w.worker] = (med > 0 and w.rate_hs
                                       < STRAGGLER_FLOOR_FRAC * med)
        for w in self._workers.values():
            w.straggler = flags.get(w.worker, False)
    _flag_stragglers._holds_lock = "_lock"

    def evaluate(self) -> list:
        """One pass of the state machine + straggler detection;
        returns (and drains) every transition since the last call --
        including rejoins queued by ``observe`` -- so the caller can
        journal them without ever running under this lock."""
        now = self._clock()
        gauges = []
        with self._lock:
            for w in self._workers.values():
                target = self._target_state(now - w.last_seen)
                if target > w.state:     # decay only; observe() heals
                    self._transition(w, target)
            self._flag_stragglers()
            for w in self._workers.values():
                gauges.append((w.worker, w.state, w.straggler))
            out = self._transitions
            self._transitions = []
        for worker, state, straggler in gauges:
            self._g_state.set(state, worker=worker)
            self._g_straggler.set(1 if straggler else 0, worker=worker)
        return out

    # -- reads -----------------------------------------------------------

    def states(self) -> dict:
        """{worker: state name} -- the ``dprf top`` HEALTH column."""
        with self._lock:
            return {w.worker: STATE_NAMES[w.state]
                    for w in self._workers.values()}

    def snapshot(self) -> dict:
        """{worker: full record} for ``op_health``/``dprf health``."""
        now = self._clock()
        with self._lock:
            return {w.worker: w.as_dict(now)
                    for w in self._workers.values()}

    def slowest_worker(self) -> Optional[str]:
        """The live (healthy/degraded) worker with the lowest
        throughput EWMA -- who a stalled-job alert implicates when no
        label names a worker (the auto-capture target)."""
        with self._lock:
            live = [w for w in self._workers.values()
                    if w.state <= DEGRADED and w.rate_hs is not None
                    and w.worker != "_overflow"]
            if not live:
                return None
            return min(live, key=lambda w: w.rate_hs).worker

    def profile_by_worker(self) -> dict:
        """{worker: {"ts", "trigger"}} from the heartbeat payloads
        (ISSUE 15): each worker's last kernel capture, including
        env-local ones that never pushed a summary -- the fallback
        half of the ``dprf top`` PROF column."""
        with self._lock:
            out = {}
            for w in self._workers.values():
                ts = w.payload.get("profile_ts")
                if isinstance(ts, (int, float)) and not isinstance(
                        ts, bool):
                    out[w.worker] = {
                        "ts": ts,
                        "trigger": w.payload.get("profile_trigger")}
            return out

    def mem_by_worker(self) -> dict:
        """{worker: hbm bytes in use} from the heartbeat payloads
        (ISSUE 13) -- the ``dprf top`` MEM column; workers on
        backends without memory stats simply have no entry."""
        with self._lock:
            out = {}
            for w in self._workers.values():
                v = w.payload.get("hbm_in_use")
                if isinstance(v, (int, float)) and not isinstance(
                        v, bool):
                    out[w.worker] = int(v)
            return out

    def hbm_totals(self) -> Optional[dict]:
        """Fleet HBM headroom summed over LIVE (healthy/degraded)
        workers' heartbeat payloads: {in_use, limit, workers}; None
        when no worker reported memory stats -- exactly the
        coordinator-side view the capability payload exists for."""
        with self._lock:
            use = limit = n = 0
            for w in self._workers.values():
                if w.state > DEGRADED:
                    continue
                lv = w.payload.get("hbm_limit")
                uv = w.payload.get("hbm_in_use")
                if not isinstance(lv, (int, float)) or isinstance(
                        lv, bool) or lv <= 0:
                    continue
                limit += int(lv)
                use += int(uv) if isinstance(uv, (int, float)) \
                    and not isinstance(uv, bool) else 0
                n += 1
            if n == 0:
                return None
            return {"in_use": use, "limit": limit, "workers": n}


class HealthMonitor:
    """Background evaluation loop: calls ``tick`` (normally
    ``CoordinatorState.health_tick``) every ``DPRF_ALERT_EVAL_S``
    seconds -- the TelemetrySnapshotter shape: daemon thread, Event
    wait, ``stop()`` joins.  A tick failure is logged and the loop
    keeps going: a health-plane bug must never take the serve plane
    down with it."""

    def __init__(self, tick, interval: Optional[float] = None):
        from dprf_tpu.telemetry.alerts import eval_interval
        self.tick = tick
        self.interval = max(0.25, float(
            interval if interval is not None else eval_interval()))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:   # noqa: BLE001 -- keep monitoring
                from dprf_tpu.utils.logging import DEFAULT as log
                log.warn("health tick failed", error=str(e))
                continue

    def start(self) -> "HealthMonitor":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            daemon=True,
                                            name="dprf-health")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.tick()          # final pass: journal the end state
        except Exception:        # noqa: BLE001 -- shutdown path
            pass
