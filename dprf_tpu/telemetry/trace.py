"""Distributed tracing & flight recorder (ISSUE 4).

The metrics layer answers "how much / how fast"; this module answers
"WHICH unit, on WHICH worker, spent its time WHERE".  Every WorkUnit
gets a trace id when the Dispatcher splits it; each lifecycle step is
a SPAN -- ``lease``, ``rpc``, ``warmup``, ``sweep``, ``hit_verify``,
``complete`` / ``fail`` / ``reissue`` / ``park`` -- recorded by the
coordinator, dispatcher, and workers.  Trace context (trace id + lease
span id) rides the existing RPC messages: the lease response carries
it out, and the worker ships its spans back inside ``complete`` /
``fail``, so a remote worker's spans stitch onto the coordinator's
timeline with correct parent links even when the unit bounced between
hosts.

Spans land in two places:

  - a bounded in-memory ring (the "flight recorder"): the last N spans
    are always available for post-mortems and the ``op_trace_tail``
    RPC that feeds ``dprf top``;
  - a JSONL stream next to the session journal (``<session>
    .trace.jsonl``), size-capped with ``.1`` rotation like the
    telemetry snapshots, which ``dprf trace export`` converts to
    Chrome-trace / Perfetto JSON.

Span schema (one JSON object per line / ring entry)::

    {"name": "sweep", "ts": <epoch s>, "dur": <s>,
     "trace": "<unit trace id>", "span": "<id>", "parent": "<id|null>",
     "proc": "<coordinator|worker id|local>", "attrs": {...}}

``SPAN_NAMES`` below is the SINGLE declaration site for span names;
``tools/check_metrics.py`` (run from conftest) statically asserts that
every ``record("...")`` call site uses a declared name and that every
metric name is declared at exactly one site.

Overhead: spans are per-UNIT events (a handful per ~20-second unit),
``record`` is a dict build + deque append + one buffered file write --
asserted <= 2% of the local sweep hot path in tests/test_trace.py.
``DPRF_TRACE=0`` disables recording entirely.  Opt-in
``DPRF_JAX_PROFILE=<dir>`` additionally wraps sweep loops in a
``jax.profiler`` trace for kernel-level drill-down.
"""

from __future__ import annotations

import itertools
import json
import os
import secrets
import threading
import time
from collections import deque
from typing import Optional

from dprf_tpu.utils import env as envreg

#: the one declaration site for span names (tools/check_metrics.py
#: enforces that every record() literal is a member).  ``phase`` is a
#: child of a sampled unit's ``sweep`` span: one per attribution
#: phase (telemetry/perf.py), attrs carry which phase.
SPAN_NAMES = ("lease", "rpc", "warmup", "sweep", "hit_verify",
              "complete", "fail", "reissue", "park", "phase",
              "restore")

#: suffix appended to a session journal path for its span stream
TRACE_SUFFIX = ".trace.jsonl"

#: kill switch: DPRF_TRACE=0 disables span recording process-wide
ENABLE_ENV = "DPRF_TRACE"
#: size cap for the trace JSONL stream (rotated to `.1` when exceeded)
MAX_BYTES_ENV = "DPRF_TRACE_MAX_BYTES"
DEFAULT_MAX_BYTES = 16 << 20

#: span-id namespace: a per-process random prefix + a cheap counter --
#: unique across the fleet without paying a uuid4 per span
_ID_PREFIX = secrets.token_hex(4)
_ID_COUNTER = itertools.count(1)

#: ingest sanitization bounds (remote spans are client-controlled)
MAX_INGEST_SPANS = 64
MAX_ATTRS = 16
MAX_ATTR_STR = 256
MAX_ID_LEN = 64

#: lock-discipline declaration (`dprf check` locks analyzer): the
#: recorder is hit from RPC handler threads, the dispatcher (under
#: CoordinatorState.lock), and worker loops at once; ring and file
#: stream state must only move under ``_lock``.  The acquisition
#: order this induces -- CoordinatorState.lock, THEN _lock -- is
#: checked package-wide; code holding ``_lock`` must never call back
#: into the coordinator.
GUARDED_BY = {
    "TraceRecorder": {
        "_lock": ("_ring", "_fh", "_path", "_max_bytes",
                  "_file_bytes", "_busy"),
    },
}

#: sliding window (seconds) the live device-busy fraction is computed
#: over, and the label-cardinality cap for its per-worker gauge
BUSY_WINDOW_S = 60.0
MAX_BUSY_WORKERS = 128

#: `dprf check` threads analyzer: the flight-recorder stream is owned
#: by the recorder across attach/rotate cycles and released by
#: detach_file() (also called on re-attach).
RELEASES = {
    "TraceRecorder": {"_fh": "detach_file"},
}


def new_trace_id() -> str:
    """Trace id for one work-unit lifecycle (assigned at split time)."""
    return secrets.token_hex(8)


def new_span_id() -> str:
    return f"{_ID_PREFIX}-{next(_ID_COUNTER):x}"


def trace_path(session_path: str) -> str:
    """Span-stream location for a session journal path (idempotent:
    a path that already IS a trace stream is returned unchanged, so
    ``dprf trace export`` accepts either)."""
    if session_path.endswith(TRACE_SUFFIX):
        return session_path
    return session_path + TRACE_SUFFIX


def trace_enabled() -> bool:
    return envreg.get_bool(ENABLE_ENV)


def trace_max_bytes() -> Optional[int]:
    """Byte cap for the trace JSONL stream; 0 disables the cap (cap
    semantics shared with the telemetry snapshot cap)."""
    from dprf_tpu.telemetry.snapshot import cap_bytes
    return cap_bytes(envreg.get_int(MAX_BYTES_ENV, DEFAULT_MAX_BYTES))


def _clean_id(v) -> Optional[str]:
    if isinstance(v, str) and 0 < len(v) <= MAX_ID_LEN:
        return v
    return None


def _clean_attrs(attrs) -> dict:
    if not isinstance(attrs, dict):
        return {}
    out = {}
    for k, v in itertools.islice(attrs.items(), MAX_ATTRS):
        k = str(k)[:32]
        if isinstance(v, bool) or isinstance(v, (int, float)):
            out[k] = v
        elif isinstance(v, str):
            out[k] = v[:MAX_ATTR_STR]
        else:
            out[k] = str(v)[:MAX_ATTR_STR]
    return out


class _BusyTracker:
    """Incremental per-worker device-busy fraction over a sliding
    window -- ``trace.overlap_report``'s union-hole math kept LIVE:
    each sweep span folds its [ts, ts+dur) interval into the worker's
    merged interval set, intervals older than the window are pruned,
    and the fraction is covered / elapsed-in-window.  Driven only
    from TraceRecorder._append under its ``_lock``."""

    __slots__ = ("window", "procs")

    def __init__(self, window: float = BUSY_WINDOW_S):
        self.window = window
        #: proc -> sorted merged [[start, end], ...] within the window
        self.procs: dict = {}

    def _label(self, proc: str) -> str:
        if proc not in self.procs and len(self.procs) >= MAX_BUSY_WORKERS:
            return "_overflow"
        return proc

    def observe(self, proc: str, start: float, end: float,
                now: float) -> tuple:
        """Fold one sweep interval in; returns (gauge label, updated
        fraction)."""
        proc = self._label(proc)
        iv = self.procs.setdefault(proc, [])
        lo, hi = 0, len(iv)
        while lo < hi:
            mid = (lo + hi) // 2
            if iv[mid][0] < start:
                lo = mid + 1
            else:
                hi = mid
        i = lo
        if i > 0 and iv[i - 1][1] >= start:
            i -= 1
            iv[i][1] = max(iv[i][1], end)
        else:
            iv.insert(i, [start, end])
        j = i + 1
        while j < len(iv) and iv[j][0] <= iv[i][1]:
            iv[i][1] = max(iv[i][1], iv[j][1])
            j += 1
        del iv[i + 1:j]
        return proc, self._fraction(iv, now)

    def _fraction(self, iv: list, now: float) -> float:
        """Prune to the window, then covered / elapsed where elapsed
        runs from max(window start, first retained sweep) to now --
        so a run younger than the window is not under-read."""
        floor = now - self.window
        while iv and iv[0][1] <= floor:
            iv.pop(0)
        if iv and iv[0][0] < floor:
            iv[0][0] = floor
        if not iv:
            return 0.0
        covered = sum(e - s for s, e in iv)
        span = now - max(floor, iv[0][0])
        if span <= 0:
            return 1.0
        return min(1.0, covered / span)

    def fractions(self, now: float) -> dict:
        return {proc: round(self._fraction(iv, now), 4)
                for proc, iv in self.procs.items()}


class TraceRecorder:
    """Bounded flight-recorder ring + optional JSONL stream.

    Thread-safe; ``record`` is the only hot-path entry and returns the
    span dict (so a worker can ship it over RPC) or None when tracing
    is disabled.  One recorder per process is the normal shape (the
    module-level DEFAULT); tests construct their own.
    """

    def __init__(self, capacity: int = 4096, clock=time.time,
                 enabled: Optional[bool] = None, proc: str = "local",
                 registry=None):
        self._ring: deque = deque(maxlen=max(16, int(capacity)))
        self._clock = clock
        self.enabled = trace_enabled() if enabled is None else enabled
        self.proc = proc
        self._lock = threading.Lock()
        self._fh = None
        self._path: Optional[str] = None
        self._max_bytes: Optional[int] = None
        self._file_bytes = 0
        #: live device-utilization state: sweep spans fold into a
        #: sliding-window interval union per worker (ISSUE 9)
        self._busy = _BusyTracker()
        from dprf_tpu.telemetry import get_registry
        self._m_spans = get_registry(registry).counter(
            "dprf_trace_spans_total",
            "lifecycle spans recorded into the flight recorder")
        #: the trace_drops alert condition (telemetry/alerts.py): a
        #: sustained nonzero rate means the timeline is lying by
        #: omission -- spans over the ingest bound, failing
        #: sanitization, or lost to a dead stream write
        self._m_dropped = get_registry(registry).counter(
            "dprf_trace_spans_dropped_total",
            "spans dropped at remote ingest (over the per-message "
            "bound or failing sanitization) or lost to a failed "
            "trace-stream write")
        self._g_busy = get_registry(registry).gauge(
            "dprf_device_busy_fraction",
            "fraction of the sliding window each worker's sweep "
            "spans cover (union holes = device idle; the live form "
            "of tools/trace_overlap.py)", labelnames=("worker",))

    # -- recording -------------------------------------------------------

    def record(self, name: str, dur: float = 0.0, ts: Optional[float] = None,
               trace: Optional[str] = None, parent: Optional[str] = None,
               proc: Optional[str] = None, span: Optional[str] = None,
               **attrs) -> Optional[dict]:
        """Record one span; ``ts`` defaults to now - dur (i.e. the
        caller measured ``dur`` ending now).  ``span`` overrides the
        generated span id -- how a sampled sweep's pre-allocated id
        (telemetry/perf.py) lets its phase children parent onto a
        span recorded later.  Returns the span dict (shippable over
        RPC) or None when disabled."""
        if not self.enabled:
            return None
        if ts is None:
            ts = self._clock() - dur
        span = {"name": name, "ts": round(float(ts), 6),
                "dur": round(float(dur), 6), "trace": trace,
                "parent": parent, "span": span or new_span_id(),
                "proc": proc if proc is not None else self.proc,
                "attrs": attrs}
        self._append(span)
        return span

    def ingest(self, spans, proc: Optional[str] = None,
               sent_at=None, limit: Optional[int] = None) -> int:
        """Fold REMOTE spans (shipped inside an RPC complete/fail
        message) into this recorder.  Client-controlled data, so
        sanitize hard: bounded count, declared span names only, scalar
        attrs, and ``proc`` forced to the server-known worker id when
        given -- a worker cannot impersonate another's timeline.
        ``limit`` overrides the per-message span bound (the
        ring-sized op_trace_push path); the per-unit default stays
        MAX_INGEST_SPANS.

        ``sent_at`` is the sender's wall clock at send time: span
        timestamps are REBASED by (our now - sent_at), so a fleet
        whose hosts disagree by NTP drift still renders one coherent
        timeline (residual error = one-way network latency, seconds of
        drift otherwise)."""
        if not self.enabled or not isinstance(spans, list):
            return 0
        offset = 0.0
        if isinstance(sent_at, (int, float)):
            offset = self._clock() - float(sent_at)
        n = 0
        bound = limit if limit is not None else MAX_INGEST_SPANS
        dropped = max(0, len(spans) - bound)
        for s in spans[:bound]:
            if not isinstance(s, dict):
                dropped += 1
                continue
            name = s.get("name")
            if not isinstance(name, str) or name not in SPAN_NAMES:
                dropped += 1
                continue
            try:
                ts = float(s.get("ts", 0.0))
                dur = float(s.get("dur", 0.0))
            except (TypeError, ValueError):
                dropped += 1
                continue
            clean = {"name": name, "ts": round(ts + offset, 6),
                     "dur": round(dur, 6),
                     "trace": _clean_id(s.get("trace")),
                     "parent": _clean_id(s.get("parent")),
                     "span": _clean_id(s.get("span")) or new_span_id(),
                     "proc": str(proc if proc is not None
                                 else s.get("proc", "?"))[:MAX_ID_LEN],
                     "attrs": _clean_attrs(s.get("attrs"))}
            self._append(clean)
            n += 1
        if dropped:
            self._m_dropped.inc(dropped)
        return n

    def _append(self, span: dict) -> None:
        self._m_spans.inc()
        busy = None
        lost_write = False
        with self._lock:
            if span["name"] == "sweep" and span["dur"] > 0:
                # live utilization: fold the sweep interval into the
                # worker's window union (both local records and
                # coordinator-rebased ingests land here)
                busy = self._busy.observe(
                    str(span.get("proc") or "?"), span["ts"],
                    span["ts"] + span["dur"], self._clock())
            self._ring.append(span)
            if self._fh is not None:
                try:
                    data = json.dumps(span, separators=(",", ":"),
                                      default=str) + "\n"
                    if (self._max_bytes is not None
                            and self._file_bytes
                            and self._file_bytes + len(data)
                            > self._max_bytes):
                        self._rotate_locked()
                    if self._fh is not None:
                        self._fh.write(data)
                        self._fh.flush()
                        self._file_bytes += len(data)
                except OSError:
                    # a full disk must not kill the job, but a span
                    # the stream lost is a drop the alert engine
                    # should see (counted below, outside the lock)
                    lost_write = True
        if busy is not None:
            # gauge set OUTSIDE _lock: code holding _lock must never
            # call into other locked subsystems (lock-order contract)
            self._g_busy.set(busy[1], worker=busy[0])
        if lost_write:
            self._m_dropped.inc()

    def _rotate_locked(self) -> None:
        """Size-cap rotation: the stream moves to ``<path>.1``
        (replacing any previous rotation) and restarts -- a long serve
        session holds at most ~2x the cap on disk.  An unusable
        rotation target truncates in place instead (the cap must hold
        either way); an unreopenable path degrades to ring-only."""
        try:
            self._fh.close()
        except OSError:
            pass
        mode = "a"
        try:
            os.replace(self._path, self._path + ".1")
        except OSError:
            mode = "w"
        try:
            self._fh = open(self._path, mode, encoding="utf-8")
            self._file_bytes = 0
        except OSError:
            self._fh = None
    _rotate_locked._holds_lock = "_lock"   # only _append calls it

    # -- file stream -----------------------------------------------------

    def attach_file(self, path: str,
                    max_bytes: Optional[int] = None) -> "TraceRecorder":
        """Stream subsequent spans to a JSONL file (the session's
        flight-recorder journal).  Ring contents recorded BEFORE the
        attach are not replayed -- the file is this run's record, the
        ring is the process's."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._path = path
            self._max_bytes = (trace_max_bytes() if max_bytes is None
                               else (max_bytes or None))
            self._fh = open(path, "a", encoding="utf-8")
            try:
                self._file_bytes = os.path.getsize(path)
            except OSError:
                self._file_bytes = 0
        return self

    def detach_file(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._fh = None
            self._path = None

    # -- reads -----------------------------------------------------------

    def tail(self, n: int = 200, trace: Optional[str] = None) -> list:
        """The most recent n spans (optionally one trace's), oldest
        first -- the op_trace_tail payload."""
        with self._lock:
            items = list(self._ring)
        if trace is not None:
            items = [s for s in items if s.get("trace") == trace]
        return [dict(s) for s in items[-max(1, int(n)):]]

    def tail_after(self, since: Optional[str], n: int = 200,
                   trace: Optional[str] = None) -> tuple:
        """Incremental flight-recorder read (``dprf top --follow``):
        (spans recorded AFTER the span id ``since``, resync flag),
        oldest first.  When ``since`` is unknown -- first call, or the
        ring wrapped past it -- the plain tail comes back with
        resync=True and the caller must REPLACE its buffer, not
        append."""
        with self._lock:
            items = list(self._ring)
        idx = None
        if since:
            # scan from the new end: the cursor is almost always near it
            for i in range(len(items) - 1, -1, -1):
                if items[i].get("span") == since:
                    idx = i
                    break
        resync = idx is None
        out = items if resync else items[idx + 1:]
        if trace is not None:
            out = [s for s in out if s.get("trace") == trace]
        n = max(1, int(n))
        if len(out) > n:
            # the increment itself overflows the window: the caller
            # cannot stitch it onto its buffer without a silent hole,
            # so this is a resync too (replace, newest n)
            out = out[-n:]
            resync = True
        return [dict(s) for s in out], resync

    def head_after(self, since: Optional[str], n: int = 200) -> tuple:
        """Forward pager for a FULL ring dump (op_trace_pull): (up to
        n spans recorded after span id ``since``, resync flag), oldest
        first, starting at the ring's OLDEST span when ``since`` is
        None.  Unlike ``tail_after`` -- which serves live follow and
        clamps to the newest window -- an oversized remainder pages
        from the front; the caller walks forward until a short page.
        An unknown cursor (the ring wrapped past it) restarts from the
        oldest with resync=True: the caller replaces its buffer."""
        with self._lock:
            items = list(self._ring)
        idx = None
        if since:
            # scan from the new end: the cursor is usually near it
            for i in range(len(items) - 1, -1, -1):
                if items[i].get("span") == since:
                    idx = i
                    break
        resync = since is not None and idx is None
        out = items if idx is None else items[idx + 1:]
        return [dict(s) for s in out[:max(1, int(n))]], resync

    def busy_fractions(self) -> dict:
        """{worker: live busy fraction} over the sliding window,
        recomputed against the current clock (so an idle fleet's
        fractions decay between sweeps) -- the op_trace_tail status
        payload and the ``dprf top`` header read this."""
        with self._lock:
            return self._busy.fractions(self._clock())

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._busy.procs.clear()


#: process-wide recorder, like telemetry.DEFAULT: library code with no
#: recorder threaded through records here
DEFAULT_TRACER = TraceRecorder()


def get_tracer(recorder: Optional[TraceRecorder] = None) -> TraceRecorder:
    return recorder if recorder is not None else DEFAULT_TRACER


def span_id(span: Optional[dict]) -> Optional[str]:
    """The id of a recorded span, tolerating a disabled recorder's
    None."""
    return span["span"] if span else None


# ---------------------------------------------------------------------------
# trace-file loading + analysis (dprf trace export, tests)

def load_trace(path: str) -> list:
    """Read a span stream back (rotated ``.1`` part first, torn tail
    lines skipped), sorted by start time."""
    spans = []
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    s = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(s, dict) and isinstance(s.get("name"), str) \
                        and "ts" in s:
                    spans.append(s)
    spans.sort(key=lambda s: (s.get("ts", 0.0), s.get("span") or ""))
    return spans


def lifecycle_report(spans: list) -> dict:
    """Reconstruct per-unit lifecycles: for every trace id, the ordered
    span names, the procs that touched it, lease/terminal accounting,
    and ORPHANS (spans whose parent id never appears in their trace --
    a broken context-propagation link)."""
    traces: dict = {}
    for s in spans:
        tid = s.get("trace")
        if not tid:
            continue
        t = traces.setdefault(tid, {"spans": [], "ids": set()})
        t["spans"].append(s)
        sid = s.get("span")
        if sid:
            t["ids"].add(sid)
    details = {}
    orphans = 0
    incomplete = []
    for tid, t in traces.items():
        names = [s["name"] for s in t["spans"]]
        t_orphans = [s.get("span") for s in t["spans"]
                     if s.get("parent") and s["parent"] not in t["ids"]]
        orphans += len(t_orphans)
        terminal = any(n in ("complete", "park") for n in names)
        if not terminal:
            incomplete.append(tid)
        details[tid] = {
            "names": names,
            "procs": sorted({str(s.get("proc")) for s in t["spans"]}),
            "leases": names.count("lease"),
            "reissues": names.count("reissue"),
            "terminal": terminal,
            "orphans": t_orphans,
        }
    return {"traces": len(traces), "spans": len(spans),
            "orphans": orphans, "incomplete": sorted(incomplete),
            "details": details}


def overlap_report(spans: list) -> dict:
    """Per-worker device-idle analysis of a span stream -- the
    ``tools/trace_overlap.py`` report, and the ROADMAP "span-level
    assertions back perf PRs" item.

    For every proc with ``sweep`` spans, the gaps are the HOLES in the
    union of its sweep intervals: walking spans by start time with a
    running coverage frontier ``end = max(end, span.ts + span.dur)``,
    a span starting past the frontier opens a device-idle hole of
    ``span.ts - end`` seconds.  (Pipelined sweeps overlap -- several
    units ride the stream at once and an ahead-batch's sweeps share a
    start time -- so pairwise prev/next differences would misread tied
    orderings; union holes are order-stable.)  On a pipelined worker
    the max hole must stay below the RPC round trip; the serial loop
    idles ~2 RTT per unit.  ``overlapped`` counts sweeps that started
    before the coverage frontier (pipeline overlap events), and
    ``complete_overlaps`` counts sweeps that started before the
    coordinator recorded the PREVIOUS unit's ``complete`` span --
    proof the report round trip overlapped device work.  (Both clocks
    are coordinator-rebased at ingest, so every comparison is within
    one timeline.)"""
    completes: dict = {}
    for s in spans:
        if s.get("name") == "complete":
            u = (s.get("attrs") or {}).get("unit")
            if u is not None:
                completes[u] = float(s.get("ts", 0.0))
    by_proc: dict = {}
    for s in spans:
        if s.get("name") == "sweep":
            by_proc.setdefault(str(s.get("proc")), []).append(s)
    workers = {}
    for proc, sw in by_proc.items():
        sw.sort(key=lambda s: float(s.get("ts", 0.0)))
        gaps, overlapped, c_overlaps = [], 0, 0
        end = None
        for i, s in enumerate(sw):
            ts = float(s.get("ts", 0.0))
            if end is not None:
                if ts > end:
                    gaps.append(ts - end)
                else:
                    overlapped += 1
            if i > 0:
                ct = completes.get(
                    (sw[i - 1].get("attrs") or {}).get("unit"))
                if ct is not None and ts < ct:
                    c_overlaps += 1
            send = ts + float(s.get("dur", 0.0))
            end = send if end is None else max(end, send)
        workers[proc] = {
            "sweeps": len(sw),
            "sweep_s": round(sum(float(s.get("dur", 0.0))
                                 for s in sw), 6),
            "gaps": len(sw) - 1,
            "holes": len(gaps),
            "idle_s": round(sum(gaps), 6),
            "max_gap_s": round(max(gaps), 6) if gaps else 0.0,
            "overlapped": overlapped,
            "complete_overlaps": c_overlaps,
        }
    return {"workers": workers,
            "max_gap_s": round(max(
                (w["max_gap_s"] for w in workers.values()),
                default=0.0), 6)}


def export_chrome_trace(spans: list) -> dict:
    """Spans -> Chrome-trace JSON (the "JSON Array Format" with
    metadata events), loadable in Perfetto / chrome://tracing.

    Mapping: pid = actor (coordinator / worker id / local), tid = one
    work-unit trace within that actor -- so a reissued unit renders as
    aligned lanes across the workers that touched it.  Timestamps are
    microseconds relative to the earliest span (absolute epoch kept in
    ``otherData``)."""
    pids: dict = {}
    tids: dict = {}
    events = []

    def pid_of(proc: str) -> int:
        if proc not in pids:
            pids[proc] = len(pids) + 1
            events.append({"name": "process_name", "ph": "M", "cat": "__metadata",
                           "pid": pids[proc], "tid": 0,
                           "args": {"name": proc}})
        return pids[proc]

    def tid_of(pid: int, tid_key) -> int:
        key = (pid, tid_key)
        if key not in tids:
            tids[key] = len(tids) + 1
            label = (f"unit trace {str(tid_key)[:10]}"
                     if tid_key != "-" else "untraced")
            events.append({"name": "thread_name", "ph": "M",
                           "cat": "__metadata", "pid": pid,
                           "tid": tids[key], "args": {"name": label}})
        return tids[key]

    t0 = min((float(s.get("ts", 0.0)) for s in spans), default=0.0)
    for s in spans:
        proc = str(s.get("proc") or "?")
        pid = pid_of(proc)
        tid = tid_of(pid, s.get("trace") or "-")
        dur_us = max(float(s.get("dur", 0.0)) * 1e6, 1.0)
        args = dict(s.get("attrs") or {})
        args.update({"trace": s.get("trace"), "span": s.get("span"),
                     "parent": s.get("parent")})
        events.append({"name": s["name"], "cat": "dprf", "ph": "X",
                       "ts": round((float(s["ts"]) - t0) * 1e6, 3),
                       "dur": round(dur_us, 3),
                       "pid": pid, "tid": tid, "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"tool": "dprf trace export",
                          "t0_epoch_s": t0, "spans": len(spans)}}


# ---------------------------------------------------------------------------
# dprf top rendering

def _fmt_age(s: float) -> str:
    if s < 0:
        return "expired"
    if s < 120:
        return f"{s:.0f}s"
    return f"{s / 60:.1f}m"


def _fmt_bytes(v) -> str:
    if not isinstance(v, (int, float)) or v <= 0:
        return "-"
    for unit, div in (("G", 1 << 30), ("M", 1 << 20), ("K", 1 << 10)):
        if v >= div:
            return f"{v / div:.1f}{unit}"
    return str(int(v))


def render_top(resp: dict, prev: Optional[tuple] = None) -> str:
    """One frame of the ``dprf top`` live view from an op_trace_tail
    response.  ``prev`` is (monotonic_time, status) of the previous
    frame, used for the interval throughput estimate."""
    status = resp.get("status") or {}
    spans = resp.get("spans") or []
    leases = resp.get("leases") or []
    done = status.get("done", 0)
    total = max(status.get("total", 0), 1)
    lines = []
    rate = ""
    if prev:
        t_prev, s_prev = prev
        dt = time.monotonic() - t_prev
        if dt > 0:
            rate = f" | {max(done - s_prev.get('done', 0), 0) / dt:,.0f}/s"
    state = "FINISHED" if status.get("stop") else "running"
    # live utilization & roofline distance (ISSUE 9): mean sweep-span
    # window coverage across workers, and the per-engine fraction of
    # the int32 roofline ceiling the fleet's throughput reaches
    busy = status.get("busy") or {}
    busy_s = ""
    if busy:
        busy_s = (f" | busy {100.0 * sum(busy.values()) / len(busy):.0f}%"
                  f" ({len(busy)}w)")
    roofline = status.get("roofline") or {}
    roof_s = ""
    if roofline:
        roof_s = " | roofline " + " ".join(
            f"{e}:{f:.2f}" for e, f in sorted(roofline.items()))
    # fleet HBM header (ISSUE 13): summed worker memory from the
    # heartbeat payloads; absent on fleets without memory stats
    hbm = status.get("hbm") or {}
    hbm_s = ""
    if hbm.get("limit"):
        hbm_s = (f" | hbm {_fmt_bytes(hbm.get('in_use', 0))}"
                 f"/{_fmt_bytes(hbm['limit'])}"
                 f" ({hbm.get('workers', 0)}w)")
    lines.append(
        f"dprf top — {state} | found {status.get('found', 0)}"
        f"/{status.get('targets', '?')} | "
        f"{100.0 * done / total:.2f}% covered | parked "
        f"{status.get('parked', 0)} | elapsed "
        f"{status.get('elapsed', 0.0):.0f}s{rate}{busy_s}{roof_s}"
        f"{hbm_s}")
    quarantined = status.get("quarantined") or []
    if quarantined:
        lines.append(f"quarantined workers: {', '.join(quarantined)}")
    # fleet health plane (ISSUE 10): firing alerts lead the frame --
    # an operator watching top must not need a second terminal to
    # learn the fleet is on fire
    firing = status.get("alerts") or []
    if firing:
        lines.append(f"FIRING ALERTS: {', '.join(firing)}")
    # per-job table (multi-tenant serve plane): one row per scheduler
    # job once the coordinator holds more than the default job
    jobs = status.get("jobs") or []
    if len(jobs) > 1:
        lines.append("")
        lines.append(f"{'JOB':6s} {'OWNER':12s} {'PRIO':>4s} "
                     f"{'STATE':10s} {'COVERED':>20s} {'FOUND':>7s} "
                     f"{'OUT':>4s} {'LEASES':>7s}")
        for j in jobs:
            cov = f"{j.get('done', 0)}/{j.get('total', 0)}"
            fnd = f"{j.get('found', 0)}/{j.get('targets', 0)}"
            lines.append(
                f"{str(j.get('id'))[:6]:6s} "
                f"{str(j.get('owner'))[:12]:12s} "
                f"{j.get('priority', 1):>4d} "
                f"{str(j.get('state'))[:10]:10s} {cov:>20s} "
                f"{fnd:>7s} {j.get('outstanding', 0):>4d} "
                f"{j.get('leases', 0):>7d}")
    # per-worker table: current lease + the worker's most recent span,
    # GROUPED by the job each worker is currently leased to (so a
    # multi-tenant fleet reads per job), with the live busy fraction
    last_span: dict = {}
    for s in spans:
        last_span[str(s.get("proc"))] = s
    by_worker = {str(l.get("worker")): l for l in leases}
    # workers known only to the health plane (heartbeating while
    # holding no lease -- or missing/dead) still get a row: a silent
    # worker that vanished from the lease table is exactly the one
    # the operator is looking for
    health = status.get("health") or {}
    workers = sorted(set(by_worker)
                     | set(health)
                     | {p for p in last_span
                        if p not in ("coordinator",)})
    # grouping key: the worker's current job first ("-" for idle
    # workers, sorted last), then worker id -- stable per-job blocks
    workers.sort(key=lambda w: (
        str((by_worker.get(w) or {}).get("job", "~")), w))
    mem = status.get("mem") or {}
    # kernel-profiling plane (ISSUE 15): last capture per worker --
    # the coordinator's pushed-summary table, with the heartbeat
    # payload's profile_ts/profile_trigger as the fallback for
    # env-local captures that never pushed
    profiles = status.get("profiles") or {}
    lines.append("")
    lines.append(f"{'WORKER':20s} {'JOB':>5s} {'STATE':10s} "
                 f"{'UNIT':>8s} {'RANGE':>24s} {'LEASE':>8s} "
                 f"{'BUSY':>5s} {'MEM':>6s} {'HEALTH':>8s} "
                 f"{'PROF':>14s} {'LAST SPAN':>10s}")
    # ages against the COORDINATOR's clock (shipped in status): the
    # spans carry its wall time, and the viewer's clock may be skewed
    now = status.get("now") or time.time()
    for w in workers:
        lease = by_worker.get(w)
        s = last_span.get(w)
        state = s["name"] if s else ("sweep" if lease else "idle")
        # the unit column names the owning job too (unit ids are only
        # unique within a job's ledger)
        jid = str(lease.get("job", "?")) if lease else "-"
        unit = f"{jid}#{lease['unit']}" if lease else "-"
        rng = (f"[{lease['start']},{lease['start'] + lease['length']})"
               if lease else "-")
        dl = _fmt_age(lease["deadline_s"]) if lease else "-"
        b = busy.get(w)
        b_s = f"{100.0 * b:.0f}%" if b is not None else "-"
        hw = str(health.get(w) or "-")[:8]
        m_s = _fmt_bytes(mem.get(w))
        p = profiles.get(w)
        p_ts, p_trig = ((p.get("ts"), p.get("trigger"))
                        if isinstance(p, dict) else (None, None))
        prof = (f"{_fmt_age(max(0.0, now - p_ts))}/"
                f"{str(p_trig or '?')[:8]}"
                if isinstance(p_ts, (int, float)) else "-")
        age = (_fmt_age(max(0.0, now - (s.get("ts", now)
                                        + s.get("dur", 0.0))))
               if s else "-")
        lines.append(f"{w[:20]:20s} {jid[:5]:>5s} {state:10s} "
                     f"{unit:>8s} {rng:>24s} {dl:>8s} {b_s:>5s} "
                     f"{m_s:>6s} {hw:>8s} {prof:>14s} {age:>10s}")
    lines.append("")
    lines.append("recent spans:")
    for s in spans[-8:]:
        tid = (s.get("trace") or "-")[:8]
        lines.append(f"  {s['name']:11s} trace={tid:8s} "
                     f"proc={str(s.get('proc'))[:16]:16s} "
                     f"dur={s.get('dur', 0.0):.3f}s")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# opt-in jax.profiler wrapping of sweep loops

def jax_profile_ctx(log=None):
    """``DPRF_JAX_PROFILE=<dir>``: a jax.profiler trace context for a
    sweep loop, now owned by telemetry/profiler.py's single-flight
    ProfileCapture (jax allows ONE active trace; the ``--profile``
    flag and on-demand capture windows share the same slot).  Kept
    here as a re-export for the loop call sites."""
    from dprf_tpu.telemetry import profiler as profiler_mod
    return profiler_mod.jax_profile_ctx(log=log)
