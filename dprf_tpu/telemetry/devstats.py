"""Device-memory accounting (ISSUE 13): ``device.memory_stats()``
polled into HBM gauges, plus the OOM-headroom estimate the sizing
layers consult.

Nothing in the stack observed device memory before this module, even
though the next scenario axes (10^6-10^7-target device-resident probe
tables, superstep ``hit_capacity`` fusion) are fundamentally
HBM-budget problems.  jax exposes the allocator's live counters on
real devices as ``device.memory_stats()`` (``bytes_in_use``,
``bytes_limit``, ``peak_bytes_in_use``); CPU/interpret backends return
None -- the GRACEFUL-NONE contract every reader here keeps: a backend
without stats publishes nothing and every derived estimate returns
None, never a made-up number.

Surfaces:

  - ``poll()``            one pass over ``jax.local_devices()`` into
        ``dprf_hbm_bytes_in_use/_limit/_peak{device}``; returns the
        per-device snapshot dict ({} off-HBM backends).
  - ``DevstatsPoller``    background loop on the ``DPRF_DEVSTATS_POLL_S``
        cadence (TelemetrySnapshotter shape: daemon thread, Event
        wait, stop() joins; 0 disables) -- started by serve/crack so
        the session telemetry snapshots carry the HBM timeline.
  - ``summary()``         host totals for the worker heartbeat payload
        (hbm_in_use / hbm_limit / hbm_peak) and the ``dprf top``
        header.
  - ``headroom_frac()``   free fraction of the HBM limit -- the
        OOM-headroom estimate: the adaptive unit sizer halves its next
        units under ``LOW_HEADROOM_FRAC`` and the tune ladder stops
        climbing when a projected program footprint exceeds the free
        bytes.
"""

from __future__ import annotations

import threading
from typing import Optional

from dprf_tpu.telemetry import get_registry
from dprf_tpu.utils import env as envreg

POLL_ENV = "DPRF_DEVSTATS_POLL_S"

#: free-HBM fraction under which the adaptive unit sizer halves its
#: next units (tune/unit_sizer.py): cheap insurance against sizing
#: into an allocator already near its ceiling
LOW_HEADROOM_FRAC = 0.10

#: memory_stats keys -> our gauge suffixes (allocator counters differ
#: slightly across backends; missing keys simply publish nothing)
_STAT_KEYS = (("bytes_in_use", "in_use"),
              ("bytes_limit", "limit"),
              ("peak_bytes_in_use", "peak"))


def poll_interval(default: float = 15.0) -> float:
    v = envreg.get_float(POLL_ENV, default)
    return max(0.0, float(v or 0.0))


def device_memory_stats() -> dict:
    """{device label: {in_use, limit, peak}} over the local devices;
    {} when jax is absent or no device reports memory stats (the CPU
    backend's documented None)."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:   # noqa: BLE001 -- jax-less host
        return {}
    out = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:   # noqa: BLE001 -- backend without the API
            stats = None
        if not isinstance(stats, dict):
            continue
        rec = {}
        for theirs, ours in _STAT_KEYS:
            v = stats.get(theirs)
            if isinstance(v, (int, float)):
                rec[ours] = int(v)
        if rec:
            out[f"{d.platform}:{d.id}"] = rec
    return out


def _hbm_gauges(registry=None) -> tuple:
    """ONE declaration site for the three HBM gauges."""
    m = get_registry(registry)
    return (
        m.gauge("dprf_hbm_bytes_in_use",
                "device allocator bytes currently in use "
                "(device.memory_stats; absent on backends without "
                "memory accounting)", labelnames=("device",)),
        m.gauge("dprf_hbm_bytes_limit",
                "device allocator byte limit (the HBM budget every "
                "probe-table / superstep sizing decision is against)",
                labelnames=("device",)),
        m.gauge("dprf_hbm_bytes_peak",
                "high-water mark of device allocator bytes in use",
                labelnames=("device",)),
    )


def poll(registry=None) -> dict:
    """One polling pass: publish the gauges, return the snapshot."""
    snap = device_memory_stats()
    if not snap:
        return snap
    g_use, g_limit, g_peak = _hbm_gauges(registry)
    for dev, rec in snap.items():
        if "in_use" in rec:
            g_use.set(rec["in_use"], device=dev)
        if "limit" in rec:
            g_limit.set(rec["limit"], device=dev)
        if "peak" in rec:
            g_peak.set(rec["peak"], device=dev)
    return snap


def summary(snap: Optional[dict] = None) -> Optional[dict]:
    """Host totals {in_use, limit, peak} summed over devices, or None
    on a backend without memory stats (heartbeat payload / top
    header)."""
    if snap is None:
        snap = device_memory_stats()
    if not snap:
        return None
    out = {"in_use": 0, "limit": 0, "peak": 0}
    for rec in snap.values():
        for k in out:
            out[k] += rec.get(k, 0)
    return out


def bytes_free(snap: Optional[dict] = None) -> Optional[int]:
    """limit - in_use summed over devices; None without stats."""
    s = summary(snap)
    if s is None or not s.get("limit"):
        return None
    return max(0, s["limit"] - s["in_use"])


def headroom_frac(snap: Optional[dict] = None) -> Optional[float]:
    """Free fraction of the HBM limit (the OOM-headroom estimate);
    None on backends without memory stats -- callers treat None as
    'no signal', never as 'plenty free'."""
    s = summary(snap)
    if s is None or not s.get("limit"):
        return None
    return max(0.0, 1.0 - s["in_use"] / s["limit"])


def peak_hbm_bytes() -> tuple:
    """(peak bytes, source) for a bench result: the allocator's
    measured high-water mark when the backend has one, else the
    largest ANALYZED program footprint (telemetry/programs.py) as a
    model-derived stand-in, else (None, None).  The source tag keeps
    the two honest in the trajectory."""
    s = summary()
    if s is not None and s.get("peak"):
        return s["peak"], "memory_stats"
    from dprf_tpu.telemetry import programs as programs_mod
    peak = programs_mod.get_programs().peak_bytes()
    if peak:
        return peak, "program_analysis"
    return None, None


class DevstatsPoller:
    """Background HBM polling loop (TelemetrySnapshotter shape).  A
    no-stats backend makes every tick a cheap no-op; interval 0 (the
    knob) makes start() a no-op entirely."""

    def __init__(self, registry=None, interval: Optional[float] = None):
        self.registry = registry
        self.interval = (poll_interval() if interval is None
                         else max(0.0, float(interval)))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                poll(self.registry)
            except Exception:   # noqa: BLE001 -- diagnostics only;
                continue        # a poll failure must not kill the loop

    def start(self) -> "DevstatsPoller":
        if self.interval <= 0:
            return self
        if self._thread is None:
            poll(self.registry)          # one immediate sample
            self._thread = threading.Thread(target=self._run,
                                            daemon=True,
                                            name="dprf-devstats")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            poll(self.registry)          # final sample for the journal
        except Exception:   # noqa: BLE001 -- shutdown path
            pass


__all__ = ["DevstatsPoller", "LOW_HEADROOM_FRAC", "POLL_ENV",
           "bytes_free", "device_memory_stats", "headroom_frac",
           "peak_hbm_bytes", "poll", "poll_interval", "summary"]
