"""Coverage audit plane (ISSUE 19): prove every candidate is tried
exactly once.

The metrics layer answers "how much / how fast" and the trace layer
answers "which unit, where"; this module answers the invariant that
actually defines correctness for a cracking run: **did the fleet
cover the keyspace exactly once?**  A silent gap is a missed password
and a silent overlap is wasted H/s, and the interval arithmetic that
decides both is spread across lease/complete/reissue/park, journal
resume, unit resplit, hit-capacity redrive, and sharded superstep
windows.

One ``CoverageLedger`` per job, owned and fed by its Dispatcher (and
therefore serialized by the same caller lock -- see GUARDED_BY).  The
ledger is an interval set over the generator's index space plus a
live-unit table: every range-mutating event flows through ONE event
API, ``ledger.event(name, ...)``, whose names are declared below in
``EVENT_NAMES`` exactly like ``trace.SPAN_NAMES`` -- and the
``coverage-events`` analyzer (analysis/coverage_events.py) statically
verifies both that every event literal is declared and that every
Dispatcher/worker site that mutates a unit's index range calls the
API (``COVERAGE_EVENT_SITES`` below is the site manifest it checks).

What the ledger detects, live:

  - **overlaps at insert time**: ``complete`` folds the unit's range
    into the covered set via an O(log n) merged-interval insert that
    returns the NEWLY covered length; any shortfall is double-covered
    keyspace (a stale lease that slipped the guard, a resume that
    re-ran finished work) and increments
    ``dprf_job_coverage_overlap_total``;
  - **gaps against the declared keyspace**: every index must at all
    times be covered, live on a split unit (pending / outstanding /
    parked), or not yet split (above the split frontier).  Anything
    else was LOST -- ``dprf_job_coverage_gap_total`` goes nonzero and
    the ``coverage_gap`` alert fires.

The ledger also computes an order-independent **coverage digest**:
sha256 over the keyspace size and the canonical merged covered
intervals (the same 16-hex shape as ``session.job_fingerprint``).
Journals and completion records carry it; a coordinator rebuild
(``Dispatcher.from_completed``) must REPRODUCE it from the journaled
intervals or refuse the resume -- the PR 14 fingerprint discipline
applied to coverage state.  ``dprf audit SESSION``
(perfreport/audit.py) reconstructs the whole story offline from
session artifacts alone.

Worker-side range mutations (hit-capacity redrive, rescan, sharded
superstep windows) happen on hot paths in worker processes, far from
any ledger.  They report through the module-level ``note()`` API:
a counter bump by default (far under the <=2% overhead budget), plus
an optional process-local collector that the chaos harness and tests
install to assert the windows tile each unit exactly once.

``DPRF_COVERAGE=0`` disables the plane process-wide (the ledger still
answers digests -- resume correctness must not depend on a telemetry
knob -- but stops detecting, counting, and exporting).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional

from dprf_tpu.utils import env as envreg

#: the one declaration site for coverage event names (the
#: coverage-events analyzer enforces that every ``.event("...")`` /
#: ``coverage.note("...")`` literal is a member).  Range semantics:
#:
#:   split      a unit was cut from the keyspace (lazy split or resume
#:              resplit): its range becomes LIVE
#:   restore    journaled covered interval folded in at rebuild
#:   resplit    a resume gap below the frontier was re-split into units
#:   lease      a live unit went out on a lease (no range movement)
#:   complete   a live unit's range moved into the covered set
#:   fail       a leased unit was released by its worker
#:   reissue    a failed/expired unit went back on the queue
#:   park       a unit burned its retry budget (still live: parked
#:              ranges are accounted, intentionally unreachable)
#:   unpark     a parked unit re-entered the queue (retry-parked op)
#:   abandon    job cancel: every live unit dropped, ledger frozen
#:   force_complete  the coordinator completed a unit on worker
#:              consensus-of-rejection (rpc.op_complete): covered, but
#:              flagged -- the range may hold an unrecovered crack
#:   redrive    worker re-enqueued a sub-range after hit-buffer
#:              overflow (worker-side, via note())
#:   rescan     worker re-swept a collided tile/window (worker-side)
#:   window     one superstep window dispatched over [start, end)
#:              (worker-side; windows must tile the unit)
EVENT_NAMES = ("split", "restore", "resplit", "lease", "complete",
               "fail", "reissue", "park", "unpark", "abandon",
               "force_complete", "redrive", "rescan", "window")

#: worker-side events that flow through note() rather than a ledger
NOTE_EVENTS = ("redrive", "rescan", "window")

#: site manifest for the coverage-events analyzer: every
#: (file, function) here must exist and call the event API -- the
#: one-declaration-site discipline that keeps future refactors from
#: silently bypassing the audit.  Paths are repo-relative.
COVERAGE_EVENT_SITES = (
    ("dprf_tpu/runtime/dispatcher.py", "_make_unit"),
    ("dprf_tpu/runtime/dispatcher.py", "from_completed"),
    ("dprf_tpu/runtime/dispatcher.py", "lease"),
    ("dprf_tpu/runtime/dispatcher.py", "complete"),
    ("dprf_tpu/runtime/dispatcher.py", "fail"),
    ("dprf_tpu/runtime/dispatcher.py", "_requeue"),
    ("dprf_tpu/runtime/dispatcher.py", "retry_parked"),
    ("dprf_tpu/runtime/dispatcher.py", "abandon"),
    ("dprf_tpu/runtime/rpc.py", "op_complete"),
    ("dprf_tpu/runtime/worker.py", "_redrive_wide"),
    ("dprf_tpu/runtime/worker.py", "_rescan"),
    ("dprf_tpu/runtime/worker.py", "_redrive_wide_words"),
    ("dprf_tpu/runtime/worker.py", "_rescan_words"),
    ("dprf_tpu/parallel/worker.py", "_redrive_sharded_words"),
    # every submit() in the sharded module notes its superstep /
    # per-batch dispatch windows ("window" tiling evidence); the
    # sharded word rescan is the inherited WordlistWorkerBase
    # _rescan_words above
    ("dprf_tpu/parallel/worker.py", "submit"),
)

#: kill switch: DPRF_COVERAGE=0 disables ledger accounting + notes
ENABLE_ENV = "DPRF_COVERAGE"
#: cap on gap/overlap intervals enumerated in reports and audits
MAX_GAPS_ENV = "DPRF_COVERAGE_MAX_GAPS"

#: lock-discipline declaration (`dprf check` locks analyzer): a
#: ledger belongs to one Dispatcher and inherits its serialization
#: (CoordinatorState.lock on the serve plane, single-threaded locally)
#: -- ``<extern>``, like the Dispatcher itself.  The worker-side note
#: state is module-global, touched from worker submit threads, and
#: guarded by its own module lock; note() must never call back into
#: coordinator-side locks while holding it.
GUARDED_BY = {
    "CoverageLedger": {"<extern>": ()},
    "<module>": {"_NOTE_LOCK": ("_NOTES", "_COLLECTOR")},
}


def coverage_enabled() -> bool:
    return envreg.get_bool(ENABLE_ENV)


def max_gaps() -> int:
    return max(1, envreg.get_int(MAX_GAPS_ENV, 64))


class IntervalSet:
    """Sorted, merged set of [start, end) integer intervals.

    The one interval implementation in the repo: the Dispatcher's
    completed set, the ledger's covered/accounted sets, and the
    offline auditor all use it.  ``add`` merges in O(log n + k) and
    returns the NEWLY covered length -- the overlap detector:
    ``(end - start) - add(start, end)`` indices were already covered.
    """

    def __init__(self, intervals=()):
        self._iv: list[list] = []
        for s, e in intervals:
            self.add(s, e)

    def add(self, start: int, end: int) -> int:
        if end <= start:
            return 0
        before = self._covered_within(start, end)
        iv = self._iv
        # binary search for insertion point by start
        lo, hi = 0, len(iv)
        while lo < hi:
            mid = (lo + hi) // 2
            if iv[mid][0] < start:
                lo = mid + 1
            else:
                hi = mid
        # merge with predecessor if touching
        i = lo
        if i > 0 and iv[i - 1][1] >= start:
            i -= 1
            iv[i][1] = max(iv[i][1], end)
        else:
            iv.insert(i, [start, end])
        # absorb successors
        j = i + 1
        while j < len(iv) and iv[j][0] <= iv[i][1]:
            iv[i][1] = max(iv[i][1], iv[j][1])
            j += 1
        del iv[i + 1:j]
        return (end - start) - before

    def _covered_within(self, start: int, end: int) -> int:
        """Indices of [start, end) already covered -- the pre-insert
        overlap measurement.  Binary search to the first interval that
        could intersect, then walk the (few) intersecting ones."""
        iv = self._iv
        lo, hi = 0, len(iv)
        while lo < hi:
            mid = (lo + hi) // 2
            if iv[mid][1] <= start:
                lo = mid + 1
            else:
                hi = mid
        covered = 0
        for s, e in iv[lo:]:
            if s >= end:
                break
            covered += min(e, end) - max(s, start)
        return covered

    def covered(self) -> int:
        return sum(e - s for s, e in self._iv)

    def contains_range(self, start: int, end: int) -> bool:
        for s, e in self._iv:
            if s <= start and end <= e:
                return True
        return False

    def gaps(self, upto: int) -> list[tuple]:
        """Uncovered ranges within [0, upto)."""
        out, prev = [], 0
        for s, e in self._iv:
            if s >= upto:
                break
            if s > prev:
                out.append((prev, min(s, upto)))
            prev = max(prev, e)
        if prev < upto:
            out.append((prev, upto))
        return out

    def intervals(self) -> list[tuple]:
        return [(s, e) for s, e in self._iv]


def coverage_digest(keyspace: int, intervals) -> str:
    """Order-independent digest of a coverage state: sha256 over the
    keyspace size and the CANONICAL merged [start, end) intervals --
    any insertion order (or pre-merged journal form) of the same
    covered set digests identically.  Same 16-hex shape as
    ``session.job_fingerprint``."""
    iv = IntervalSet(intervals)
    h = hashlib.sha256()
    h.update(f"{int(keyspace)}|".encode())
    h.update(",".join(f"{s}-{e}" for s, e in iv.intervals()).encode())
    return h.hexdigest()[:16]


class CoverageLedger:
    """Per-job live coverage accounting; see the module docstring.

    Every index of [0, keyspace) must at all times be in exactly one
    of: the covered set, a LIVE unit (split but not completed --
    pending, outstanding, or parked), or the unsplit tail above the
    split frontier.  ``complete`` moving a live range into the covered
    set is the only legal transfer; anything that breaks the partition
    surfaces as overlap (double-covered indices) or gap (lost
    indices).
    """

    def __init__(self, keyspace: int, job_id: str = "j0",
                 registry=None, enabled: Optional[bool] = None,
                 order=None):
        self.keyspace = int(keyspace)
        self.job_id = job_id
        #: rank<->index bijection of the owning dispatcher (or None =
        #: identity).  The ledger's interval arithmetic runs in the
        #: dispatcher's native space -- under an order that is RANK
        #: space, where exactly-once is the same invariant (a bijection
        #: preserves overlaps and gaps) -- and only digest()/
        #: covered_intervals() translate to the canonical index image
        #: the journal and `dprf audit` compare against.
        self.order = order
        self.enabled = (coverage_enabled() if enabled is None
                        else enabled)
        self._covered = IntervalSet()
        #: unit id -> (start, end) of every split-but-not-completed
        #: unit (pending, outstanding, or parked)
        self._live: dict[int, tuple] = {}
        self._live_len = 0
        #: split frontier: max end of any split unit or restored
        #: interval; [frontier, keyspace) is the unsplit tail
        self._frontier = 0
        self.overlap_total = 0
        self.abandoned = False
        #: event counts by declared name (includes worker-side names
        #: for schema completeness; those count in note(), not here)
        self.counts: dict[str, int] = {n: 0 for n in EVENT_NAMES}
        # the three coverage gauges -- this is their ONE declaration
        # site (analysis/metrics.py rule 1); the coverage_gap alert
        # rule (telemetry/alerts.py) reads the gap gauge
        from dprf_tpu.telemetry import get_registry
        m = get_registry(registry)
        self._g_fraction = m.gauge(
            "dprf_job_coverage_fraction",
            "fraction of the job's keyspace in the covered set",
            labelnames=("job",))
        self._g_overlap = m.gauge(
            "dprf_job_coverage_overlap_total",
            "keyspace indices covered MORE than once (a stale lease "
            "past the guard, a resume re-running finished work) -- "
            "wasted H/s, and evidence the exactly-once invariant "
            "broke", labelnames=("job",))
        self._g_gap = m.gauge(
            "dprf_job_coverage_gap_total",
            "keyspace indices in no population at all (not covered, "
            "not on a live unit, not unsplit) -- candidates LOST; "
            "the coverage_gap alert fires on nonzero",
            labelnames=("job",))
        if self.enabled:
            self._g_fraction.set(0.0 if self.keyspace else 1.0,
                                 job=job_id)
            self._g_overlap.set(0, job=job_id)
            self._g_gap.set(0, job=job_id)

    # -- the one event API ----------------------------------------------

    def event(self, name: str, start: int = 0, end: int = 0,
              unit: Optional[int] = None, **attrs) -> None:
        """Fold one range-mutating event into the ledger.  ``name``
        must be a declared member of EVENT_NAMES (the coverage-events
        analyzer enforces literal call sites; this guard catches
        dynamic ones)."""
        if name not in EVENT_NAMES:
            raise ValueError(f"undeclared coverage event: {name!r}")
        if not self.enabled:
            return
        self.counts[name] += 1
        if name == "split":
            if unit is not None:
                self._live[unit] = (start, end)
                self._live_len += end - start
            if end > self._frontier:
                self._frontier = end
            self._update_gauges()
        elif name == "restore":
            over = (end - start) - self._covered.add(start, end)
            if over:
                self.overlap_total += over
            if end > self._frontier:
                self._frontier = end
            self._update_gauges()
        elif name == "complete":
            rng = self._live.pop(unit, None)
            if rng is not None:
                self._live_len -= rng[1] - rng[0]
            over = (end - start) - self._covered.add(start, end)
            if over:
                self.overlap_total += over
            self._update_gauges()
        elif name == "abandon":
            self._live.clear()
            self._live_len = 0
            self.abandoned = True
            self._update_gauges()
        # lease/fail/reissue/park/unpark/resplit/force_complete move
        # no ranges between populations: count-only

    # -- verdicts --------------------------------------------------------

    def fraction(self) -> float:
        if self.keyspace <= 0:
            return 1.0
        return self._covered.covered() / self.keyspace

    def gaps(self) -> list[tuple]:
        """Lost ranges: keyspace indices neither covered, nor live on
        a split unit, nor above the split frontier.  Empty on every
        healthy ledger; an abandoned (cancelled) job's dropped units
        are intentional and not reported as loss."""
        if self.abandoned:
            return []
        acc = IntervalSet(self._covered.intervals())
        for s, e in self._live.values():
            acc.add(s, e)
        if self._frontier < self.keyspace:
            acc.add(self._frontier, self.keyspace)
        return acc.gaps(self.keyspace)[:max_gaps()]

    def gap_total(self) -> int:
        return sum(e - s for s, e in self.gaps())

    def digest(self) -> str:
        """Digest of the covered set over its canonical INDEX image;
        computed even when disabled (the resume rebuild check must not
        depend on a telemetry knob)."""
        return coverage_digest(self.keyspace, self.covered_intervals())

    def covered_intervals(self) -> list[tuple]:
        """Covered set in index space (the journal-comparable form);
        lazily translated -- the hot event path never pays for the
        bijection."""
        if self.order is not None:
            return self.order.index_image(self._covered.intervals())
        return self._covered.intervals()

    def live_units(self) -> dict:
        return dict(self._live)

    def summary(self) -> dict:
        """One-call state dump: the journal coverage record and the
        job-status payload."""
        return {"job": self.job_id,
                "keyspace": self.keyspace,
                "covered": self._covered.covered(),
                "fraction": round(self.fraction(), 6),
                "overlap": self.overlap_total,
                "gap": self.gap_total(),
                "live_units": len(self._live),
                "frontier": self._frontier,
                "abandoned": self.abandoned,
                "digest": self.digest(),
                "events": {n: c for n, c in self.counts.items() if c}}

    def _update_gauges(self) -> None:
        self._g_fraction.set(round(self.fraction(), 6),
                             job=self.job_id)
        self._g_overlap.set(self.overlap_total, job=self.job_id)
        self._g_gap.set(self.gap_total(), job=self.job_id)


# ---------------------------------------------------------------------------
# worker-side note API

#: module-global note state (GUARDED_BY <module> above): counters for
#: worker-side events, and an optional collector the chaos harness /
#: tests install to receive (name, start, end, attrs) per note
_NOTE_LOCK = threading.Lock()
_NOTES: dict = {n: 0 for n in NOTE_EVENTS}
_COLLECTOR = None


def note(name: str, start: int = 0, end: int = 0, **attrs) -> None:
    """Worker-side coverage event (redrive / rescan / superstep
    window).  Hot-path cheap by design: a guarded counter bump, plus
    the installed collector if any -- no RPC, no allocation beyond the
    attrs dict the caller already built."""
    if name not in EVENT_NAMES:
        raise ValueError(f"undeclared coverage event: {name!r}")
    if not coverage_enabled():
        return
    with _NOTE_LOCK:
        _NOTES[name] = _NOTES.get(name, 0) + 1
        cb = _COLLECTOR
    if cb is not None:
        # called OUTSIDE the note lock: a collector is arbitrary test
        # code and must not serialize worker submit threads
        cb(name, int(start), int(end), attrs)


def install_collector(cb) -> None:
    """Install a process-local collector receiving every note():
    ``cb(name, start, end, attrs)``.  Tests and the chaos harness use
    it to assert superstep windows / redrives tile each unit exactly
    once; pass None to uninstall."""
    global _COLLECTOR
    with _NOTE_LOCK:
        _COLLECTOR = cb


def notes() -> dict:
    """Snapshot of the worker-side note counters."""
    with _NOTE_LOCK:
        return dict(_NOTES)


def reset_notes() -> None:
    with _NOTE_LOCK:
        for k in list(_NOTES):
            _NOTES[k] = 0
